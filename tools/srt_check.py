#!/usr/bin/env python3
"""srt-check — repo-invariant static analyzer for the TPU runtime.

Eleven PRs of CONTRIBUTING prose turned into machine-checked passes:
the invariants below used to live in reviewers' heads and each of them
has been violated (or nearly) by a landed PR. This is the repo's
``compute-sanitizer``/``cuda-memcheck`` CI lane analog (see the README
parity table) — the static half; the dynamic half is the lock-order
detector in ``spark_rapids_jni_tpu/utils/lockcheck.py``.

Passes (each emits ``file:line:col`` findings):

* **SRT001 env-outside-config** — ``SPARK_RAPIDS_TPU_*`` environment
  reads anywhere but ``utils/config.py``. Every knob rides the flag
  plane (loud-fail parsers, generation-counter cache invalidation); a
  raw read is invisible to ``set_flag`` and silently un-parsed.
* **SRT002 broad-except** — ``except Exception``/``BaseException``
  handlers that swallow or reclassify without routing through the
  ``faults`` taxonomy and without a bare re-``raise``. Retrying an
  unclassified failure is how retry storms start (PR 10). Justified
  sites carry ``# srt: allow-broad-except(<reason>)``.
* **SRT003 hot-env-read** — any ``os.environ``/``os.getenv`` access
  inside a function body in the package. Module-level one-time reads
  are fine; per-call reads are the ~6 µs/op mistake the cached-gate
  pattern (``config.generation()``) exists to prevent.
* **SRT004 wallclock-in-replay** — ``time.time``/``datetime.now``/
  stdlib ``random`` in the determinism-critical modules (fault
  injection, compile-cache keys, plan fusion): seeded chaos replay and
  cache-key stability both break the moment a wall clock leaks in.
* **SRT005 retry-on-donated** — ``run_with_retry`` wrapping a call
  site that passes ``donate=True``: a donated segment consumed its
  input buffers, so a replay reads deleted memory. Retry is at-most-
  once for donated work (PR 5's doomed-replay rule).
* **SRT006 metric-name** — metric/flight event name literals that
  don't match the dotted-name convention (``^[a-z0-9_]+(\\.[a-z0-9_]+
  )*$``) or whose first segment isn't a registered namespace. One
  typo'd namespace splits a counter across two dashboard rows forever.
* **SRT007 bench-arm-tier** — every ``bench.py`` arm in
  ``_SUBPROCESS_CONFIGS`` must declare a tier (headline | extended |
  manual) in ``_ARM_TIERS``: un-tiered arms are how bench rounds
  r04/r05 silently blew the ``SRT_BENCH_BUDGET_S`` wall budget
  (rc=124, headline parsed=null).
* **SRT008 dispatch-parity** — the op registries of the dispatch
  plane (``runtime_bridge.DISPATCH_OPS``, the ``name == "..."`` arms
  of ``_dispatch_impl``, and ``plancheck._RULES``) must hold exactly
  the same op keys: an op added to the dispatcher without a plancheck
  inference rule would make the plan-time analyzer reject (or
  mis-infer) a runnable plan — the GpuOverrides-tag/exec drift bug
  class, caught statically. The exchange plane rides the same pass:
  every ``plan._EXCHANGE_OPS`` entry (the ops planmesh splits mesh
  plans at) must appear in all three registries above.
* **SRT009 host-sync** — implicit device->host synchronizations in the
  hot dispatch modules (``plan.py``, ``bucketed.py``): ``bool()``/
  ``int()``/``float()`` over device values (``.data``/``.validity``/
  ``.lengths`` attributes, locals bound from device-producing calls),
  ``.item()``, and ``np.asarray`` on non-constants. Each sync stalls
  the launch pipeline; deliberate ones (the exact path's row-count
  reads) carry ``# srt: allow-host-sync(<reason>)``.
* **SRT010 stats-append** — append-mode ``open()`` on the plan-stats
  store anywhere but ``planstats._open_append``: the store's crash
  tolerance rests on every writer emitting CRC-framed records through
  the one helper (truncate-to-good self-heal, rotation, flush
  discipline). A raw ``open(..., "a")`` on a stats path bypasses the
  framing, and a torn write there corrupts history for every later
  reader. Justified sites carry ``# srt: allow-stats-append(<reason>)``.
* **SRT011 trace-context** — trace-plane discipline, both halves: a
  string-literal span name handed to ``tracing.span_begin`` /
  ``trace_range`` must follow the same dotted-name grammar and
  registered-namespace rule as SRT006 (span names land on the flight
  ring and merge into dashboards next to metric names — one typo
  splits a request's spans across two rows); and serving modules must
  not hand-roll trace ids (``uuid``/``os.urandom``/``secrets`` flowing
  into a trace-named binding): ``tracing.new_context()`` is the one
  mint, which is what keeps ids W3C-shaped and the ambient context the
  single source of truth. Justified sites carry
  ``# srt: allow-trace-context(<reason>)``.
* **SRT012 kernel-parity** — the kernel-tier registries (the SRT008
  discipline applied to ``kernels/registry.py``): the ``KERNEL_NAMES``
  literal, the ``_REGISTRY`` dict keys, and plancheck's
  ``_KERNEL_RULES`` table must hold exactly the same kernel names, the
  ``kernel`` metric namespace must be registered here, and every
  ``_REGISTRY`` entry must be a well-formed ``KernelSpec(...)`` whose
  name argument matches its key. A kernel added to one registry
  without the others would launch untagged (no static eligibility,
  unattributed counters) or tag ops the runtime cannot accelerate.
* **SRT000 bad-pragma** — a suppression pragma with a missing reason
  or an unknown pass name is itself a finding: silent suppression
  grows back the prose problem this tool replaces.

Pragma grammar (the finding line or the line directly above)::

    # srt: allow-<pass-slug>(<non-empty reason>)

Baseline workflow: ``tools/srt_check_baseline.json`` holds
fingerprints of grandfathered findings. New findings FAIL (exit 1);
baselined ones report and burn down (a fixed finding leaves a stale
baseline entry, listed so it can be pruned with ``--write-baseline``).
Fingerprints hash (pass, path, enclosing scope, normalized source
line) — not line numbers — so unrelated edits don't churn the file.

Usage::

    python tools/srt_check.py                  # scan repo, gate on new
    python tools/srt_check.py --json           # machine-readable
    python tools/srt_check.py --write-baseline # re-grandfather all
    python tools/srt_check.py path.py ...      # scan specific files
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "srt_check_baseline.json"
)

# scan roots relative to the repo root (tests are exempt: test code
# legitimately monkeypatches environs and provokes broad failures)
DEFAULT_ROOTS = ("spark_rapids_jni_tpu", "tools", "bench.py")

ENV_PREFIX = "SPARK_RAPIDS_TPU_"
CONFIG_MODULE = os.path.join("spark_rapids_jni_tpu", "utils", "config.py")

# SRT004 scope: the modules where wall-clock / unseeded randomness
# breaks seeded replay or cache-key stability
DETERMINISM_MODULES = (
    os.path.join("spark_rapids_jni_tpu", "utils", "faults.py"),
    os.path.join("spark_rapids_jni_tpu", "utils", "buckets.py"),
    os.path.join("spark_rapids_jni_tpu", "plan.py"),
)

# SRT009 scope: the hot dispatch modules where an implicit host sync
# stalls the launch pipeline (each one blocks until the device drains)
HOT_SYNC_MODULES = (
    os.path.join("spark_rapids_jni_tpu", "plan.py"),
    os.path.join("spark_rapids_jni_tpu", "bucketed.py"),
    # the distributed tier: syncs here stall every device on the mesh,
    # so the deliberate ones (two-phase sizing, overflow verdicts,
    # result gathers) carry allow-host-sync pragmas and anything new
    # gets flagged
    os.path.join("spark_rapids_jni_tpu", "parallel", "mesh.py"),
    os.path.join("spark_rapids_jni_tpu", "parallel", "shuffle.py"),
    os.path.join("spark_rapids_jni_tpu", "parallel", "distributed.py"),
    os.path.join("spark_rapids_jni_tpu", "parallel", "planmesh.py"),
)

# attribute names that denote DEVICE buffers on a Column/Table — an
# int()/bool()/float() over an expression touching one is a sync
DEVICE_ATTRS = frozenset({"data", "validity", "lengths", "offsets"})

# attribute reads that are HOST scalars even on device-holding objects
# (Table/Column bookkeeping) — reading one is not a sync
HOST_ATTRS = frozenset({
    "row_count", "logical_row_count", "logical_rows", "names",
    "dtype", "scale", "id", "shape", "ndim", "size",
})

# call names whose result is a HOST value: assigning a local from one
# of these does NOT mark it device (everything else conservatively
# does — in the hot modules most call results are jax arrays)
HOST_CALLS = frozenset({
    "int", "float", "bool", "str", "len", "range", "enumerate", "zip",
    "list", "tuple", "dict", "set", "sorted", "min", "max", "sum",
    "abs", "get", "isinstance", "getattr", "hasattr", "repr", "format",
    "join", "split", "append", "pop", "keys", "values", "items",
    "perf_counter", "monotonic", "bucket_for", "enabled", "get_flag",
    "generation", "segment_plan", "op_fusable", "is_bucketable",
    "table_bytes", "dumps", "loads",
})

# the faults-taxonomy vocabulary whose presence in a broad handler
# counts as "routed through the taxonomy" (SRT002)
FAULTS_NAMES = frozenset({
    "faults", "classify", "classify_text", "run_with_retry",
    "FaultError", "TransientDeviceError", "PermanentError",
    "ResourceExhausted", "Cancelled", "DeadlineExceeded", "Degraded",
    "DependencyFailed",
    # taxonomy entry points: feeding a breaker / the error-class
    # counters IS routing the failure through the fault plane
    "note_failure", "note_success", "note_error_class",
})

# SRT006: registered metric/flight namespace roots. A NEW subsystem
# registers its namespace here (one line, reviewed) — that is what
# makes the dotted names "registered" instead of folklore.
METRIC_NAMESPACES = frozenset({
    "op", "wire", "resident", "dispatch", "plan", "bucket",
    "compile_cache", "pipeline", "hbm", "span", "span_ms", "serving",
    "session", "retry", "faults", "breaker", "fault", "spill", "lock",
    "shuffle", "distributed", "io", "probe", "bench", "groupby",
    "join", "sort", "profile", "stream", "checkpoint", "restore",
    "mesh", "planstats", "drift", "partition", "client", "compile",
    "kernel",
})
METRIC_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")

# metrics-registry entry points whose FIRST string arg is a metric
# name; flight.record's name is its SECOND arg
METRIC_FNS = frozenset({
    "counter_add", "bytes_add", "timer_record", "gauge_set",
    "hist_observe", "self_time_record", "span",
})

# SRT011: tracing entry points whose FIRST string arg is a span name
# (rides the SRT006 grammar: span names land on the flight ring next
# to metric names)
TRACE_SPAN_FNS = frozenset({"span_begin", "trace_range"})

# SRT011: calls that mint random identity. In serving modules a result
# of one of these flowing into a trace-named binding bypasses
# tracing.new_context(), the one sanctioned trace-id mint.
_MINT_CALLS = frozenset({
    "uuid1", "uuid4", "urandom", "token_hex", "token_bytes",
    "getrandbits",
})

BENCH_TIERS = frozenset({"headline", "extended", "manual"})

# pass -> pragma slug; a suppression comment is "srt:" then
# "allow-" + slug + "(reason)" (see the module docstring)
PASS_PRAGMAS = {
    "SRT001": "env-read",
    "SRT002": "broad-except",
    "SRT003": "hot-env",
    "SRT004": "wallclock",
    "SRT005": "retry-donated",
    "SRT006": "metric-name",
    "SRT007": "untiered-arm",
    "SRT008": "dispatch-parity",
    "SRT009": "host-sync",
    "SRT010": "stats-append",
    "SRT011": "trace-context",
    "SRT012": "kernel-parity",
}
PRAGMA_RE = re.compile(r"#\s*srt:\s*allow-([a-z0-9-]+)\(([^)]*)\)")
LOOSE_PRAGMA_RE = re.compile(r"#\s*srt:\s*allow-")
KNOWN_PRAGMAS = frozenset(PASS_PRAGMAS.values())


class Finding:
    __slots__ = ("pass_id", "path", "line", "col", "message",
                 "fingerprint", "baselined")

    def __init__(self, pass_id: str, path: str, line: int, col: int,
                 message: str):
        self.pass_id = pass_id
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.fingerprint = ""
        self.baselined = False

    def to_doc(self) -> dict:
        return {
            "pass": self.pass_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "baselined": self.baselined,
        }

    def render(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.pass_id} {self.message}{tag}"
        )


# ---------------------------------------------------------------------------
# pragma handling
# ---------------------------------------------------------------------------


class _Pragmas:
    """Suppression pragmas of one file: line -> (slug, reason).

    Scans REAL comment tokens (via ``tokenize``), not raw line text —
    a docstring or string literal that happens to quote the pragma
    grammar (this file's own docs, error messages) is not a pragma.
    """

    def __init__(self, source: str, relpath: str):
        self.by_line: Dict[int, Tuple[str, str]] = {}
        self.bad: List[Finding] = []
        self.used: set = set()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline
            ))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # scan_file already reports the syntax error
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            i, col = tok.start
            text = tok.string
            m = PRAGMA_RE.search(text)
            if not m:
                # a pragma-looking comment that doesn't parse (e.g. no
                # parens, a typo'd slug shape) is a silent no-op — flag
                if LOOSE_PRAGMA_RE.search(text):
                    self.bad.append(Finding(
                        "SRT000", relpath, i, col,
                        "malformed srt pragma: expected "
                        "'# srt: allow-<pass>(<reason>)'",
                    ))
                continue
            slug, reason = m.group(1), m.group(2).strip()
            if slug not in KNOWN_PRAGMAS:
                self.bad.append(Finding(
                    "SRT000", relpath, i, col,
                    f"unknown srt pragma 'allow-{slug}' (known: "
                    + ", ".join(
                        f"allow-{s}" for s in sorted(KNOWN_PRAGMAS)
                    ) + ")",
                ))
                continue
            if not reason:
                self.bad.append(Finding(
                    "SRT000", relpath, i, col,
                    f"srt pragma 'allow-{slug}' requires a non-empty "
                    "reason: the justification IS the point",
                ))
                continue
            self.by_line[i] = (slug, reason)

    def suppresses(self, pass_id: str, line: int) -> bool:
        slug = PASS_PRAGMAS[pass_id]
        for ln in (line, line - 1):
            got = self.by_line.get(ln)
            if got is not None and got[0] == slug:
                self.used.add(ln)
                return True
        return False


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def _is_environ(node: ast.AST) -> bool:
    """True for the expression ``os.environ``."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


def _env_read_key(node: ast.AST) -> Optional[Tuple[ast.AST, Optional[str]]]:
    """If ``node`` reads an environment variable, return (node, key or
    None-when-dynamic); else None. Writes (``os.environ[k] = v``) pass."""
    if isinstance(node, ast.Call):
        f = node.func
        # os.environ.get(...) / os.environ.setdefault(...)
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("get", "setdefault")
            and _is_environ(f.value)
        ) or (
            # os.getenv(...)
            isinstance(f, ast.Attribute)
            and f.attr == "getenv"
            and isinstance(f.value, ast.Name)
            and f.value.id == "os"
        ):
            key = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                key = node.args[0].value
            return node, key
    if isinstance(node, ast.Subscript) and _is_environ(node.value):
        if isinstance(node.ctx, ast.Load):
            key = None
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                key = sl.value
            return node, key
    if isinstance(node, ast.Compare) and any(
        isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
    ):
        for cand in node.comparators:
            if _is_environ(cand):
                key = None
                if isinstance(node.left, ast.Constant) and isinstance(
                    node.left.value, str
                ):
                    key = node.left.value
                return node, key
    return None


def _call_name(node: ast.Call) -> str:
    """Trailing name of the called function (``a.b.c()`` -> ``c``)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _names_in(tree: ast.AST):
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr
            if isinstance(sub.value, ast.Name):
                yield sub.value.id


def _mints_id(node: ast.AST) -> bool:
    """True when the subtree calls a random-identity mint
    (``uuid.uuid4()``, ``os.urandom()``, ``secrets.token_hex()``...)."""
    return any(
        isinstance(sub, ast.Call) and _call_name(sub) in _MINT_CALLS
        for sub in ast.walk(node)
    )


def _trace_named(node: ast.AST) -> bool:
    """True when a binding target / dict key names trace identity
    (``trace_id = ...``, ``header["traceparent"] = ...``)."""
    if isinstance(node, ast.Name):
        return "trace" in node.id
    if isinstance(node, ast.Attribute):
        return "trace" in node.attr
    if isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return "trace" in sl.value
        return _trace_named(node.value)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "trace" in node.value
    return False


# ---------------------------------------------------------------------------
# per-file analysis
# ---------------------------------------------------------------------------


class _FileChecker(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str, pragmas: _Pragmas):
        self.relpath = relpath
        self.pragmas = pragmas
        self.findings: List[Finding] = []
        self.scope: List[str] = []
        self.func_depth = 0
        norm = relpath.replace("/", os.sep)
        self.in_package = norm.startswith("spark_rapids_jni_tpu" + os.sep)
        self.is_config = norm == CONFIG_MODULE
        self.determinism = norm in DETERMINISM_MODULES
        self.hot_sync = norm in HOT_SYNC_MODULES
        # SRT011 mint-check scope: the serving tier (tracing.py itself
        # owns the os.urandom mint and lives in utils/)
        self.in_serving = norm.startswith(
            os.path.join("spark_rapids_jni_tpu", "serving") + os.sep
        )
        # SRT009: per-function sets of local names bound from
        # device-producing calls (conservative: any call not in
        # HOST_CALLS and not itself flagged as a sync)
        self._device_locals: List[set] = []

    # -- bookkeeping ------------------------------------------------------
    def _emit(self, pass_id: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.pragmas.suppresses(pass_id, line):
            return
        self.findings.append(
            Finding(pass_id, self.relpath, line, col, message)
        )

    def _scoped(self, name: str, node, is_func: bool):
        self.scope.append(name)
        if is_func:
            self.func_depth += 1
        self.generic_visit(node)
        if is_func:
            self.func_depth -= 1
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self._device_locals.append(set())
        self._scoped(node.name, node, True)
        self._device_locals.pop()

    def visit_AsyncFunctionDef(self, node):
        self._device_locals.append(set())
        self._scoped(node.name, node, True)
        self._device_locals.pop()

    def visit_Lambda(self, node):
        self.func_depth += 1
        self.generic_visit(node)
        self.func_depth -= 1

    def visit_ClassDef(self, node):
        self._scoped(node.name, node, False)

    # -- SRT001 / SRT003: env reads ---------------------------------------
    def _check_env(self, node) -> None:
        got = _env_read_key(node)
        if got is None:
            return
        _, key = got
        if key is not None and key.startswith(ENV_PREFIX) \
                and not self.is_config:
            self._emit(
                "SRT001", node,
                f"{key} read outside utils/config.py — declare a Flag "
                "and use config.get_flag (loud-fail parse + generation-"
                "cached gates)",
            )
            return  # one finding per site; SRT003 would double-report
        if self.in_package and not self.is_config and self.func_depth > 0:
            self._emit(
                "SRT003", node,
                "environ read inside a function body — per-call env "
                "reads cost ~6us each; cache on config.generation() "
                "(the metrics-gate pattern) or read once at module "
                "scope",
            )

    def visit_Subscript(self, node):
        self._check_env(node)
        self.generic_visit(node)

    def visit_Compare(self, node):
        self._check_env(node)
        self.generic_visit(node)

    # -- SRT002: broad excepts --------------------------------------------
    def _broad_types(self, node: ast.ExceptHandler) -> List[str]:
        out = []
        t = node.type
        cands = t.elts if isinstance(t, ast.Tuple) else [t]
        for c in cands:
            if isinstance(c, ast.Name) and c.id in (
                "Exception", "BaseException"
            ):
                out.append(c.id)
        return out

    def visit_ExceptHandler(self, node):
        # SRT002 applies to the runtime package, where the faults
        # taxonomy lives; bench.py / tools are offline drivers whose
        # broad excepts are best-effort harness resilience by design
        broad = (
            self._broad_types(node)
            if node.type is not None and self.in_package else []
        )
        if broad:
            body_names = set()
            reraises = False
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Raise) and sub.exc is None:
                        reraises = True
                body_names.update(
                    n for stmt2 in [stmt] for n in _names_in(stmt2)
                )
            if not reraises and not (body_names & FAULTS_NAMES):
                self._emit(
                    "SRT002", node,
                    f"broad 'except {'/'.join(broad)}' neither "
                    "re-raises nor routes through the faults taxonomy "
                    "(classify / typed FaultError) — add "
                    "'# srt: allow-broad-except(<reason>)' if the "
                    "swallow is deliberate",
                )
        self.generic_visit(node)

    # -- SRT009: implicit host syncs in the hot dispatch modules ----------
    def _is_device_expr(self, expr: ast.AST) -> bool:
        """Could ``expr`` hold a device value? Attribute reads of device
        buffers, locals bound from device-producing calls, and direct
        jnp/jax calls count; host-scalar attribute reads (row counts,
        dtypes) and HOST_CALLS results don't."""
        locals_ = self._device_locals[-1] if self._device_locals else set()

        def dev(n: ast.AST) -> bool:
            if isinstance(n, ast.Attribute):
                if n.attr in DEVICE_ATTRS:
                    return True
                if n.attr in HOST_ATTRS:
                    return False  # host bookkeeping on a device object
                return dev(n.value)
            if isinstance(n, ast.Name):
                return n.id in locals_
            if isinstance(n, ast.Call):
                root = n.func
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in (
                    "jnp", "jax", "lax"
                ):
                    return True
                if _call_name(n) in HOST_CALLS:
                    return False  # host-valued helper
                return any(dev(a) for a in n.args)
            return any(dev(c) for c in ast.iter_child_nodes(n))

        return dev(expr)

    def _classify_assign(self, node: ast.Assign) -> None:
        if not (self.hot_sync and self._device_locals):
            return
        v = node.value
        is_device = False
        if isinstance(v, ast.Call):
            root = v.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in (
                "jnp", "jax", "lax"
            ):
                # jnp.sum/jnp.max/... produce device arrays even though
                # the bare names shadow HOST_CALLS entries
                is_device = True
            else:
                is_device = _call_name(v) not in HOST_CALLS
        elif isinstance(v, (ast.Name, ast.Attribute, ast.Subscript,
                            ast.IfExp, ast.BinOp)):
            is_device = self._is_device_expr(v)
        targets: List[str] = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, ast.Tuple):
                targets.extend(
                    e.id for e in t.elts if isinstance(e, ast.Name)
                )
        locals_ = self._device_locals[-1]
        for name in targets:
            if is_device:
                locals_.add(name)
            else:
                locals_.discard(name)

    def visit_Assign(self, node):
        self._classify_assign(node)
        if self.in_serving and any(
            _trace_named(t) for t in node.targets
        ) and _mints_id(node.value):
            self._emit(
                "SRT011", node,
                "hand-rolled trace id in a serving module — "
                "tracing.new_context() / tracing.ensure_context() is "
                "the one mint (W3C-shaped ids, ambient context as the "
                "single source of truth)",
            )
        self.generic_visit(node)

    def visit_Dict(self, node):
        if self.in_serving:
            for k, v in zip(node.keys, node.values):
                if k is not None and _trace_named(k) and _mints_id(v):
                    self._emit(
                        "SRT011", v,
                        "hand-rolled trace id under a trace-named key "
                        "in a serving module — mint through "
                        "tracing.new_context() / ensure_context()",
                    )
        self.generic_visit(node)

    def _check_host_sync(self, node: ast.Call, name: str) -> None:
        if not self.hot_sync or self.func_depth == 0:
            return
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args:
            self._emit(
                "SRT009", node,
                ".item() is an implicit device->host sync (blocks until "
                "the device drains) — keep the value on device or mark "
                "a deliberate sync with '# srt: allow-host-sync(<why>)'",
            )
            return
        if (
            name == "asarray"
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "np"
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            self._emit(
                "SRT009", node,
                "np.asarray on a (potentially device) value is an "
                "implicit transfer+sync in a hot dispatch module — use "
                "jnp ops, or mark with '# srt: allow-host-sync(<why>)'",
            )
            return
        if (
            isinstance(f, ast.Name)
            and f.id in ("bool", "int", "float")
            and node.args
            and self._is_device_expr(node.args[0])
        ):
            self._emit(
                "SRT009", node,
                f"{f.id}() over a device value is an implicit "
                "device->host sync (stalls the launch pipeline) — "
                "deliberate syncs carry "
                "'# srt: allow-host-sync(<why>)'",
            )

    # -- SRT004/005/006: calls --------------------------------------------
    def visit_Call(self, node):
        self._check_env(node)
        name = _call_name(node)
        self._check_host_sync(node, name)

        if self.determinism:
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ):
                mod, attr = f.value.id, f.attr
                if (mod == "time" and attr in ("time", "time_ns")) or (
                    mod == "random"
                ) or (
                    mod in ("datetime", "date") and attr in (
                        "now", "utcnow", "today"
                    )
                ):
                    self._emit(
                        "SRT004", node,
                        f"{mod}.{attr}() in a determinism-critical "
                        "module (cache keys / fault-injection "
                        "decisions): wall clocks and unseeded "
                        "randomness break seeded chaos replay — hash "
                        "the (seed, site, index) tuple or use "
                        "time.monotonic/perf_counter for intervals",
                    )

        if name == "run_with_retry":
            for sub in ast.walk(node):
                if isinstance(sub, ast.keyword) and sub.arg in (
                    "donate", "donate_input", "donate_args"
                ):
                    v = sub.value
                    if not (
                        isinstance(v, ast.Constant)
                        and v.value in (False, None)
                    ):
                        self._emit(
                            "SRT005", node,
                            "run_with_retry wraps a donated call site "
                            f"({sub.arg}=...): donated segments consume "
                            "their input buffers, so a replay reads "
                            "deleted memory — retry must stay at-most-"
                            "once (gate on the consumed-input check "
                            "BEFORE the retry loop)",
                        )
                        break

        metric_arg = None
        if name in METRIC_FNS and node.args:
            metric_arg = node.args[0]
        elif name == "record" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "flight" and len(node.args) >= 2:
            metric_arg = node.args[1]
        if (
            metric_arg is not None
            and isinstance(metric_arg, ast.Constant)
            and isinstance(metric_arg.value, str)
        ):
            mname = metric_arg.value
            if not METRIC_NAME_RE.match(mname):
                self._emit(
                    "SRT006", node,
                    f"metric/flight name {mname!r} is not "
                    "dotted-lowercase ([a-z0-9_] segments joined "
                    "by '.')",
                )
            elif mname.split(".", 1)[0] not in METRIC_NAMESPACES:
                self._emit(
                    "SRT006", node,
                    f"metric/flight name {mname!r} uses unregistered "
                    f"namespace {mname.split('.', 1)[0]!r} — register "
                    "it in tools/srt_check.py METRIC_NAMESPACES (one "
                    "reviewed line) or reuse an existing namespace",
                )

        if name in TRACE_SPAN_FNS and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                sname = a.value
                if not METRIC_NAME_RE.match(sname):
                    self._emit(
                        "SRT011", node,
                        f"span name {sname!r} is not dotted-lowercase "
                        "([a-z0-9_] segments joined by '.') — span "
                        "names land on the flight ring next to metric "
                        "names and follow the same grammar",
                    )
                elif sname.split(".", 1)[0] not in METRIC_NAMESPACES:
                    self._emit(
                        "SRT011", node,
                        f"span name {sname!r} uses unregistered "
                        f"namespace {sname.split('.', 1)[0]!r} — "
                        "register it in tools/srt_check.py "
                        "METRIC_NAMESPACES (one reviewed line) or "
                        "reuse an existing namespace",
                    )
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# SRT007: bench arm tier table
# ---------------------------------------------------------------------------


def _dict_str_keys(node: ast.Dict) -> List[Tuple[str, ast.AST]]:
    out = []
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.append((k.value, v))
    return out


def check_bench_tiers(relpath: str, tree: ast.Module,
                      pragmas: _Pragmas) -> List[Finding]:
    configs: Optional[ast.Dict] = None
    tiers: Optional[ast.Dict] = None
    configs_line = 1
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            if tgt == "_SUBPROCESS_CONFIGS" and isinstance(
                node.value, ast.Dict
            ):
                configs = node.value
                configs_line = node.lineno
            elif tgt == "_ARM_TIERS" and isinstance(node.value, ast.Dict):
                tiers = node.value
    if configs is None:
        return []  # not a bench module
    findings: List[Finding] = []

    def emit(pass_id, node, msg):
        line = getattr(node, "lineno", configs_line)
        if not pragmas.suppresses(pass_id, line):
            findings.append(Finding(
                pass_id, relpath, line,
                getattr(node, "col_offset", 0), msg,
            ))

    if tiers is None:
        emit(
            "SRT007", configs,
            "_SUBPROCESS_CONFIGS has no _ARM_TIERS table: every arm "
            "must declare headline|extended|manual so the ladder walk "
            "can budget (r04/r05 rc=124 postmortem)",
        )
        return findings
    arm_names = {k for k, _ in _dict_str_keys(configs)}
    tier_entries = _dict_str_keys(tiers)
    tier_names = set()
    for arm, v in tier_entries:
        tier_names.add(arm)
        tier = v.value if isinstance(v, ast.Constant) else None
        if tier not in BENCH_TIERS:
            emit(
                "SRT007", v,
                f"arm {arm!r} declares invalid tier {tier!r} "
                f"(must be one of {sorted(BENCH_TIERS)})",
            )
        if arm not in arm_names:
            emit(
                "SRT007", v,
                f"_ARM_TIERS names unknown arm {arm!r} (not in "
                "_SUBPROCESS_CONFIGS) — stale entry?",
            )
    for k, v in _dict_str_keys(configs):
        if k not in tier_names:
            emit(
                "SRT007", v,
                f"bench arm {k!r} missing from _ARM_TIERS: un-tiered "
                "arms silently eat the SRT_BENCH_BUDGET_S wall budget "
                "— declare headline|extended|manual",
            )
    return findings


# ---------------------------------------------------------------------------
# SRT008: dispatch-plane / plancheck registry parity
# ---------------------------------------------------------------------------


def _str_set_literal(node: ast.AST) -> Optional[set]:
    """``{'a', 'b'}`` / ``frozenset({'a', 'b'})`` / list / tuple of str
    constants -> the set of strings; None when not a pure literal."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "frozenset" and len(node.args) == 1 \
            and not node.keywords:
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
            else:
                return None
        return out
    return None


def check_dispatch_parity(relpath: str, tree: ast.Module,
                          pragmas: _Pragmas,
                          src_dir: str) -> List[Finding]:
    """Runs when the scanned module IS the dispatch plane (it defines
    both ``DISPATCH_OPS`` and ``_dispatch_impl``): the three op
    registries — the DISPATCH_OPS literal, the ``name == "..."`` arms
    inside _dispatch_impl, and the sibling ``plancheck.py``'s _RULES
    table — must hold exactly the same keys. Adding an op to one
    without the others fails CI here, before the analyzer can reject
    (or mis-tag) a runnable plan."""
    ops_assign: Optional[ast.Assign] = None
    declared: Optional[set] = None
    impl: Optional[ast.FunctionDef] = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "DISPATCH_OPS":
            ops_assign = node
            declared = _str_set_literal(node.value)
        elif isinstance(node, ast.FunctionDef) \
                and node.name == "_dispatch_impl":
            impl = node
    if ops_assign is None or impl is None:
        return []  # not the dispatch-plane module
    findings: List[Finding] = []

    def emit(node, msg):
        line = getattr(node, "lineno", 1)
        if not pragmas.suppresses("SRT008", line):
            findings.append(Finding(
                "SRT008", relpath, line,
                getattr(node, "col_offset", 0), msg,
            ))

    if declared is None:
        emit(
            ops_assign,
            "DISPATCH_OPS must be a pure string-literal frozenset — "
            "the registry-parity pass reads it statically",
        )
        return findings

    # the dispatch arms: `if name == "<op>":` comparisons in the chain
    arms: set = set()
    for sub in ast.walk(impl):
        if (
            isinstance(sub, ast.Compare)
            and isinstance(sub.left, ast.Name)
            and sub.left.id == "name"
            and len(sub.ops) == 1
            and isinstance(sub.ops[0], ast.Eq)
            and isinstance(sub.comparators[0], ast.Constant)
            and isinstance(sub.comparators[0].value, str)
        ):
            arms.add(sub.comparators[0].value)

    for op in sorted(arms - declared):
        emit(ops_assign,
             f"dispatch arm {op!r} missing from DISPATCH_OPS")
    for op in sorted(declared - arms):
        emit(ops_assign,
             f"DISPATCH_OPS entry {op!r} has no `name == ...` arm in "
             "_dispatch_impl — stale entry?")

    # the analyzer side: plancheck._RULES in the sibling module
    pc_path = os.path.join(src_dir, "plancheck.py")
    if not os.path.exists(pc_path):
        emit(
            ops_assign,
            "no sibling plancheck.py next to the dispatch plane — "
            "every dispatch op needs a plan-time inference rule",
        )
        return findings
    try:
        with open(pc_path, "r", encoding="utf-8") as f:
            pc_tree = ast.parse(f.read(), filename=pc_path)
    except SyntaxError:
        return findings  # plancheck.py's own scan reports the error
    rules: Optional[set] = None
    rules_line = 1
    for node in pc_tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "_RULES" \
                and isinstance(node.value, ast.Dict):
            rules_line = node.lineno
            rules = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    rules.add(k.value)
    if rules is None:
        emit(
            ops_assign,
            "plancheck.py has no literal _RULES table — the parity "
            "pass (and the analyzer) need one rule per dispatch op",
        )
        return findings
    for op in sorted(declared - rules):
        emit(
            ops_assign,
            f"dispatch op {op!r} has no plancheck inference rule "
            f"(plancheck.py _RULES, line {rules_line}) — teach the "
            "analyzer before (or with) the dispatcher",
        )
    for op in sorted(rules - declared):
        emit(
            ops_assign,
            f"plancheck rule {op!r} has no dispatch arm — the analyzer "
            "would tag an op the runtime cannot execute",
        )

    # the exchange plane (4th registry): plan.py's _EXCHANGE_OPS names
    # the ops planmesh treats as mesh segment boundaries; each must be
    # a full dispatch citizen (DISPATCH_OPS + arm + plancheck rule), or
    # the mesh path would split plans at an op the exact path cannot
    # run and the analyzer cannot tag
    plan_path = os.path.join(src_dir, "plan.py")
    if os.path.exists(plan_path):
        try:
            with open(plan_path, "r", encoding="utf-8") as f:
                plan_tree = ast.parse(f.read(), filename=plan_path)
        except SyntaxError:
            return findings  # plan.py's own scan reports the error
        exchange: Optional[set] = None
        exch_line = 1
        for node in plan_tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "_EXCHANGE_OPS":
                exch_line = node.lineno
                exchange = _str_set_literal(node.value)
        if exchange is None:
            emit(
                ops_assign,
                "plan.py has no literal _EXCHANGE_OPS frozenset — the "
                "exchange-plane side of the registry-parity pass reads "
                "it statically",
            )
            return findings
        for op in sorted(exchange - declared):
            emit(
                ops_assign,
                f"exchange op {op!r} (plan.py _EXCHANGE_OPS, line "
                f"{exch_line}) is not in DISPATCH_OPS — the mesh path "
                "would split plans at an op the exact path cannot run",
            )
        for op in sorted(exchange - arms):
            emit(
                ops_assign,
                f"exchange op {op!r} (plan.py _EXCHANGE_OPS, line "
                f"{exch_line}) has no `name == ...` arm in "
                "_dispatch_impl — no exact fallback for the boundary",
            )
        for op in sorted(exchange - rules):
            emit(
                ops_assign,
                f"exchange op {op!r} (plan.py _EXCHANGE_OPS, line "
                f"{exch_line}) has no plancheck inference rule "
                f"(plancheck.py _RULES, line {rules_line})",
            )
    return findings


def check_kernel_parity(relpath: str, tree: ast.Module,
                        pragmas: _Pragmas,
                        src_dir: str) -> List[Finding]:
    """Runs when the scanned module IS the kernel registry (it defines
    both ``KERNEL_NAMES`` and ``_REGISTRY``): the kernel-tier parity
    pass, mirroring SRT008 for the kernel plane. The KERNEL_NAMES
    literal, the _REGISTRY dict keys, and the sibling plancheck.py's
    _KERNEL_RULES table must hold exactly the same names; every
    _REGISTRY entry must be a ``KernelSpec(...)`` whose name argument
    matches its key; and the ``kernel`` metric namespace must be
    registered so the tier's counters/spans pass SRT006."""
    names_assign: Optional[ast.Assign] = None
    declared: Optional[set] = None
    reg_assign: Optional[ast.Assign] = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if node.targets[0].id == "KERNEL_NAMES":
                names_assign = node
                declared = _str_set_literal(node.value)
            elif node.targets[0].id == "_REGISTRY":
                reg_assign = node
    if names_assign is None or reg_assign is None:
        return []  # not the kernel-registry module
    findings: List[Finding] = []

    def emit(node, msg):
        line = getattr(node, "lineno", 1)
        if not pragmas.suppresses("SRT012", line):
            findings.append(Finding(
                "SRT012", relpath, line,
                getattr(node, "col_offset", 0), msg,
            ))

    if declared is None:
        emit(
            names_assign,
            "KERNEL_NAMES must be a pure string-literal frozenset — "
            "the kernel-parity pass reads it statically",
        )
        return findings
    if not isinstance(reg_assign.value, ast.Dict):
        emit(
            reg_assign,
            "_REGISTRY must be a literal dict keyed by kernel-name "
            "strings — the kernel-parity pass reads it statically",
        )
        return findings

    registered: set = set()
    for k, v in zip(reg_assign.value.keys, reg_assign.value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            emit(k or reg_assign,
                 "_REGISTRY keys must be kernel-name string literals")
            continue
        registered.add(k.value)
        # malformed-entry check: a KernelSpec(...) whose first/name
        # argument is the key itself
        spec_name = None
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                and v.func.id == "KernelSpec":
            if v.args and isinstance(v.args[0], ast.Constant):
                spec_name = v.args[0].value
            for kw in v.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    spec_name = kw.value.value
        else:
            emit(v, f"_REGISTRY[{k.value!r}] is not a KernelSpec(...) "
                    "literal")
            continue
        if spec_name != k.value:
            emit(v, f"_REGISTRY[{k.value!r}] names its KernelSpec "
                    f"{spec_name!r} — key and spec name must match")

    for kn in sorted(registered - declared):
        emit(names_assign,
             f"_REGISTRY entry {kn!r} missing from KERNEL_NAMES")
    for kn in sorted(declared - registered):
        emit(names_assign,
             f"KERNEL_NAMES entry {kn!r} has no _REGISTRY spec — "
             "orphan name?")

    # the metric namespace the tier's counters/spans live under
    if "kernel" not in METRIC_NAMESPACES:
        emit(
            names_assign,
            "the 'kernel' metric namespace is not registered in "
            "tools/srt_check.py METRIC_NAMESPACES — kernel.launches/"
            "declines/fallbacks would fail SRT006",
        )

    # the analyzer side: plancheck._KERNEL_RULES one directory up
    pc_path = os.path.join(os.path.dirname(src_dir), "plancheck.py")
    if not os.path.exists(pc_path):
        emit(
            names_assign,
            "no plancheck.py above the kernel registry — every kernel "
            "needs a static eligibility rule (_KERNEL_RULES)",
        )
        return findings
    try:
        with open(pc_path, "r", encoding="utf-8") as f:
            pc_tree = ast.parse(f.read(), filename=pc_path)
    except SyntaxError:
        return findings  # plancheck.py's own scan reports the error
    rules: Optional[set] = None
    rules_line = 1
    for node in pc_tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "_KERNEL_RULES" \
                and isinstance(node.value, ast.Dict):
            rules_line = node.lineno
            rules = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    rules.add(k.value)
    if rules is None:
        emit(
            names_assign,
            "plancheck.py has no literal _KERNEL_RULES table — the "
            "kernel-parity pass (and the static kernel tag) need one "
            "rule per registered kernel",
        )
        return findings
    for kn in sorted(declared - rules):
        emit(
            names_assign,
            f"kernel {kn!r} has no plancheck eligibility rule "
            f"(plancheck.py _KERNEL_RULES, line {rules_line}) — the "
            "static report would never tag its ops",
        )
    for kn in sorted(rules - declared):
        emit(
            names_assign,
            f"plancheck kernel rule {kn!r} has no registry spec — the "
            "analyzer would tag ops no kernel accelerates",
        )
    return findings


# ---------------------------------------------------------------------------
# SRT010: plan-stats store writes go through the CRC-framed helper
# ---------------------------------------------------------------------------

# the one sanctioned raw-append site (crc framing + self-heal live there)
STATS_APPEND_HELPER = "_open_append"
_STATS_PATH_HINTS = ("planstats", "stats_dir", "stats_path")


def _open_mode_literal(call: ast.Call) -> Optional[str]:
    """The string mode of an ``open()`` call, or None when dynamic."""
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _mentions_stats_path(call: ast.Call) -> bool:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ) and "planstats" in node.value:
                return True
            if isinstance(node, ast.Name) and any(
                h in node.id for h in _STATS_PATH_HINTS
            ):
                return True
            if isinstance(node, ast.Attribute) and any(
                h in node.attr for h in _STATS_PATH_HINTS
            ):
                return True
    return False


def check_stats_append(relpath: str, tree: ast.Module,
                       pragmas: _Pragmas) -> List[Finding]:
    """Append-mode ``open()`` on the stats store outside the framed
    helper. Inside ``utils/planstats.py`` every append-mode open must
    live in ``_open_append``; elsewhere, an append-mode open whose
    arguments reference a stats path is a bypass of the framing."""
    in_planstats = relpath.replace(os.sep, "/").endswith(
        "spark_rapids_jni_tpu/utils/planstats.py"
    )
    findings: List[Finding] = []

    class _V(ast.NodeVisitor):
        def __init__(self):
            self.fn_stack: List[str] = []

        def visit_FunctionDef(self, node):
            self.fn_stack.append(node.name)
            self.generic_visit(node)
            self.fn_stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _open_mode_literal(node)
                if mode is not None and "a" in mode:
                    if in_planstats:
                        if STATS_APPEND_HELPER not in self.fn_stack:
                            self._emit(
                                node,
                                "append-mode open() in planstats "
                                "outside _open_append — every store "
                                "write must go through the CRC-framed "
                                "helper (torn-tail self-heal, "
                                "rotation, flush discipline)",
                            )
                    elif _mentions_stats_path(node):
                        self._emit(
                            node,
                            "raw append-mode open() on a plan-stats "
                            "path — append via planstats' framed "
                            "writer instead; unframed bytes corrupt "
                            "the store for every later reader",
                        )
            self.generic_visit(node)

        def _emit(self, node, msg):
            if not pragmas.suppresses("SRT010", node.lineno):
                findings.append(Finding(
                    "SRT010", relpath, node.lineno,
                    node.col_offset, msg,
                ))

    _V().visit(tree)
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def scan_file(path: str, repo_root: str = REPO_ROOT) -> List[Finding]:
    relpath = os.path.relpath(os.path.abspath(path), repo_root)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            "SRT000", relpath, e.lineno or 1, e.offset or 0,
            f"syntax error: {e.msg}",
        )]
    lines = source.splitlines()
    pragmas = _Pragmas(source, relpath)
    checker = _FileChecker(relpath, source, pragmas)
    checker.visit(tree)
    findings = checker.findings
    findings.extend(check_bench_tiers(relpath, tree, pragmas))
    findings.extend(check_stats_append(relpath, tree, pragmas))
    findings.extend(check_dispatch_parity(
        relpath, tree, pragmas,
        os.path.dirname(os.path.abspath(path)),
    ))
    findings.extend(check_kernel_parity(
        relpath, tree, pragmas,
        os.path.dirname(os.path.abspath(path)),
    ))
    findings.extend(pragmas.bad)
    # fingerprints: (pass, path, scope-less normalized line, occurrence)
    seen: Dict[str, int] = {}
    for fd in findings:
        text = lines[fd.line - 1].strip() if fd.line - 1 < len(lines) else ""
        base = f"{fd.pass_id}|{fd.path}|{text}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        fd.fingerprint = hashlib.sha1(
            f"{base}|{n}".encode()
        ).hexdigest()[:16]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.pass_id))
    return findings


def iter_sources(roots: Sequence[str], repo_root: str = REPO_ROOT):
    for root in roots:
        full = os.path.join(repo_root, root)
        if os.path.isfile(full):
            yield full
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", ".git")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def scan_repo(roots: Sequence[str] = DEFAULT_ROOTS,
              repo_root: str = REPO_ROOT) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_sources(roots, repo_root):
        findings.extend(scan_file(path, repo_root))
    return findings


def load_baseline(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "fingerprints" not in doc:
        raise ValueError(
            f"baseline {path!r} is not a srt-check baseline "
            "(missing 'fingerprints')"
        )
    return dict(doc["fingerprints"])


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {
        "version": 1,
        "tool": "srt-check",
        "note": (
            "grandfathered findings: new violations fail CI while "
            "these burn down. Regenerate with --write-baseline; an "
            "EMPTY table is the goal state."
        ),
        "fingerprints": {
            f.fingerprint: {
                "pass": f.pass_id,
                "path": f.path,
                "line": f.line,
                "message": f.message,
            }
            for f in findings
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def prune_baseline(path: str, live_fps) -> int:
    """Drop baseline fingerprints that no longer match any finding;
    returns how many were removed. The doc is rewritten in place with
    everything else (version, note) preserved."""
    if not os.path.exists(path):
        return 0
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    fps = doc.get("fingerprints", {})
    stale = [fp for fp in fps if fp not in live_fps]
    if not stale:
        return 0
    for fp in stale:
        del fps[fp]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(stale)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-check", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to scan "
                    "(default: the repo's standard roots)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding fails")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-grandfather every current finding and exit")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop stale fingerprints from the baseline in "
                    "place (keeps grandfathered entries that still "
                    "match) and continue the normal gate")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root for relative paths")
    args = ap.parse_args(argv)

    if args.paths:
        findings: List[Finding] = []
        for p in args.paths:
            full = p if os.path.isabs(p) else os.path.join(args.root, p)
            findings.extend(scan_repo([os.path.relpath(full, args.root)],
                                      args.root)
                            if os.path.isdir(full)
                            else scan_file(full, args.root))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.pass_id))
    else:
        findings = scan_repo(repo_root=args.root)

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"srt-check: baseline written to {args.baseline} "
            f"({len(findings)} findings grandfathered)"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new = 0
    for f in findings:
        if f.fingerprint in baseline:
            f.baselined = True
        else:
            new += 1
    live_fps = {f.fingerprint for f in findings}
    stale = [fp for fp in baseline if fp not in live_fps]
    if args.prune_baseline and stale:
        removed = prune_baseline(args.baseline, live_fps)
        print(
            f"srt-check: pruned {removed} stale baseline entr(y/ies) "
            f"from {args.baseline}"
        )
        stale = []

    files_scanned = len({f.path for f in findings}) if findings else 0
    summary = (
        f"srt-check: {len(findings)} finding(s) ({new} new, "
        f"{len(findings) - new} baselined, {len(stale)} stale baseline "
        "entr(y/ies))"
    )
    if args.json:
        print(json.dumps({
            "version": 1,
            "findings": [f.to_doc() for f in findings],
            "counts": {
                "total": len(findings),
                "new": new,
                "baselined": len(findings) - new,
                "stale_baseline": len(stale),
                "files_with_findings": files_scanned,
            },
            "stale_baseline": stale,
            "summary": summary,
        }, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if stale:
            print(
                f"srt-check: {len(stale)} baseline entr(y/ies) no "
                "longer match (fixed or moved) — prune with "
                "--prune-baseline"
            )
        print(summary)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
