"""On-chip formulation microbenchmarks for the groupby/sort redesign.

Round-5 measurement tool (VERDICT item 2): the 16M A/B landed single-
pass variadic lax.sort at 0.18 s, beating both narrow-word two-level
designs — so the constant we must attack is the sort itself (or skip
sorting entirely). Each probe below isolates one primitive cost on the
real chip; together they decide which groupby formulation can reach the
>=5x round-3 target (<=0.22 s at 100M rows):

  sort_u64_1op / sort_u32_1op   is a 32-bit sort word ~2x a 64-bit one?
  sort_u64_variadic             cost of payload operands riding lax.sort
  sort_u32_batched              XLA batched chunk sorts (the r4 bet)
  segment_sum_scatter           XLA scatter-add: skip the sort entirely?
  onehot_matmul_K{128,1024,8192}  MXU histogram: viable K ceiling?
  gather_16m                    random-gather throughput (counting-sort
                                / permutation-apply building block)

Usage:  python tools/exp_groupby.py [n_rows]   (default 16M; prints one
JSON line per probe, cheap first — safe to kill anytime)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 16_777_216
K_GROUPS = 10_000


def _sync(x):
    import jax

    leaves = [l for l in jax.tree.leaves(x) if hasattr(l, "dtype")]
    np.asarray(leaves[0].ravel()[-1])
    return x


def _time(fn, *args, reps=3):
    _sync(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _emit(name, secs, rows=N, **extra):
    d = {
        "probe": name,
        "seconds": round(secs, 6),
        "rows": rows,
        "rows_per_s": round(rows / secs, 1),
    }
    d.update(extra)
    print("EXP " + json.dumps(d), flush=True)


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(99)
    platform = jax.devices()[0].platform
    print(f"# platform={platform} n={N}", file=sys.stderr, flush=True)

    k_host = rng.integers(0, K_GROUPS, N, dtype=np.int64)
    v_host = rng.integers(-1000, 1000, N, dtype=np.int64)
    u64 = jax.device_put(
        ((k_host.astype(np.uint64) << np.uint64(24))
         | np.arange(N, dtype=np.uint64) & np.uint64((1 << 24) - 1))
    )
    u32 = jax.device_put(rng.integers(0, 1 << 32, N, dtype=np.uint64)
                         .astype(np.uint32))
    k_dev = jax.device_put(k_host)
    v_dev = jax.device_put(v_host)
    k32 = jax.device_put(k_host.astype(np.int32))
    v32 = jax.device_put(v_host.astype(np.int32))
    jax.block_until_ready(v32)

    # --- gather: random permutation apply ------------------------------
    idx = jax.device_put(rng.permutation(N).astype(np.int32))
    f = jax.jit(lambda a, i: jnp.take(a, i, axis=0))
    _emit("gather_16m_i64", _time(f, v_dev, idx))
    _emit("gather_16m_i32", _time(f, v32, idx))

    # --- single-operand sorts -----------------------------------------
    f = jax.jit(lambda a: jax.lax.sort((a,), num_keys=1)[0])
    _emit("sort_u32_1op", _time(f, u32))
    _emit("sort_u64_1op", _time(f, u64))

    # --- variadic: key + payload --------------------------------------
    f = jax.jit(lambda a, b: jax.lax.sort((a, b), num_keys=1))
    _emit("sort_u64_variadic2", _time(f, u64, v_dev))
    f = jax.jit(
        lambda a, b, c, d: jax.lax.sort((a, b, c, d), num_keys=1)
    )
    _emit(
        "sort_u64_variadic4",
        _time(f, u64, v_dev, k_dev, jnp.arange(N, dtype=jnp.int32)),
    )

    # --- batched chunk sorts (u32, single word) ------------------------
    t = 8192
    b32 = u32.reshape(N // t, t)
    f = jax.jit(lambda a: jax.lax.sort((a,), dimension=1, num_keys=1)[0])
    _emit("sort_u32_batched_8192", _time(f, b32))

    # --- scatter segment-sum ------------------------------------------
    f = jax.jit(
        lambda v, s: jax.ops.segment_sum(v, s, num_segments=K_GROUPS)
    )
    _emit("segment_sum_scatter_i64", _time(f, v_dev, k32))
    f = jax.jit(
        lambda v, s: jax.ops.segment_sum(v, s, num_segments=K_GROUPS)
    )
    _emit(
        "segment_sum_scatter_f32",
        _time(f, v32.astype(jnp.float32), k32),
    )

    # --- one-hot MXU histogram ----------------------------------------
    # bf16 one-hot @ bf16 limbs, f32 accumulate; R-row blocks keep the
    # f32 partials exact (R * 255 < 2^24). Timing probe only: exact
    # recombination is the production arm's job.
    def onehot_sum(kk, vv, K, R):
        kb = kk.reshape(N // R, R)
        vb = vv.reshape(N // R, R)
        iota = jnp.arange(K, dtype=jnp.int32)

        def step(carry, kv):
            kr, vr = kv
            oh = (kr[:, None] == iota[None, :]).astype(jnp.bfloat16)
            lo = (vr & 0xFF).astype(jnp.bfloat16)
            hi = ((vr >> 8) & 0xFF).astype(jnp.bfloat16)
            x = jnp.stack([lo, hi, jnp.ones_like(lo)], axis=1)
            p = jax.lax.dot_general(
                x, oh,
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (3, K)
            return carry + p.astype(jnp.int64), None

        init = jnp.zeros((3, K), jnp.int64)
        out, _ = jax.lax.scan(step, init, (kb, vb))
        return out

    for K in (128, 1024, 8192):
        f = jax.jit(lambda kk, vv, K=K: onehot_sum(kk, vv, K, 32768))
        kk = jax.device_put((k_host % K).astype(np.int32))
        _emit(f"onehot_matmul_K{K}", _time(f, kk, v32), K=K)


if __name__ == "__main__":
    main()
