"""Fold daemon-captured bench results into BASELINE.json's published
section.

The self-healing daemon (bench.py --daemon) merges each config's
result into benchmarks/bench_state.json the moment the flaky tunnel
yields it. This tool publishes whatever has landed into
BASELINE.json["published"] — keyed by entry name, stamped with
measurement time and round — so the repo's own baseline record stays
current even when the round ends mid-outage.

    python tools/publish_bench.py [--round N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATE = os.path.join(REPO, "benchmarks", "bench_state.json")
BASELINE = os.path.join(REPO, "BASELINE.json")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=5)
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()

    try:
        with open(STATE) as f:
            state = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("no bench state captured (tunnel never answered)")
        return 1
    entries = state.get("entries", {})
    if not entries:
        print("bench state empty")
        return 1

    with open(BASELINE) as f:
        baseline = json.load(f)
    pub = baseline.setdefault("published", {})
    measured = pub.setdefault("measured_entries", {})
    added = 0
    for config, got in sorted(entries.items()):
        for e in got.get("results", []):
            name = e.get("name", config)
            measured[name] = dict(e, measured_at=got["measured_at"],
                                  round=args.round)
            added += 1
    pub["round"] = max(pub.get("round", 0), args.round)
    print(f"publishing {added} entries from {len(entries)} configs")
    if args.dry_run:
        print(json.dumps(measured, indent=1)[:2000])
        return 0
    tmp = BASELINE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(baseline, f, indent=1)
    os.replace(tmp, BASELINE)
    return 0


if __name__ == "__main__":
    sys.exit(main())
