#!/usr/bin/env python3
"""Run the plan-time analyzer over every plan LITERAL in the repo's
drivers — the CI gate that keeps bench arms and smoke scripts inside
the dispatch plane's statically-supported surface.

Scans the given files for plan literals — a list literal whose elements
are all dicts with an ``"op"`` key, or a lone op dict (treated as a
1-op plan) — resolves the small constant vocabulary those literals use
(``int(dt.TypeId.X)``, ``dt.TypeId.X``, and module-level names assigned
from either), and runs ``plancheck.analyze`` structurally (no input
schema: the drivers feed many shapes). Any plan that fails the
structural walk — unknown op, malformed spec, bad join how — fails the
gate with the op index and reason.

Shell scripts are scanned too: python heredocs (``<<'PY'`` ... ``PY``)
are extracted and parsed as modules, which is how the smoke scripts
embed their plans.

Usage::

    python tools/plancheck_literals.py bench.py ci/smoke-chaos.sh ...
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_HEREDOC_RE = re.compile(
    r"<<\s*['\"]?(PY|PYTHON|EOF_PY)['\"]?\n(.*?)\n\1\s*$",
    re.DOTALL | re.MULTILINE,
)


class _Unresolved(Exception):
    pass


def _typeid_value(node: ast.AST) -> Optional[int]:
    """``dt.TypeId.X`` / ``TypeId.X`` -> the numeric id, else None."""
    from spark_rapids_jni_tpu import dtype as dt

    if isinstance(node, ast.Attribute):
        v = node.value
        is_typeid = (
            isinstance(v, ast.Attribute) and v.attr == "TypeId"
        ) or (isinstance(v, ast.Name) and v.id == "TypeId")
        if is_typeid and node.attr in dt.TypeId.__members__:
            return int(dt.TypeId[node.attr])
    return None


def _resolve(node: ast.AST, env: Dict[str, object]):
    """Literal evaluator for the plan-constant vocabulary."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Dict):
        out = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                raise _Unresolved("dict splat")
            out[_resolve(k, env)] = _resolve(v, env)
        return out
    if isinstance(node, (ast.List, ast.Tuple)):
        return [_resolve(e, env) for e in node.elts]
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise _Unresolved(f"name {node.id!r}")
    tid = _typeid_value(node)
    if tid is not None:
        return tid
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "int" and len(node.args) == 1:
        return int(_resolve(node.args[0], env))
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_resolve(node.operand, env)
    raise _Unresolved(ast.dump(node)[:60])


def _is_op_dict(node: ast.AST) -> bool:
    return isinstance(node, ast.Dict) and any(
        isinstance(k, ast.Constant) and k.value == "op"
        for k in node.keys
    )


def _collect_plans(tree: ast.Module) -> List[Tuple[int, list]]:
    """(line, plan) for every plan literal in the module. A constant
    environment of module/function-level ``NAME = <resolvable>``
    assignments feeds the evaluator."""
    env: Dict[str, object] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                env[node.targets[0].id] = _resolve(node.value, env)
            except _Unresolved:
                pass

    plans: List[Tuple[int, list]] = []
    in_list: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.List) and node.elts and all(
            _is_op_dict(e) for e in node.elts
        ):
            try:
                plans.append((node.lineno, _resolve(node, env)))
            except _Unresolved as e:
                print(
                    f"  note: line {node.lineno}: plan literal uses "
                    f"unresolvable value ({e}) — skipped"
                )
            in_list.update(id(e) for e in node.elts)
    for node in ast.walk(tree):
        if _is_op_dict(node) and id(node) not in in_list:
            try:
                plans.append((node.lineno, [_resolve(node, env)]))
            except _Unresolved as e:
                print(
                    f"  note: line {node.lineno}: op literal uses "
                    f"unresolvable value ({e}) — skipped"
                )
    plans.sort(key=lambda p: p[0])
    return plans


def _modules_in(path: str) -> List[Tuple[str, ast.Module]]:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    if path.endswith(".py"):
        return [(path, ast.parse(text, filename=path))]
    out = []
    for m in _HEREDOC_RE.finditer(text):
        body = m.group(2)
        line0 = text[: m.start(2)].count("\n")
        try:
            tree = ast.parse(body)
        except SyntaxError:
            continue  # not a python heredoc after all
        ast.increment_lineno(tree, line0)
        out.append((path, tree))
    return out


def main(argv=None) -> int:
    from spark_rapids_jni_tpu import plancheck

    paths = (argv if argv is not None else sys.argv[1:]) or ["bench.py"]
    total = 0
    bad = 0
    for path in paths:
        for src, tree in _modules_in(path):
            for line, plan in _collect_plans(tree):
                total += 1
                # generic unknown-schema extra tables: the drivers feed
                # multi-table ops (join/concat) their build sides at
                # runtime, which a structural walk cannot see — without
                # these, every join-bearing driver plan would be
                # rejected for missing inputs it does in fact have
                report = plancheck.analyze(
                    plan, rest=[(None, None)] * 8
                )
                if report["ok"]:
                    continue
                bad += 1
                first = next(
                    e for e in report["ops"]
                    if e["tier"] == "unsupported"
                )
                print(
                    f"{src}:{line}: plan literal REJECTED — "
                    f"op[{first['index']}] {first['op']!r}: "
                    f"{first['reason']}"
                )
    label = "clean" if not bad else f"{bad} REJECTED"
    print(
        f"plancheck-literals: {total} plan literal(s) across "
        f"{len(paths)} file(s): {label}"
    )
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
