"""Render profiler sessions as a human-readable EXPLAIN ANALYZE tree.

Input is anything that carries profile sessions (utils/profiler.py):

* a ``SPARK_RAPIDS_TPU_PROFILE_DUMP`` file (``{"sessions": [...]}``),
* a flight-recorder dump (sessions ride as the ``profile_sessions``
  exit section),
* a raw session doc, or a bench output file / stdout whose config
  records embed ``profile`` blocks (last-parseable-line discipline).

One line per plan op, annotated with its fused-segment membership;
segment headers carry the wall-time split (compile / execute / serde /
stall — they sum to the segment wall by construction), time %, rows
in/out, pad waste and compile-cache status. ``--json`` emits the
machine form instead.

``--merge`` combines dumps from SEVERAL processes/hosts into one
report ordered on the shared wall clock (profiler.merge_sessions) and
— when the inputs are flight dumps with events — one merged Perfetto
trace with a process track per dump (tracing.merge_chrome_traces),
written to ``-o`` (default: merged.trace.json).

``--static`` switches to plan-time analysis: the input is a plan JSON
file (a list of op objects) rendered as a tagged report — per-op
support tier + reason, inferred output schema, predicted segmentation
and the static HBM footprint bound — without executing anything
(spark_rapids_jni_tpu/plancheck.py, the GpuOverrides tagging analog).
``--schema`` supplies the input column signature as comma-separated
tokens (``int64``, ``decimal64:-2``, ``list<int32>``, ``string``...);
without it the walk is structural only.

``--drift`` renders the plan-stats store (utils/planstats.py) instead:
per-(plan, schema, bucket) group, each segment's observed rows/HBM/
wall-time percentiles next to plancheck's static prediction, plus the
typed drift findings recorded at append time. Inputs are store files
or directories (default: the configured ``PLANSTATS_DIR``).

Usage:
    python tools/explain.py profile.json
    python tools/explain.py --json profile.json
    python tools/explain.py --merge worker0.json worker1.json -o m.json
    python tools/explain.py --static plan.json --schema int64,bool8 --rows 4096
    python tools/explain.py --drift [statsdir]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
# report rendering is pure stdlib, but importing the package pulls jax
# in — keep the reader off the accelerator plugin
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spark_rapids_jni_tpu.utils.profiler import (  # noqa: E402
    extract_sessions,
    merge_sessions,
)
from spark_rapids_jni_tpu.utils.tracing import (  # noqa: E402
    merge_chrome_traces,
)


def load_doc(path: str):
    """One JSON doc from ``path``, or the LAST parseable line (bench
    stdout / BENCH_r*.json — the analyze_bench discipline)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in text.splitlines():
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise
        return doc


def parse_schema_tokens(spec: str):
    """``int64,decimal64:-2,list<int32>,string`` -> [ColType, ...]."""
    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import plancheck

    cols = []
    for raw in spec.split(","):
        tok = raw.strip()
        if not tok:
            continue
        scale = 0
        child = None
        if tok.lower().startswith("list<") and tok.endswith(">"):
            child = dt.TypeId[tok[5:-1].strip().upper()]
            tid = dt.TypeId.LIST
        else:
            if ":" in tok:
                tok, scale_s = tok.split(":", 1)
                scale = int(scale_s)
            tid = dt.TypeId[tok.strip().upper()]
        cols.append(plancheck.ColType(tid, scale, child))
    return cols


def run_drift(args) -> int:
    """--drift: render the plan-stats store as predicted-vs-observed
    per-segment history with percentiles (utils/planstats.py). Inputs
    are stats-store files or directories; with none, the configured
    ``SPARK_RAPIDS_TPU_PLANSTATS_DIR`` (or its tempdir default)."""
    from spark_rapids_jni_tpu.utils import planstats

    records = []
    paths = args.inputs or [planstats.stats_dir()]
    for p in paths:
        records.extend(planstats.load(p))
    if not records:
        print(
            "explain: no plan-stats records in "
            + ", ".join(repr(p) for p in paths)
            + " (was SPARK_RAPIDS_TPU_PLANSTATS on?)",
            file=sys.stderr,
        )
        return 1
    report = planstats.drift_report(records)
    if args.as_json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(planstats.render_drift(report))
    return 0


def run_static(args) -> int:
    """--static: tag a plan file without executing it."""
    from spark_rapids_jni_tpu import plancheck

    rc = 0
    out = []
    for path in args.inputs:
        with open(path) as f:
            ops = json.load(f)
        schema = (
            parse_schema_tokens(args.schema) if args.schema else None
        )
        report = plancheck.analyze(ops, schema=schema, rows=args.rows)
        if args.as_json:
            out.append(json.dumps(report, indent=1, sort_keys=True))
        else:
            out.append(f"== {path} ==\n" + plancheck.render_report(report))
        if not report["ok"]:
            rc = 1
    print("\n\n".join(out))
    return rc


def _ms(seconds) -> str:
    return f"{float(seconds or 0.0) * 1e3:.2f}ms"


def _bytes_h(n) -> str:
    n = int(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n}B"


def _cache_status(seg: dict) -> str:
    hits = int(seg.get("cache_hits") or 0)
    misses = int(seg.get("cache_misses") or 0)
    if hits == 0 and misses == 0:
        return "cache -"
    return f"cache {hits}H/{misses}M"


def render_session(doc: dict) -> str:
    """One session doc -> the EXPLAIN ANALYZE tree."""
    lines = []
    wall = float(doc.get("wall_s") or 0.0)
    head = (
        f"EXPLAIN ANALYZE  session={doc.get('session_id', '?')}"
        f"  label={doc.get('label', '?')}"
        f"  pid={doc.get('pid', '?')}@{doc.get('host', '?')}"
        f"  wall={_ms(wall)}"
    )
    if doc.get("batches") is not None:
        head += f"  batches={doc['batches']}"
    lines.append(head)
    segs = doc.get("segments", []) or []
    plan = doc.get("plan") or []
    fused = sum(1 for s in segs if s.get("kind") == "fused")
    launches = sum(int(s.get("launches") or 0) for s in segs)
    hits = sum(int(s.get("cache_hits") or 0) for s in segs)
    misses = sum(int(s.get("cache_misses") or 0) for s in segs)
    lines.append(
        f"plan: {len(plan) or sum(len(s.get('ops', [])) for s in segs)}"
        f" ops -> {len(segs)} segments ({fused} fused)"
        f" · launches {launches} (cache {hits}H/{misses}M)"
    )
    for s in segs:
        pct = (100.0 * float(s.get("wall_s") or 0.0) / wall) if wall else 0.0
        calls = int(s.get("calls") or 1)
        hdr = (
            f"  Segment {s.get('index', '?')} [{s.get('kind', '?')}"
            + (f" x{calls}" if calls > 1 else "")
            + f"]  {pct:5.1f}%  {_ms(s.get('wall_s'))}"
            f"  (compile {_ms(s.get('compile_s'))}"
            f" + execute {_ms(s.get('execute_s'))}"
            f" + serde {_ms(s.get('serde_s'))}"
            f" + stall {_ms(s.get('stall_s'))})"
        )
        lines.append(hdr)
        detail = (
            f"      rows {int(s.get('rows_in') or 0)}"
            f" -> {int(s.get('rows_out') or 0)}"
            f" · {_cache_status(s)}"
        )
        if s.get("pad_rows"):
            detail += (
                f" · pad {int(s['pad_rows'])} rows"
                f"/{_bytes_h(s.get('pad_waste_bytes'))}"
            )
        if s.get("donated_bytes"):
            detail += f" · donated {_bytes_h(s['donated_bytes'])}"
        if s.get("fallbacks"):
            detail += f" · FALLBACKS {int(s['fallbacks'])}"
        lines.append(detail)
        ops = s.get("ops", []) or []
        for j, op in enumerate(ops):
            branch = "└─" if j == len(ops) - 1 else "├─"
            member = (
                f"seg {s.get('index', '?')} · {s.get('kind', '?')}"
            )
            lines.append(f"      {branch} {op}  [{member}]")
    b = doc.get("boundary") or {}
    extras = []
    if b.get("serde_s") or b.get("serde_bytes_in") or b.get(
        "serde_bytes_out"
    ):
        extras.append(
            f"serde {_ms(b.get('serde_s'))}"
            f" (in {_bytes_h(b.get('serde_bytes_in'))}"
            f" / out {_bytes_h(b.get('serde_bytes_out'))})"
        )
    if b.get("stall_s"):
        extras.append(f"stall {_ms(b.get('stall_s'))}")
    if b.get("compile_s"):
        extras.append(f"compile {_ms(b.get('compile_s'))}")
    if b.get("pad_rows"):
        extras.append(
            f"pad {int(b['pad_rows'])} rows"
            f"/{_bytes_h(b.get('pad_waste_bytes'))}"
        )
    if b.get("shuffles"):
        extras.append(
            f"shuffles {int(b['shuffles'])}"
            f" ({int(b.get('shuffle_rows') or 0)} rows)"
        )
    if extras:
        lines.append("  boundary (outside segments): " + " · ".join(extras))
    ua = float(doc.get("unattributed_s") or 0.0)
    if wall:
        lines.append(
            f"  unattributed: {_ms(ua)} ({100.0 * ua / wall:.1f}%)"
        )
    return "\n".join(lines)


def render_merged(merged: dict) -> str:
    """A profiler.merge_sessions document -> one multi-process report."""
    lines = []
    procs = merged.get("processes", []) or []
    sess = merged.get("sessions", []) or []
    lines.append(
        f"MERGED PROFILE  {len(procs)} process(es), "
        f"{len(sess)} session(s)"
    )
    for p in procs:
        ids = ", ".join(str(s)[:8] for s in p.get("session_ids", []))
        lines.append(
            f"  process {p.get('host', '?')}:{p.get('pid', '?')}"
            f"  sessions: {ids}"
        )
    for s in sess:
        lines.append("")
        lines.append(render_session(s))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="profiler sessions -> EXPLAIN ANALYZE report",
    )
    ap.add_argument(
        "inputs", nargs="*",
        help="profile dump / flight dump / bench output file(s); with "
        "--drift, stats-store files/directories (default: the "
        "configured store directory)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the machine-readable document instead of the tree",
    )
    ap.add_argument(
        "--merge", action="store_true",
        help="merge multiple process dumps into one report (+ one "
        "Perfetto trace when the inputs carry flight events)",
    )
    ap.add_argument(
        "-o", "--output",
        help="merged Perfetto trace path (with --merge; default: "
        "merged.trace.json)",
    )
    ap.add_argument(
        "--static", action="store_true",
        help="inputs are plan JSON files: render the plancheck tagged "
        "report (tiers, inferred schemas, predicted segments, HBM "
        "bound) without executing; exit 1 if any plan is rejected",
    )
    ap.add_argument(
        "--schema",
        help="with --static: input column signature, comma-separated "
        "(int64, decimal64:-2, list<int32>, string, ...)",
    )
    ap.add_argument(
        "--rows", type=int,
        help="with --static: input row-count bound for the footprint "
        "estimate",
    )
    ap.add_argument(
        "--drift", action="store_true",
        help="inputs are plan-stats store files/dirs (utils/"
        "planstats.py): render predicted-vs-observed per-segment "
        "history with percentiles + typed drift findings",
    )
    args = ap.parse_args(argv)
    if args.drift:
        return run_drift(args)
    if not args.inputs:
        ap.error("inputs are required (except with --drift)")
    if args.static:
        return run_static(args)
    if len(args.inputs) > 1 and not args.merge:
        args.merge = True
    docs = [load_doc(p) for p in args.inputs]

    if args.merge:
        merged = merge_sessions(docs)
        if not merged["sessions"]:
            print(
                "explain: no profile sessions in "
                + ", ".join(repr(p) for p in args.inputs)
                + " (was SPARK_RAPIDS_TPU_PROFILE on?)",
                file=sys.stderr,
            )
            return 1
        if args.as_json:
            print(json.dumps(merged, indent=1, sort_keys=True))
        else:
            print(render_merged(merged))
        # one merged Perfetto timeline from whichever inputs are flight
        # dumps with events (wall-clock aligned, one process track per
        # dump)
        flight_docs = [
            d for d in docs
            if isinstance(d, dict) and isinstance(d.get("events"), list)
            and d["events"]
        ]
        if flight_docs:
            trace = merge_chrome_traces(flight_docs)
            out_path = args.output or "merged.trace.json"
            with open(out_path, "w") as f:
                json.dump(trace, f, indent=1, sort_keys=True)
                f.write("\n")
            print(
                f"\nwrote {out_path}: {len(trace['traceEvents'])} trace "
                f"events across {len(flight_docs)} process(es) — open "
                "at https://ui.perfetto.dev",
                file=sys.stderr,
            )
        return 0

    sessions = extract_sessions(docs[0])
    if not sessions:
        print(
            f"explain: no profile sessions in {args.inputs[0]!r} "
            "(was SPARK_RAPIDS_TPU_PROFILE on?)",
            file=sys.stderr,
        )
        return 1
    if args.as_json:
        print(json.dumps(sessions, indent=1, sort_keys=True))
        return 0
    out = []
    for s in sessions:
        out.append(render_session(s))
    print("\n\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
