"""Convert a flight-recorder dump into a chrome://tracing / Perfetto JSON.

Input is either:

* a ``SPARK_RAPIDS_TPU_FLIGHT_DUMP`` file (``{"events": [...], ...}``,
  written at exit / SIGTERM by utils/flight.py), or
* a bench output file (``BENCH_r*.json`` or the raw bench stdout): the
  last parseable JSON line is scanned and every structured failure
  record's ``flight_tail`` is concatenated into one timeline — the
  postmortem view of a run that died with ``"device unreachable"``.

Usage:
    python tools/trace2chrome.py flight.json [-o trace.json]

Open the output at https://ui.perfetto.dev ("Open trace file") or
chrome://tracing ("Load"). Spans appear as per-thread tracks grouped by
subsystem category (dispatch, wire, bucketed, shuffle, ...); counter
samples (``resident.live``, ``bucket.pad_waste_bytes``) appear as
counter tracks.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
# the converter itself is pure stdlib, but importing the package pulls
# jax in — keep a converter-only import off the accelerator plugin
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spark_rapids_jni_tpu.utils.tracing import to_chrome_trace  # noqa: E402


def _events_from(doc) -> list:
    """Flight events from a flight dump or a bench summary document."""
    if isinstance(doc, dict) and isinstance(doc.get("events"), list):
        return doc["events"]
    events = []
    if isinstance(doc, dict):
        # bench headline line: collect every failure record's tail
        summary = doc.get("parsed") or doc
        for e in summary.get("configs", []) or []:
            f = e.get("failure")
            if isinstance(f, dict) and isinstance(
                f.get("flight_tail"), list
            ):
                events.extend(f["flight_tail"])
    # several configs may carry the same parent-process tail: dedup by
    # (seq, t_ns) so the timeline doesn't stack identical spans. Older
    # or corrupt dumps may carry non-dict rows — drop them here, the
    # same tolerance the exporter applies (a postmortem tool must read
    # every format that ever wrote a dump)
    seen = set()
    out = []
    for e in events:
        if not isinstance(e, dict):
            continue
        key = (e.get("seq"), e.get("t_ns"))
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def load_doc(path: str):
    """Parse ``path`` as one JSON doc, or line-wise (bench stdout /
    BENCH_r*.json: take the LAST parseable line, the analyze_bench
    discipline)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in text.splitlines():
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise
        return doc


def load_events(path: str) -> list:
    return _events_from(load_doc(path))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="flight-recorder dump -> Chrome-trace/Perfetto JSON"
    )
    ap.add_argument("input", help="flight dump or bench JSON file")
    ap.add_argument(
        "-o", "--output",
        help="output path (default: <input>.trace.json)",
    )
    args = ap.parse_args(argv)
    doc = load_doc(args.input)
    events = _events_from(doc)
    if not events:
        print(
            f"trace2chrome: no flight events in {args.input!r} "
            "(was SPARK_RAPIDS_TPU_FLIGHT_DUMP / FLIGHT enabled?)",
            file=sys.stderr,
        )
        return 1
    # a flight dump carries (pid, host, session_id) process metadata:
    # label the process track so a multi-process Perfetto merge doesn't
    # collide on tid alone
    kw = {}
    if isinstance(doc, dict) and isinstance(doc.get("events"), list):
        if doc.get("pid") is not None:
            kw["pid"] = int(doc["pid"])
        if doc.get("host"):
            name = f"{doc['host']}:{doc.get('pid', '?')}"
            if doc.get("session_id"):
                name = f"{name} [{str(doc['session_id'])[:8]}]"
            kw["process_name"] = name
            kw["process_sort_index"] = 0
    trace = to_chrome_trace(events, **kw)
    out_path = args.output or args.input + ".trace.json"
    with open(out_path, "w") as f:
        json.dump(trace, f, indent=1, sort_keys=True)
        f.write("\n")
    spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    counters = {
        e["name"] for e in trace["traceEvents"] if e.get("ph") == "C"
    }
    print(
        f"wrote {out_path}: {len(trace['traceEvents'])} trace events "
        f"({spans} spans, {len(counters)} counter tracks) — open at "
        "https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
