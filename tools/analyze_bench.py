"""Print the formulation-A/B verdicts from the banked bench state.

Reads benchmarks/bench_state.json (the daemon's merge file) and/or a
BENCH_r*.json line, groups the config-1/3 arms by shape, and prints
each A/B with its winner — the round-5 decision table (which
formulation becomes each op's default) generated from data instead of
eyeballs.

Also summarizes the per-config "metrics" blocks bench entries carry
since the observability PR (top ops by time and by bytes moved,
span-duration p50/p95/max from the ``span_ms.*`` histograms, a top-5
ops-by-self-time table, a plan-fusion summary from the ``plan.*``
counters and ``fusion`` blocks, structured failure records, plus the
headline ``drift`` block the plan-stats store emits since the
observability PR), tolerating old BENCH files that predate any of
these fields.

Usage: python tools/analyze_bench.py [path-to-state-or-bench-json]
"""

from __future__ import annotations

import json
import os
import sys

_STATE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "bench_state.json",
)

# shape key -> arms, in "formulation" order (first = current default)
_GROUPS = {
    "groupby 16M": [
        "groupby_sum_16M", "groupby_sum_16M_gather",
        "groupby_sum_16M_flat_sort", "groupby_sum_16M_flat_gather",
        "groupby_sum_16M_packed", "groupby_sum_16M_packed_pallas32",
        "groupby_sum_16M_chunked",
    ],
    "groupby 100M": [
        "groupby_sum_100M", "groupby_sum_100M_gather",
        "groupby_sum_100M_flat_gather", "groupby_sum_100M_packed",
        "groupby_sum_100M_packed_pallas32", "groupby_sum_100M_chunked",
    ],
    "sort 100M": [
        "sort_100M_int64_payload", "sort_100M_int64_gather",
        "sort_100M_int64_packed", "sort_100M_int64_packed_gather",
    ],
    "chunk sort 16.7M": [
        "lax_sort_2048x8192", "pallas_bitonic_2048x8192",
        "pallas_u32_gather_2048x8192",
    ],
    "join 100M": [
        "inner_join_100M_batched_probe",
        "inner_join_100M_batched_packed",
    ],
    "transpose 4M": [
        "transpose_cast_round_trip", "transpose_cast_round_trip_pallas",
    ],
    "parquet 6M": [
        "parquet_scan_filter_agg_4x1500k",
        "parquet_device_decode_4x1500k",
    ],
}


def _load(path: str) -> tuple:
    """(ranked-entries-by-name, raw entry list incl. failures/metrics,
    headline ``drift`` block or None for files that predate it)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # BENCH_r*.json: take the LAST parseable line
        doc = None
        for line in text.splitlines():
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise
    entries = {}
    raw = []
    if "entries" in doc:  # daemon state file
        for cfg in doc["entries"].values():
            for e in cfg["results"]:
                raw.append(e)
                if "seconds_median" in e:
                    entries[e.get("name")] = e
    # BENCH_r*.json wraps the bench summary under "parsed"
    summary = doc.get("parsed") or doc
    for e in summary.get("configs", []) or []:
        raw.append(e)
        if "name" in e and "seconds_median" in e:
            entries.setdefault(e["name"], e)
    drift = summary.get("drift")
    return entries, raw, drift if isinstance(drift, dict) else None


def _merge_metrics(raw: list) -> dict:
    """Fold every entry's "metrics" block into one {timers, bytes,
    counters, histograms, span_self} aggregate. Identical blocks
    (several entries of one config share a snapshot) are folded once.
    Old BENCH files simply lack the newer sections — quiet tolerance."""
    timers: dict = {}
    byte_ctrs: dict = {}
    counters: dict = {}
    hists: dict = {}
    span_self: dict = {}
    seen = set()
    for e in raw:
        m = e.get("metrics")
        if not isinstance(m, dict):
            continue
        key = json.dumps(m, sort_keys=True)
        if key in seen:
            continue
        seen.add(key)
        for name, t in (m.get("timers") or {}).items():
            # max_s stays None until a block actually carries one:
            # PR-1-era timer rows lack it, and folding them in as 0.0
            # would print a false 0.00ms max for real spans
            agg = timers.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": None}
            )
            agg["count"] += int(t.get("count", 0))
            agg["total_s"] += float(t.get("total_s", 0.0))
            mx = t.get("max_s")
            if mx is not None:
                mx = float(mx)
                agg["max_s"] = (
                    mx if agg["max_s"] is None else max(agg["max_s"], mx)
                )
        for name, v in (m.get("bytes") or {}).items():
            byte_ctrs[name] = byte_ctrs.get(name, 0) + int(v)
        for name, v in (m.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(v)
        for name, h in (m.get("histograms") or {}).items():
            agg = hists.get(name)
            if agg is None:
                hists[name] = {
                    "bounds": list(h.get("bounds", [])),
                    "counts": list(h.get("counts", [])),
                }
            elif agg["bounds"] == list(h.get("bounds", [])):
                agg["counts"] = [
                    a + b for a, b in zip(agg["counts"], h.get("counts", []))
                ]
            # mismatched bounds across files: keep the first block (a
            # partial sum would misestimate every percentile)
        for name, t in (m.get("span_self") or {}).items():
            agg = span_self.setdefault(name, {"count": 0, "self_s": 0.0})
            agg["count"] += int(t.get("count", 0))
            agg["self_s"] += float(t.get("self_s", 0.0))
    return {
        "timers": timers,
        "bytes": byte_ctrs,
        "counters": counters,
        "histograms": hists,
        "span_self": span_self,
    }


def _hist_percentile(bounds: list, counts: list, q: float):
    """Upper-edge percentile estimate from a bounded histogram: the
    smallest bucket edge at or below which >= q of the mass sits.
    Returns None on an empty histogram; the overflow bucket reports as
    ">last edge" via float('inf')."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return float(bounds[i]) if i < len(bounds) else float("inf")
    return float("inf")


def _fmt_ms(v) -> str:
    if v is None:
        return "      ?"
    if v == float("inf"):
        return "   >max"
    return f"{v:7.2f}"


def summarize_spans(raw: list, top: int = 10, merged=None) -> None:
    """Span-duration distribution (p50/p95 estimated from the
    ``span_ms.*`` bounded histograms, exact max from the timer table)
    plus the top-5 ops by SELF time — the table that surfaces the hot
    leaf instead of the wrapper that encloses it. Old BENCH files that
    predate these sections are silently skipped. Pass a precomputed
    ``_merge_metrics(raw)`` to avoid re-folding."""
    if merged is None:
        merged = _merge_metrics(raw)
    span_hists = {
        name[len("span_ms."):]: h
        for name, h in merged["histograms"].items()
        if name.startswith("span_ms.")
    }
    if span_hists:
        ranked = sorted(
            span_hists.items(),
            key=lambda kv: sum(kv[1]["counts"]),
            reverse=True,
        )[:top]
        print("\nspan durations (ms; p50/p95 are histogram upper edges):")
        print(f"  {'span':42} {'count':>8} {'p50':>7} {'p95':>7} {'max':>9}")
        for name, h in ranked:
            p50 = _hist_percentile(h["bounds"], h["counts"], 0.50)
            p95 = _hist_percentile(h["bounds"], h["counts"], 0.95)
            t = merged["timers"].get(name) or {}
            mx = t.get("max_s")
            mx_ms = f"{mx * 1e3:9.2f}" if mx is not None else "        ?"
            print(
                f"  {name:42} {sum(h['counts']):8d} {_fmt_ms(p50)} "
                f"{_fmt_ms(p95)} {mx_ms}"
            )
    if merged["span_self"]:
        ranked = sorted(
            merged["span_self"].items(),
            key=lambda kv: kv[1]["self_s"],
            reverse=True,
        )[:5]
        print("\ntop 5 ops by self time (excl. enclosed spans):")
        for name, t in ranked:
            tot = merged["timers"].get(name, {}).get("total_s")
            frac = (
                f" ({100.0 * t['self_s'] / tot:.0f}% of span)"
                if tot else ""
            )
            print(
                f"  {name:42} {t['self_s']:9.3f}s over "
                f"{t['count']} calls{frac}"
            )


def summarize_metrics(raw: list, top: int = 10, merged=None) -> None:
    """Print top-N ops by total time and byte counters by volume from
    the entries' "metrics" blocks; quiet note when absent (old files).
    Pass a precomputed ``_merge_metrics(raw)`` to avoid re-folding."""
    if merged is None:
        merged = _merge_metrics(raw)
    if not merged["timers"] and not merged["bytes"]:
        print("\nno metrics blocks (pre-observability BENCH file)")
        return
    if merged["timers"]:
        print(f"\ntop {top} ops by total time:")
        ranked = sorted(
            merged["timers"].items(),
            key=lambda kv: kv[1]["total_s"],
            reverse=True,
        )[:top]
        for name, t in ranked:
            print(
                f"  {name:42} {t['total_s']:9.3f}s over "
                f"{t['count']} calls"
            )
    if merged["bytes"]:
        print(f"\ntop {top} byte counters:")
        ranked = sorted(
            merged["bytes"].items(), key=lambda kv: kv[1], reverse=True
        )[:top]
        for name, v in ranked:
            print(f"  {name:42} {v / 1e6:12.2f} MB")
    ops = sorted(
        (k, v) for k, v in merged["counters"].items()
        if k.startswith("op.") and k.endswith(".calls")
    )
    if ops:
        print("\ndispatched ops:")
        for name, v in ops:
            print(f"  {name[3:-6]:42} {v} calls")


def summarize_compile_cache(raw: list) -> None:
    """Per config block: compiled-executable cache efficiency
    (compile_cache.hit/miss) and shape-bucket pad waste
    (bucket.pad_waste_bytes) from the entries' metrics. Old BENCH files
    that predate the bucket plane simply have no such fields — silent
    skip, like the other metrics summaries."""
    rows = []
    seen = set()
    for e in raw:
        m = e.get("metrics")
        if not isinstance(m, dict):
            continue
        c = m.get("counters") or {}
        b = m.get("bytes") or {}
        hits = c.get("compile_cache.hit")
        misses = c.get("compile_cache.miss")
        waste = b.get("bucket.pad_waste_bytes", 0)
        if hits is None and misses is None and not waste:
            continue
        # several entries of one config share ONE snapshot: fold by the
        # full metrics block (the _merge_metrics discipline) — distinct
        # configs whose cache counters merely coincide keep their rows
        key = json.dumps(m, sort_keys=True)
        if key in seen:
            continue
        seen.add(key)
        rows.append((e.get("name", "?"), hits or 0, misses or 0, waste))
    if not rows:
        return
    print("\ncompile cache (per config block):")
    for name, h, mi, w in rows:
        tot = h + mi
        rate = (100.0 * h / tot) if tot else 0.0
        print(
            f"  {name:42} {h}/{tot} hits ({rate:.0f}%), "
            f"pad waste {w / 1e6:.2f} MB"
        )
    th = sum(r[1] for r in rows)
    tm = sum(r[2] for r in rows)
    tw = sum(r[3] for r in rows)
    if th + tm:
        print(
            f"  {'TOTAL':42} {th}/{th + tm} hits "
            f"({100.0 * th / (th + tm):.0f}%), "
            f"pad waste {tw / 1e6:.2f} MB"
        )


def summarize_plan_fusion(raw: list, merged=None) -> None:
    """Plan-fusion summary: fused-op fraction and launch savings from
    the ``plan.*`` counters in the metrics blocks, plus the structured
    ``fusion`` block the bench ``fused_plan`` config emits (per-op vs
    fused launch counts). Old BENCH files have neither — silent skip,
    like the other metrics summaries. Pass a precomputed
    ``_merge_metrics(raw)`` to avoid re-folding."""
    if merged is None:
        merged = _merge_metrics(raw)
    c = merged["counters"]
    fused_ops = int(c.get("plan.fused_ops", 0))
    exact_ops = int(c.get("plan.exact_ops", 0))
    segments = int(c.get("plan.segments", 0))
    blocks = [e for e in raw if isinstance(e.get("fusion"), dict)]
    if not (fused_ops or exact_ops or segments or blocks):
        return
    print("\nplan fusion:")
    if fused_ops or exact_ops or segments:
        total = fused_ops + exact_ops
        frac = (100.0 * fused_ops / total) if total else 0.0
        fused_segs = int(c.get("plan.fused_segments", 0))
        print(
            f"  plans={int(c.get('plan.calls', 0))} segments={segments} "
            f"fused_segments={fused_segs} "
            f"fallbacks={int(c.get('plan.fallbacks', 0))} "
            f"declined={int(c.get('plan.declined', 0))}"
        )
        print(
            f"  fused ops {fused_ops}/{total} ({frac:.0f}%), "
            f"launches saved {fused_ops - fused_segs} "
            "(vs one launch per fused op)"
        )
    for e in blocks:
        f = e["fusion"]
        print(
            f"  {e.get('name', '?'):42} "
            f"{f.get('fused_launches', '?')} fused vs "
            f"{f.get('per_op_launches', '?')} per-op launches "
            f"(saved {f.get('launches_saved', '?')}); "
            f"warm {e.get('warm_speedup', '?')}x "
            f"cold {e.get('cold_speedup', '?')}x"
        )


def summarize_pipeline(raw: list, merged=None) -> None:
    """Pipelined-dispatch summary: per-entry ``pipeline`` blocks (the
    bench ``pipelined_stream`` config) plus the merged ``pipeline.*``
    counters — depth, overlap fraction, stalls/replays, donated and
    batch-upload savings. Old BENCH files have neither — silent skip,
    like the other metrics summaries."""
    if merged is None:
        merged = _merge_metrics(raw)
    c = merged["counters"]
    b = merged["bytes"]
    blocks = [e for e in raw if isinstance(e.get("pipeline"), dict)]
    enq = int(c.get("pipeline.enqueued", 0))
    donated = int(b.get("hbm.donated_bytes", 0))
    if not (blocks or enq or donated):
        return
    print("\npipelined dispatch:")
    if enq or donated:
        print(
            f"  stages={enq} completed={int(c.get('pipeline.completed', 0))} "
            f"stalls={int(c.get('pipeline.stalls', 0))} "
            f"replays={int(c.get('pipeline.replays', 0))} "
            f"donated {donated / 1e6:.2f} MB over "
            f"{int(c.get('hbm.donations', 0))} donations, "
            f"batched-upload transfers saved "
            f"{int(c.get('wire.upload.batched', 0))}"
        )
    for e in blocks:
        p = e["pipeline"]
        print(
            f"  {e.get('name', '?'):42} depth={p.get('depth', '?')} "
            f"overlap {p.get('overlap_fraction', '?')} "
            f"({p.get('overlap_ms', '?')} ms) "
            f"stalls={p.get('stalls', '?')} "
            f"donated {int(p.get('donated_bytes', 0)) / 1e6:.2f} MB; "
            f"warm {e.get('warm_speedup', '?')}x vs per-op sync, "
            f"{e.get('vs_plan_sync', '?')}x vs plan sync"
        )


def summarize_serving(raw: list) -> None:
    """Multi-tenant serving summary: per-entry ``serving`` blocks (the
    bench ``serving_multiquery`` config) — sessions, shed count, merged
    queue-wait percentiles and the cross-session compile-cache hit rate
    (tenant B warm-hitting tenant A's executables). Old BENCH files
    have no such blocks — silent skip, like the other summaries."""
    blocks = [e for e in raw if isinstance(e.get("serving"), dict)]
    if not blocks:
        return
    print("\nserving daemon:")
    for e in blocks:
        s = e["serving"]
        hits = int(s.get("cross_session_hits", 0))
        misses = int(s.get("cross_session_misses", 0))
        print(
            f"  {e.get('name', '?'):42} sessions={s.get('sessions', '?')} "
            f"requests={s.get('requests', '?')} shed={s.get('shed', '?')} "
            f"wait p50/p95 {s.get('queue_wait_ms_p50', '?')}/"
            f"{s.get('queue_wait_ms_p95', '?')} ms"
        )
        print(
            f"    cross-session cache: {hits} hits / {misses} misses "
            f"(rate {s.get('cross_session_hit_rate', '?')}; warm session "
            f"paid {s.get('warm_misses', '?')} compiles); leaked "
            f"tables={s.get('leaked_tables', '?')}"
        )
        for d in s.get("sessions_detail", []) or []:
            qw = d.get("queue_wait") or {}
            print(
                f"    {d.get('name', '?'):28} requests={d.get('requests', '?'):>3} "
                f"shed={d.get('shed', 0)} wait p95 {qw.get('p95_ms', '?')} ms "
                f"donated-credit {int(d.get('donated_credit_bytes', 0)) / 1e6:.2f} MB"
            )


def summarize_profile(raw: list, top: int = 8) -> None:
    """Top plan segments by time from the entries' ``profile`` blocks
    (the per-config aggregated profiler summary bench embeds since the
    query-profiler PR; tools/explain.py renders the full per-session
    tree). Old BENCH files have no such blocks — silent skip, like the
    other summaries."""
    segs: dict = {}
    order: list = []
    n_sessions = 0
    seen = set()
    for e in raw:
        p = e.get("profile")
        if not isinstance(p, dict) or not isinstance(
            p.get("segments"), list
        ):
            continue
        # several entries of one config share one block: fold once
        key = json.dumps(p, sort_keys=True)
        if key in seen:
            continue
        seen.add(key)
        n_sessions += int(p.get("sessions") or 0)
        for sd in p["segments"]:
            k = (
                e.get("name", "?"), sd.get("index"), sd.get("kind"),
                tuple(sd.get("ops", [])),
            )
            agg = segs.get(k)
            if agg is None:
                agg = dict(sd)
                agg["config"] = e.get("name", "?")
                segs[k] = agg
                order.append(k)
            else:
                for f in (
                    "calls", "wall_s", "compile_s", "execute_s",
                    "serde_s", "stall_s", "cache_hits", "cache_misses",
                    "launches",
                ):
                    agg[f] = (agg.get(f) or 0) + (sd.get(f) or 0)
    if not segs:
        return
    ranked = sorted(
        segs.values(), key=lambda s: float(s.get("wall_s") or 0.0),
        reverse=True,
    )[:top]
    print(f"\ntop plan segments by time ({n_sessions} profiled sessions):")
    print(
        f"  {'config/segment':42} {'wall':>9} {'compile':>9} "
        f"{'execute':>9} {'cache':>9}"
    )
    for s in ranked:
        label = (
            f"{s['config']}#"
            f"{s.get('index', '?')}[{s.get('kind', '?')}] "
            + "+".join(s.get("ops", []))
        )[:42]
        hits = int(s.get("cache_hits") or 0)
        misses = int(s.get("cache_misses") or 0)
        print(
            f"  {label:42} "
            f"{float(s.get('wall_s') or 0) * 1e3:8.2f}ms "
            f"{float(s.get('compile_s') or 0) * 1e3:8.2f}ms "
            f"{float(s.get('execute_s') or 0) * 1e3:8.2f}ms "
            f"{hits:>4}H/{misses}M"
        )


def summarize_failures(raw: list) -> None:
    """Print the structured failure records (diagnosable-from-JSON),
    grouped headline-first by taxonomy class. Old result files predate
    the ``class``/``backoff_ms`` fields — they render as
    ``unclassified`` / no backoff note rather than erroring."""
    fails = [e for e in raw if isinstance(e.get("failure"), dict)]
    if not fails:
        return
    by_class: dict = {}
    for e in fails:
        cls = e["failure"].get("class") or "unclassified"
        by_class[cls] = by_class.get(cls, 0) + 1
    classes = ", ".join(
        f"{c}={n}" for c, n in sorted(by_class.items())
    )
    print(f"\nfailures ({len(fails)} total: {classes}):")
    for e in fails:
        f = e["failure"]
        extra = []
        if f.get("class"):
            extra.append(f["class"])
        if f.get("skipped"):
            extra.append("skipped")
        if f.get("elapsed_s") is not None:
            extra.append(f"after {f['elapsed_s']}s")
        if f.get("retries"):
            extra.append(f"{f['retries']} retries")
        if f.get("backoff_ms"):
            extra.append(f"{f['backoff_ms']}ms backoff")
        tail = f" ({', '.join(extra)})" if extra else ""
        print(
            f"  {e.get('name', '?'):32} {f.get('type', 'Error')}: "
            f"{f.get('message', '')[:80]}{tail}"
        )


def summarize_skew(raw: list, merged=None) -> None:
    """Adaptive-shuffle-skew summary: the structured ``skew`` A/B block
    the ``mesh_skew_adaptive`` arm emits (splitting off vs on with
    seconds / recv-buffer / peak-RSS deltas) plus the skew fields on
    plain ``4-skew`` entries and the merged ``shuffle.skew_*`` /
    ``partition.*`` counters. Old BENCH files predate all of these —
    silent skip, like the other summaries."""
    if merged is None:
        merged = _merge_metrics(raw)
    blocks = [e for e in raw if isinstance(e.get("skew"), dict)]
    plain = [
        e for e in raw
        if not isinstance(e.get("skew"), dict)
        and ("skew_splits" in e or "max_over_mean" in e)
    ]
    c = merged["counters"]
    ctr_keys = sorted(
        k for k in c
        if k.startswith("shuffle.skew_") or k.startswith("partition.")
    )
    if not (blocks or plain or ctr_keys):
        return
    print("\nadaptive shuffle skew:")
    for e in blocks:
        s = e["skew"]
        off, on = s.get("off") or {}, s.get("on") or {}
        d = s.get("deltas") or {}

        def _f(v, fmt="{:.3f}"):
            return "?" if v is None else fmt.format(v)

        print(
            f"  {e.get('name', '?'):42} factor={s.get('factor', '?')} "
            f"splits={s.get('splits', '?')}"
        )
        print(
            f"    off: {_f(off.get('seconds'))}s "
            f"recv_buffer_rows={off.get('recv_buffer_rows', '?')} "
            f"rss={off.get('peak_rss_mb', '?')}MB "
            f"max/mean={_f(off.get('max_over_mean'), '{:.2f}')}"
        )
        print(
            f"    on:  {_f(on.get('seconds'))}s "
            f"recv_buffer_rows={on.get('recv_buffer_rows', '?')} "
            f"rss={on.get('peak_rss_mb', '?')}MB "
            f"max/mean={_f(on.get('max_over_mean'), '{:.2f}')}"
        )
        print(
            f"    deltas (off-on): {_f(d.get('seconds'))}s, "
            f"{d.get('recv_buffer_rows', '?')} recv rows, "
            f"{d.get('peak_rss_mb', '?')} MB RSS"
        )
    for e in plain:
        print(
            f"  {str(e.get('name') or e.get('config', '?')):42} "
            f"splits={e.get('skew_splits', '?')} "
            f"max_recv_rows={e.get('max_recv_rows', '?')} "
            f"max/mean={e.get('max_over_mean', '?')}"
        )
    if ctr_keys:
        print(
            "  counters: "
            + ", ".join(f"{k}={int(c[k])}" for k in ctr_keys)
        )


def summarize_drift(drift) -> None:
    """Plan-stats drift summary from the headline ``drift`` block
    (record/plan-group counts and typed findings accumulated by the
    run's stats store — planstats.summary()). Old BENCH files predate
    the block and pass None — silent skip, like the other summaries."""
    if not isinstance(drift, dict):
        return
    head = (
        f"\nplan drift: {drift.get('records', 0)} stats record(s) over "
        f"{drift.get('plans', 0)} plan group(s)"
    )
    findings = drift.get("findings") or {}
    if findings:
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(findings.items())
        )
        print(f"{head}; findings: {detail}")
        print("  inspect with: python tools/explain.py --drift <stats-dir>")
    else:
        print(f"{head}; no drift findings")


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else _STATE
    entries, raw, drift = _load(path)
    if not entries:
        print("no measured entries")
        merged = _merge_metrics(raw)
        summarize_metrics(raw, merged=merged)
        summarize_spans(raw, merged=merged)
        summarize_compile_cache(raw)
        summarize_plan_fusion(raw, merged=merged)
        summarize_pipeline(raw, merged=merged)
        summarize_serving(raw)
        summarize_profile(raw)
        summarize_skew(raw, merged=merged)
        summarize_failures(raw)
        summarize_drift(drift)
        return
    for label, arms in _GROUPS.items():
        got = [(a, entries[a]) for a in arms if a in entries]
        if not got:
            continue
        best = min(got, key=lambda kv: kv[1]["seconds_median"])
        print(f"\n{label}  (winner: {best[0]})")
        for name, e in got:
            ratio = e["seconds_median"] / best[1]["seconds_median"]
            mark = " <== winner" if name == best[0] else f"  {ratio:.2f}x"
            print(
                f"  {name:42} {e['seconds_median']:9.3f}s "
                f"{e.get('rows_per_s', 0) / 1e6:9.1f}M rows/s{mark}"
            )
    extra = sorted(
        n for n in entries
        if not any(n in arms for arms in _GROUPS.values())
    )
    if extra:
        print("\nother measured entries:", ", ".join(extra))
    merged = _merge_metrics(raw)
    summarize_metrics(raw, merged=merged)
    summarize_spans(raw, merged=merged)
    summarize_compile_cache(raw)
    summarize_plan_fusion(raw, merged=merged)
    summarize_pipeline(raw, merged=merged)
    summarize_serving(raw)
    summarize_profile(raw)
    summarize_skew(raw, merged=merged)
    summarize_failures(raw)
    summarize_drift(drift)


if __name__ == "__main__":
    main()
