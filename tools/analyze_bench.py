"""Print the formulation-A/B verdicts from the banked bench state.

Reads benchmarks/bench_state.json (the daemon's merge file) and/or a
BENCH_r*.json line, groups the config-1/3 arms by shape, and prints
each A/B with its winner — the round-5 decision table (which
formulation becomes each op's default) generated from data instead of
eyeballs.

Usage: python tools/analyze_bench.py [path-to-state-or-bench-json]
"""

from __future__ import annotations

import json
import os
import sys

_STATE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "bench_state.json",
)

# shape key -> arms, in "formulation" order (first = current default)
_GROUPS = {
    "groupby 16M": [
        "groupby_sum_16M", "groupby_sum_16M_gather",
        "groupby_sum_16M_flat_sort", "groupby_sum_16M_flat_gather",
        "groupby_sum_16M_packed", "groupby_sum_16M_packed_pallas32",
        "groupby_sum_16M_chunked",
    ],
    "groupby 100M": [
        "groupby_sum_100M", "groupby_sum_100M_gather",
        "groupby_sum_100M_flat_gather", "groupby_sum_100M_packed",
        "groupby_sum_100M_packed_pallas32", "groupby_sum_100M_chunked",
    ],
    "sort 100M": [
        "sort_100M_int64_payload", "sort_100M_int64_gather",
        "sort_100M_int64_packed", "sort_100M_int64_packed_gather",
    ],
    "chunk sort 16.7M": [
        "lax_sort_2048x8192", "pallas_bitonic_2048x8192",
        "pallas_u32_gather_2048x8192",
    ],
    "join 100M": [
        "inner_join_100M_batched_probe",
        "inner_join_100M_batched_packed",
    ],
    "transpose 4M": [
        "transpose_cast_round_trip", "transpose_cast_round_trip_pallas",
    ],
    "parquet 6M": [
        "parquet_scan_filter_agg_4x1500k",
        "parquet_device_decode_4x1500k",
    ],
}


def _load(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # BENCH_r*.json: take the LAST parseable line
        doc = None
        for line in text.splitlines():
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise
    entries = {}
    if "entries" in doc:  # daemon state file
        for cfg in doc["entries"].values():
            for e in cfg["results"]:
                entries[e.get("name")] = e
    # BENCH_r*.json wraps the bench summary under "parsed"
    summary = doc.get("parsed") or doc
    for e in summary.get("configs", []) or []:
        if "name" in e and "seconds_median" in e:
            entries.setdefault(e["name"], e)
    return entries


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else _STATE
    entries = _load(path)
    if not entries:
        print("no measured entries")
        return
    for label, arms in _GROUPS.items():
        got = [(a, entries[a]) for a in arms if a in entries]
        if not got:
            continue
        best = min(got, key=lambda kv: kv[1]["seconds_median"])
        print(f"\n{label}  (winner: {best[0]})")
        for name, e in got:
            ratio = e["seconds_median"] / best[1]["seconds_median"]
            mark = " <== winner" if name == best[0] else f"  {ratio:.2f}x"
            print(
                f"  {name:42} {e['seconds_median']:9.3f}s "
                f"{e.get('rows_per_s', 0) / 1e6:9.1f}M rows/s{mark}"
            )
    extra = sorted(
        n for n in entries
        if not any(n in arms for arms in _GROUPS.values())
    )
    if extra:
        print("\nother measured entries:", ", ".join(extra))


if __name__ == "__main__":
    main()
