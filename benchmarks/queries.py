"""TPC-DS q5/q23/q64-shaped queries over the op library.

Not the literal TPC-DS SQL (whose dimension DDL is far wider) but the
same operator DAGs at the same shapes — the structures BASELINE.json
configs 4-5 name:

* q5-shape:  multi-channel fact union -> date filter -> dimension join
             -> rollup aggregation.
* q23-shape: frequent-item CTE (groupby+filter) -> semi join against the
             fact table -> per-customer aggregation.
* q64-shape: chained multi-dimension joins (item, customer, date) with
             predicates -> wide-key aggregation.

Each query runs single-chip (eager ops) or distributed over a mesh
(shuffle-exchange + local capped ops under one jitted shard_map — the
GpuShuffleExchangeExec replacement, SURVEY.md §2.5/§5.8).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
from spark_rapids_jni_tpu.parallel.distributed import (
    broadcast_inner_join,
    distributed_groupby,
    distributed_inner_join,
    distributed_semi_join,
)


def _date_filter(t: Table, lo: int, hi: int) -> Table:
    mask = Column(
        jnp.logical_and(t["date_sk"].data >= lo, t["date_sk"].data < hi),
        dt.BOOL8,
        None,
    )
    return ops.filter_table(t, mask)


# ---------------------------------------------------------------------------
# q5-shape: channel union -> date window -> join item -> category rollup
# ---------------------------------------------------------------------------

def q5(tables: dict, date_lo: int = 100, date_hi: int = 200) -> Table:
    store = _date_filter(tables["store_sales"], date_lo, date_hi)
    web = _date_filter(tables["web_sales"], date_lo, date_hi)
    allsales = ops.concatenate([store, web])
    joined = ops.inner_join(allsales, tables["item"], ["item_sk"])
    rev = ops.mul(joined["quantity"], joined["sales_price"])
    with_rev = Table(
        [*joined.columns, rev], [*joined.names, "revenue"]
    )
    return ops.groupby_aggregate(
        with_rev,
        ["category_id"],
        [
            GroupbyAgg("revenue", "sum"),
            GroupbyAgg("net_profit", "sum"),
            GroupbyAgg("revenue", "count"),
        ],
    )


def q5_distributed(tables: dict, mesh, date_lo=100, date_hi=200):
    """Distributed q5: the union + filter happen per-shard inside the
    fact tables (cheap, embarrassingly parallel); the item dimension
    join is a BROADCAST hash join (the BroadcastHashJoinExec plan Spark
    picks for dimension tables — fact side stays sharded in place, zero
    fact rows cross the ICI); the aggregation shuffles by category."""
    store = _date_filter(tables["store_sales"], date_lo, date_hi)
    web = _date_filter(tables["web_sales"], date_lo, date_hi)
    allsales = _pad_to_mesh(ops.concatenate([store, web]), mesh)
    # padding rows carry _PAD_KEY, which matches no real item_sk — the
    # inner broadcast join drops them with no special handling
    joined_sh, counts = broadcast_inner_join(
        allsales, tables["item"], ["item_sk"], mesh
    )
    joined = _unpad_join(joined_sh, counts)
    rev = ops.mul(joined["quantity"], joined["sales_price"])
    with_rev = Table([*joined.columns, rev], [*joined.names, "revenue"])
    # pad rows to a multiple of the mesh size for sharding; the
    # ragged-compact exchange auto-plans its buffer from the real
    # per-destination totals (12 categories = maximal skew is fine)
    padded = _pad_to_mesh(with_rev, mesh)
    return distributed_groupby(
        padded,
        ["category_id"],
        [
            GroupbyAgg("revenue", "sum"),
            GroupbyAgg("net_profit", "sum"),
            GroupbyAgg("revenue", "count"),
        ],
        mesh,
    )


# ---------------------------------------------------------------------------
# q23-shape: frequent items CTE -> semi join -> per-customer spend
# ---------------------------------------------------------------------------

def q23(tables: dict, min_count: int = 4) -> Table:
    sales = tables["store_sales"]
    freq = ops.groupby_aggregate(
        sales, ["item_sk"], [GroupbyAgg("item_sk", "count")]
    )
    hot = ops.filter_table(
        freq,
        Column(freq["count_item_sk"].data >= min_count, dt.BOOL8, None),
    )
    hot_sales = ops.semi_join(sales, hot, ["item_sk"])
    spend = ops.mul(hot_sales["quantity"], hot_sales["sales_price"])
    t = Table([*hot_sales.columns, spend], [*hot_sales.names, "spend"])
    return ops.groupby_aggregate(
        t, ["customer_sk"], [GroupbyAgg("spend", "sum")]
    )


def q23_distributed(tables: dict, mesh, min_count: int = 4):
    sales = tables["store_sales"]
    # distributed frequent-item count (shuffle by item)
    sales_padded = _pad_to_mesh(sales, mesh)
    freq_padded, counts, _ = distributed_groupby(
        sales_padded,
        ["item_sk"],
        [GroupbyAgg("item_sk", "count")],
        mesh,
    )
    # gather the (small) hot-item list to every chip, host-side finish
    freq = unpad_groupby(freq_padded, counts)
    hot = ops.filter_table(
        freq,
        Column(freq["count_item_sk"].data >= min_count, dt.BOOL8, None),
    )
    # distributed LEFT SEMI against the hot-item list: both sides
    # hash-exchange by item over ICI, then membership lands in the
    # occupancy column of the exchanged shards (the compaction below is
    # a host-side convenience for the next stage)
    hot_pad = _pad_to_mesh(hot, mesh)
    sales_sh, occ, _, _ = distributed_semi_join(
        sales_padded, hot_pad, ["item_sk"], mesh
    )
    hot_sales = _unpad_occupancy(sales_sh, occ)
    spend = ops.mul(hot_sales["quantity"], hot_sales["sales_price"])
    t = Table([*hot_sales.columns, spend], [*hot_sales.names, "spend"])
    # customer_sk is uniform (~rows/20 distinct): the balanced default
    # capacity scales with the mesh instead of replicating the table
    t_padded = _pad_to_mesh(t, mesh)
    return distributed_groupby(
        t_padded, ["customer_sk"], [GroupbyAgg("spend", "sum")], mesh
    )


# ---------------------------------------------------------------------------
# q64-shape: chained dimension joins -> wide-key aggregation
# ---------------------------------------------------------------------------

def _price_cutoff(col, max_price: float):
    """Threshold in the column's own representation (decimal columns
    hold unscaled values: $150.00 at scale -2 is 15000)."""
    scale = col.dtype.scale if col.dtype.is_decimal else 0
    return max_price * (10 ** -scale)


def q64(tables: dict, max_price: float = 150.0) -> Table:
    sales = tables["store_sales"]
    item = tables["item"]
    cheap = ops.filter_table(
        item,
        Column(
            ops.compute.values(item["current_price"])
            <= _price_cutoff(item["current_price"], max_price),
            dt.BOOL8,
            None,
        ),
    )
    j1 = ops.inner_join(sales, cheap, ["item_sk"])
    j2 = ops.inner_join(j1, tables["customer"], ["customer_sk"])
    j3 = ops.inner_join(j2, tables["date_dim"], ["date_sk"])
    rev = ops.mul(j3["quantity"], j3["sales_price"])
    t = Table([*j3.columns, rev], [*j3.names, "revenue"])
    return ops.groupby_aggregate(
        t,
        ["brand_id", "state_id", "year"],
        [GroupbyAgg("revenue", "sum"), GroupbyAgg("revenue", "count")],
    )


def q64_distributed(tables: dict, mesh, max_price: float = 150.0):
    """Distributed q64: the big fact-fact-shaped join (sales x customer)
    shuffles both sides; the small dimension joins (filtered item,
    date_dim) are broadcast hash joins — the fact side never crosses
    the ICI for them."""
    sales = tables["store_sales"]
    item = tables["item"]
    cheap = ops.filter_table(
        item,
        Column(
            ops.compute.values(item["current_price"])
            <= _price_cutoff(item["current_price"], max_price),
            dt.BOOL8,
            None,
        ),
    )
    j1_sh, j1_counts = broadcast_inner_join(
        _pad_to_mesh(sales, mesh), cheap, ["item_sk"], mesh
    )
    j1 = _unpad_join(j1_sh, j1_counts)
    lpad = _pad_to_mesh(j1, mesh)
    rpad = _pad_to_mesh(tables["customer"], mesh)
    num = int(np.prod(list(mesh.shape.values())))
    # customer_sk is unique on the right, so per-device real matches are
    # bounded by the left rows received (<= lpad.row_count); pad rows
    # share _PAD_KEY on both sides and cross-join on one device, adding
    # at most (num-1)^2 pairs
    # exchange capacities auto-plan (lossless); an undersized explicit
    # out_capacity would raise rather than silently corrupt the result
    joined, counts, lov, rov = distributed_inner_join(
        lpad,
        rpad,
        ["customer_sk"],
        mesh,
        out_capacity=lpad.row_count + (num - 1) ** 2,
    )
    out = _unpad_join(joined, counts)
    j3_sh, j3_counts = broadcast_inner_join(
        _pad_to_mesh(out, mesh), tables["date_dim"], ["date_sk"], mesh
    )
    j3 = _unpad_join(j3_sh, j3_counts)
    rev = ops.mul(j3["quantity"], j3["sales_price"])
    t = Table([*j3.columns, rev], [*j3.names, "revenue"])
    return ops.groupby_aggregate(
        t,
        ["brand_id", "state_id", "year"],
        [GroupbyAgg("revenue", "sum"), GroupbyAgg("revenue", "count")],
    )


# ---------------------------------------------------------------------------
# padding helpers (mesh sharding wants row_count % devices == 0; padding
# rows carry a key no real row uses so they aggregate separately and are
# dropped on unpad)
# ---------------------------------------------------------------------------

_PAD_KEY = np.int64(-(2**62))


def _pad_to_mesh(table: Table, mesh) -> Table:
    num = int(np.prod(list(mesh.shape.values())))
    n = table.row_count
    rem = (-n) % num
    if rem == 0:
        return table
    pad_cols = []
    for c in table.columns:
        if c.dtype.is_string:
            # empty-string padding rows (zero bytes, zero lengths)
            data = jnp.zeros((rem, c.data.shape[1]), jnp.uint8)
            pad_cols.append(
                Column(data, c.dtype, None, jnp.zeros((rem,), jnp.int32))
            )
            continue
        fill_vals = jnp.full(
            (rem,) + tuple(c.data.shape[1:]), _PAD_KEY
        ).astype(c.data.dtype)
        pad_cols.append(Column(fill_vals, c.dtype, None))
    pad = Table(pad_cols, list(table.names))
    return ops.concatenate([table, pad])


def _real_mask(table: Table):
    """Per-row bool: not a _PAD_KEY padding row (keyed off the first
    column, which _pad_to_mesh fills with the sentinel)."""
    return table.columns[0].data != jnp.asarray(
        _PAD_KEY, table.columns[0].data.dtype
    )


def unpad_groupby(padded: Table, counts) -> Table:
    """Compact the sharded padded result: keep each device's first
    count rows, drop padding groups (the _PAD_KEY key). Device-side
    filter so storage encodings (FLOAT64 bit patterns) stay intact."""
    cnt = jnp.asarray(counts).reshape(-1)
    n_dev = cnt.shape[0]
    per = padded.row_count // n_dev
    slot = jnp.arange(padded.row_count, dtype=jnp.int32)
    occupied = (slot % per) < cnt[slot // per]
    mask = Column(
        jnp.logical_and(occupied, _real_mask(padded)), dt.BOOL8, None
    )
    return ops.filter_table(padded, mask)


def _unpad_join(padded: Table, counts) -> Table:
    """Same shard-stacking for distributed join output."""
    return unpad_groupby(padded, counts)


def _unpad_occupancy(sharded: Table, occ) -> Table:
    """Compact a padded-shard result by its occupancy column (the
    semi/anti join convention), dropping _PAD_KEY padding rows too."""
    mask = Column(
        jnp.logical_and(jnp.asarray(occ), _real_mask(sharded)),
        dt.BOOL8,
        None,
    )
    return ops.filter_table(sharded, mask)


# compat alias: tests and older call sites used the private name
_unpad_groupby = unpad_groupby
