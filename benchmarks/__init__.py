"""TPC-DS-shaped benchmark suite (BASELINE.json configs 3-5).

The reference publishes no benchmark numbers (SURVEY.md §6); the
driver-set north star is TPC-DS-style relational work: single-chip
joins (config 3) and q5/q23/q64-shaped distributed queries over the
shuffle exchange (configs 4-5). This package provides the synthetic
star-schema generator, the query implementations (single-chip and
mesh-distributed), and a JSON-line runner — the measured baseline the
reference never recorded.
"""
