"""TPC-DS-class real-data benchmark: seeded dbgen-equivalent to Parquet
plus scan-driven q5/q23/q64 pipelines with pandas oracles.

Round-4 VERDICT item 6: the in-memory DAGs in benchmarks/queries.py
prove operator shapes, but BASELINE.json configs 4-5 call for REAL
Parquet scans — decimals, strings, nulls, row-group streaming — feeding
shuffle/join/agg. This module is that end-to-end path:

  generate_parquet  spec-inspired star schema (store_sales, web_sales,
                    item, customer, date_dim) at a scale factor:
                    SF 1 ~ 2.88M store_sales rows (the TPC-DS ratio),
                    DECIMAL(7,2) money columns, nullable FKs (~4%, like
                    dbgen), string dimension attributes.
  q5_stream         channel union -> date-window pushdown -> item join
                    -> category rollup, streamed per row group.
  q23_stream        frequent-item CTE over store_sales -> semi join of
                    web_sales -> per-customer aggregation.
  q64_stream        store_sales -> item (price filter) -> customer ->
                    wide-key aggregation.
  oracle_*          the same queries in pandas/pyarrow on the same
                    files; run_all() compares counts exactly and money
                    totals at float64 precision (sums in cents stay
                    under 2^53 through SF100, so this is exact too).

Streaming model: dimensions load resident (they are the small side;
the reference broadcasts them, GpuBroadcastHashJoinExec), fact batches
arrive via io.parquet.scan_parquet with predicate pushdown + prefetch,
each batch joins + partially aggregates on device, and one final
groupby combines the partials — the two-level shape the chunked
groupby (ops/groupby_chunked.py) uses, applied across IO batches.
"""

from __future__ import annotations

import os
import time

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.io.parquet import read_parquet, scan_parquet
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg, groupby_aggregate

# spec row-count ratios (TPC-DS dbgen at SF1, rounded)
_SS_PER_SF = 2_880_000
_WS_PER_SF = 720_000
_CUST_PER_SF = 100_000
_ITEM_SF1 = 18_000
_N_DATES = 73_049  # 1900..2100, the fixed TPC-DS calendar


def _money(rng, n, lo=50, hi=20_000):
    """DECIMAL(7,2) money as unscaled cents."""
    return rng.integers(lo, hi, n, dtype=np.int64)


def generate_parquet(out_dir: str, scale: float = 0.01, seed: int = 0):
    """Write the star schema to ``out_dir``; returns a manifest dict."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_ss = max(int(_SS_PER_SF * scale), 1000)
    n_ws = max(int(_WS_PER_SF * scale), 250)
    n_cust = max(int(_CUST_PER_SF * scale), 100)
    n_item = max(int(_ITEM_SF1 * max(scale, 1) ** 0.5), 100)
    os.makedirs(out_dir, exist_ok=True)
    money = pa.decimal128(7, 2)

    def write(name, table, row_group_rows):
        pq.write_table(
            table, os.path.join(out_dir, f"{name}.parquet"),
            row_group_size=row_group_rows,
        )

    # date_dim: dense sk, year/moy derivable from sk
    d_sk = np.arange(_N_DATES, dtype=np.int64)
    write(
        "date_dim",
        pa.table({
            "d_date_sk": d_sk,
            "d_year": 1900 + d_sk // 365,
            "d_moy": (d_sk % 365) // 31 + 1,
        }),
        _N_DATES,
    )

    # item: skewed brand/category, string attributes, decimal price
    i_sk = np.arange(n_item, dtype=np.int64)
    write(
        "item",
        pa.table({
            "i_item_sk": i_sk,
            "i_item_id": pa.array(
                [f"AAAAAAAA{i:08d}" for i in range(n_item)]
            ),
            "i_brand_id": rng.integers(1, 1000, n_item),
            "i_category_id": rng.integers(1, 11, n_item),
            "i_brand": pa.array(
                [f"brand#{int(b):03d}" for b in rng.integers(0, 200, n_item)]
            ),
            "i_category": pa.array(
                [
                    ["Books", "Home", "Electronics", "Jewelry", "Men",
                     "Music", "Shoes", "Sports", "Children", "Women"][c]
                    for c in rng.integers(0, 10, n_item)
                ]
            ),
            "i_current_price": pa.array(
                _money(rng, n_item) / 100.0
            ).cast(money),
        }),
        max(n_item, 1024),
    )

    # customer: nullable names/birth year (dbgen leaves ~3% null)
    c_sk = np.arange(n_cust, dtype=np.int64)
    first = rng.integers(0, 512, n_cust)
    last = rng.integers(0, 2048, n_cust)
    name_null = rng.random(n_cust) < 0.03
    write(
        "customer",
        pa.table({
            "c_customer_sk": c_sk,
            "c_first_name": pa.array(
                [None if m else f"F{v:03d}" for m, v in zip(name_null, first)]
            ),
            "c_last_name": pa.array(
                [None if m else f"L{v:04d}" for m, v in zip(name_null, last)]
            ),
            "c_birth_year": pa.array(
                np.where(rng.random(n_cust) < 0.03, -1,
                         rng.integers(1930, 2005, n_cust))
            ).cast(pa.int64()),
            # ca_state folded onto customer (spec keeps it on the
            # customer_address dimension; one less table, same join/agg
            # shape for the q64 group-by)
            "c_state_id": rng.integers(0, 50, n_cust),
        }),
        max(n_cust, 4096),
    )

    def fact(n):
        # zipf item popularity: the join/shuffle skew that matters
        item_fk = (rng.zipf(1.2, n) - 1) % n_item
        cust_null = rng.random(n) < 0.04  # dbgen null FK rate
        cust_fk = rng.integers(0, n_cust, n)
        return pa.table({
            "sold_date_sk": rng.integers(0, _N_DATES, n),
            "item_sk": item_fk.astype(np.int64),
            "customer_sk": pa.array(cust_fk, mask=cust_null),
            "quantity": rng.integers(1, 100, n),
            "sales_price": pa.array(_money(rng, n) / 100.0).cast(money),
            "ext_sales_price": pa.array(
                _money(rng, n, 100, 3_000_000) / 100.0
            ).cast(money),
            "net_profit": pa.array(
                rng.integers(-500_000, 1_200_000, n) / 100.0
            ).cast(money),
        })

    rg = 1 << 19  # ~512k-row groups: the streaming batch unit
    write("store_sales", fact(n_ss), rg)
    write("web_sales", fact(n_ws), rg)
    return {
        "dir": out_dir, "scale": scale, "store_sales": n_ss,
        "web_sales": n_ws, "item": n_item, "customer": n_cust,
    }


# ---------------------------------------------------------------------------
# streamed queries (scan -> join -> agg)
# ---------------------------------------------------------------------------


def _combine_partials(partials, by, agg_specs):
    whole = ops.concatenate(partials) if len(partials) > 1 else partials[0]
    return groupby_aggregate(whole, by, agg_specs)


_DATE_LO, _DATE_HI = 36_000, 36_730  # a 2-year window in the calendar


def q5_stream(data_dir: str, prefetch: int = 2) -> Table:
    """Channel union -> date pushdown -> item join -> category rollup."""
    from spark_rapids_jni_tpu.io.predicates import col as C

    item = read_parquet(
        os.path.join(data_dir, "item.parquet"),
        columns=["i_item_sk", "i_category_id"],
    )
    pred = (C("sold_date_sk") >= _DATE_LO) & (C("sold_date_sk") < _DATE_HI)
    partials = []
    for name in ("store_sales", "web_sales"):
        for batch in scan_parquet(
            os.path.join(data_dir, f"{name}.parquet"),
            columns=["sold_date_sk", "item_sk", "ext_sales_price",
                     "net_profit"],
            filters=pred,
            prefetch=prefetch,
        ):
            joined = ops.inner_join(
                batch, item, ["item_sk"], ["i_item_sk"]
            )
            partials.append(
                groupby_aggregate(
                    joined, ["i_category_id"],
                    [GroupbyAgg("ext_sales_price", "sum", "sales"),
                     GroupbyAgg("net_profit", "sum", "profit"),
                     GroupbyAgg("item_sk", "count", "n")],
                )
            )
    return _combine_partials(
        partials, ["i_category_id"],
        [GroupbyAgg("sales", "sum", "sales"),
         GroupbyAgg("profit", "sum", "profit"),
         GroupbyAgg("n", "sum", "n")],
    )


def q23_stream(data_dir: str, min_count: int = 50, prefetch: int = 2) -> Table:
    """Frequent-item CTE -> semi join -> per-customer aggregation."""
    # pass 1: item frequency over store_sales
    partials = []
    for batch in scan_parquet(
        os.path.join(data_dir, "store_sales.parquet"),
        columns=["item_sk"],
        prefetch=prefetch,
    ):
        partials.append(
            groupby_aggregate(
                batch, ["item_sk"], [GroupbyAgg("item_sk", "count", "n")]
            )
        )
    freq = _combine_partials(
        partials, ["item_sk"], [GroupbyAgg("n", "sum", "n")]
    )
    hot_mask = Column(freq["n"].data >= min_count, dt.BOOL8, None)
    hot = ops.filter_table(freq, hot_mask)

    # pass 2: web_sales rows on frequent items -> customer totals
    partials = []
    for batch in scan_parquet(
        os.path.join(data_dir, "web_sales.parquet"),
        columns=["item_sk", "customer_sk", "sales_price"],
        prefetch=prefetch,
    ):
        kept = ops.semi_join(batch, hot, ["item_sk"])
        partials.append(
            groupby_aggregate(
                kept, ["customer_sk"],
                [GroupbyAgg("sales_price", "sum", "total")],
            )
        )
    return _combine_partials(
        partials, ["customer_sk"], [GroupbyAgg("total", "sum", "total")]
    )


def q64_stream(
    data_dir: str, max_price: float = 50.0, prefetch: int = 2
) -> Table:
    """store_sales -> item(price<cap) -> customer -> (brand, birth_year)."""
    item = read_parquet(
        os.path.join(data_dir, "item.parquet"),
        columns=["i_item_sk", "i_brand_id", "i_current_price"],
    )
    # DECIMAL(7,2) predicate on the unscaled cents (exact, no decode)
    unscaled_cap = int(round(max_price * 100))
    keep = Column(
        item["i_current_price"].data < unscaled_cap, dt.BOOL8, None
    )
    item = ops.filter_table(item, keep)
    customer = read_parquet(
        os.path.join(data_dir, "customer.parquet"),
        columns=["c_customer_sk", "c_birth_year"],
    )
    partials = []
    for batch in scan_parquet(
        os.path.join(data_dir, "store_sales.parquet"),
        columns=["item_sk", "customer_sk", "ext_sales_price"],
        prefetch=prefetch,
    ):
        j1 = ops.inner_join(batch, item, ["item_sk"], ["i_item_sk"])
        j2 = ops.inner_join(
            j1, customer, ["customer_sk"], ["c_customer_sk"]
        )
        partials.append(
            groupby_aggregate(
                j2, ["i_brand_id", "c_birth_year"],
                [GroupbyAgg("ext_sales_price", "sum", "sales"),
                 GroupbyAgg("item_sk", "count", "n")],
            )
        )
    return _combine_partials(
        partials, ["i_brand_id", "c_birth_year"],
        [GroupbyAgg("sales", "sum", "sales"), GroupbyAgg("n", "sum", "n")],
    )


# ---------------------------------------------------------------------------
# pandas oracles (same files, same predicates)
# ---------------------------------------------------------------------------


_MONEY_COLS = {
    "sales_price", "ext_sales_price", "net_profit", "i_current_price",
}


def _read_pd(data_dir, name, columns):
    import pyarrow as pa
    import pyarrow.parquet as pq

    t = pq.read_table(os.path.join(data_dir, f"{name}.parquet"),
                      columns=columns)
    # decimal -> float64 for the oracle (sums in cents stay < 2^53)
    t = pa.table(
        {
            c: (t[c].cast(pa.float64()) if c in _MONEY_COLS else t[c])
            for c in t.column_names
        }
    )
    return t.to_pandas()


def oracle_q5(data_dir):
    import pandas as pd

    item = _read_pd(data_dir, "item", ["i_item_sk", "i_category_id"])
    frames = []
    for name in ("store_sales", "web_sales"):
        df = _read_pd(
            data_dir, name,
            ["sold_date_sk", "item_sk", "ext_sales_price", "net_profit"],
        )
        df = df[(df.sold_date_sk >= _DATE_LO) & (df.sold_date_sk < _DATE_HI)]
        frames.append(df)
    fact = pd.concat(frames).merge(
        item, left_on="item_sk", right_on="i_item_sk"
    )
    return (
        fact.groupby("i_category_id")
        .agg(sales=("ext_sales_price", "sum"),
             profit=("net_profit", "sum"), n=("item_sk", "count"))
        .reset_index()
    )


def oracle_q23(data_dir, min_count: int = 50):
    ss = _read_pd(data_dir, "store_sales", ["item_sk"])
    hot = ss.groupby("item_sk").size()
    hot = set(hot[hot >= min_count].index)
    ws = _read_pd(
        data_dir, "web_sales", ["item_sk", "customer_sk", "sales_price"]
    )
    hot_ws = ws[ws.item_sk.isin(hot)]
    kept = hot_ws.dropna(subset=["customer_sk"])
    # ours groups null customer keys too; pandas dropna covers the
    # non-null groups, the null group's total is verified separately
    out = kept.groupby("customer_sk").sales_price.sum().reset_index()
    null_sum = float(hot_ws[hot_ws.customer_sk.isna()].sales_price.sum())
    return out, null_sum


def oracle_q64(data_dir, max_price: float = 50.0):
    item = _read_pd(
        data_dir, "item", ["i_item_sk", "i_brand_id", "i_current_price"]
    )
    item = item[item.i_current_price.astype(float) < max_price]
    cust = _read_pd(data_dir, "customer", ["c_customer_sk", "c_birth_year"])
    ss = _read_pd(
        data_dir, "store_sales", ["item_sk", "customer_sk", "ext_sales_price"]
    )
    j = (
        ss.dropna(subset=["customer_sk"])
        .merge(item, left_on="item_sk", right_on="i_item_sk")
        .merge(cust, left_on="customer_sk", right_on="c_customer_sk")
    )
    return (
        j.groupby(["i_brand_id", "c_birth_year"])
        .agg(sales=("ext_sales_price", "sum"), n=("item_sk", "count"))
        .reset_index()
    )


def load_tables(data_dir: str) -> dict:
    """Load the Parquet star schema into the in-memory column names the
    benchmarks/queries.py DAGs (and their distributed variants) expect —
    the bridge between this module's real files and the mesh pipelines."""
    ss = read_parquet(
        os.path.join(data_dir, "store_sales.parquet"),
        columns=["item_sk", "customer_sk", "sold_date_sk", "quantity",
                 "sales_price", "net_profit"],
    )
    ws = read_parquet(
        os.path.join(data_dir, "web_sales.parquet"),
        columns=["item_sk", "customer_sk", "sold_date_sk", "quantity",
                 "sales_price", "net_profit"],
    )

    def rename(t, names):
        return Table(list(t.columns), names)

    fact_names = ["item_sk", "customer_sk", "date_sk", "quantity",
                  "sales_price", "net_profit"]
    item = read_parquet(
        os.path.join(data_dir, "item.parquet"),
        columns=["i_item_sk", "i_brand_id", "i_category_id",
                 "i_current_price", "i_brand"],
    )
    customer = read_parquet(
        os.path.join(data_dir, "customer.parquet"),
        columns=["c_customer_sk", "c_birth_year", "c_state_id"],
    )
    date_dim = read_parquet(os.path.join(data_dir, "date_dim.parquet"))
    return {
        "store_sales": rename(ss, fact_names),
        "web_sales": rename(ws, fact_names),
        "item": rename(
            item,
            ["item_sk", "brand_id", "category_id", "current_price", "brand"],
        ),
        "customer": rename(
            customer, ["customer_sk", "birth_year", "state_id"]
        ),
        "date_dim": rename(date_dim, ["date_sk", "year", "moy"]),
    }


def run_distributed(data_dir: str, devices: int) -> list[dict]:
    """q5/q23/q64 distributed DAGs over an N-device mesh, fed from the
    Parquet files (scan -> shuffle-exchange -> join -> agg): the
    BASELINE config-4 shape with real data instead of in-memory
    synthetics."""
    from benchmarks import queries
    from spark_rapids_jni_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(devices)
    tables = load_tables(data_dir)
    out = []
    runs = [
        ("q5", lambda: queries.q5_distributed(
            tables, mesh, date_lo=_DATE_LO, date_hi=_DATE_HI)),
        ("q23", lambda: queries.q23_distributed(tables, mesh, min_count=50)),
        ("q64", lambda: queries.q64_distributed(tables, mesh)),
    ]
    for name, fn in runs:
        fn()  # compile warmup
        t0 = time.perf_counter()
        r = fn()
        leaf = r[0] if isinstance(r, tuple) else r
        np.asarray(leaf.columns[0].data.ravel()[-1:])
        out.append(
            {"name": f"tpcds_{name}_mesh{devices}",
             "seconds": round(time.perf_counter() - t0, 3),
             "devices": devices}
        )
    return out


def _dec_to_float(col: Column) -> np.ndarray:
    vals = np.asarray(col.to_numpy(), dtype=np.float64)
    if col.dtype.is_decimal:
        vals = vals * (10.0 ** col.dtype.scale)
    return vals


def run_all(data_dir: str, prefetch: int = 2) -> list[dict]:
    """Run the three pipelines; wall-clock + oracle verdicts."""
    results = []

    t0 = time.perf_counter()
    q5 = q5_stream(data_dir, prefetch)
    np.asarray(q5.columns[1].data.ravel()[-1:])  # force
    q5_s = time.perf_counter() - t0
    o5 = oracle_q5(data_dir)
    order = np.argsort(np.asarray(q5["i_category_id"].to_numpy()))
    ok5 = (
        q5.row_count == len(o5)
        and np.allclose(
            _dec_to_float(q5["sales"])[order],
            o5.sort_values("i_category_id")["sales"].to_numpy(np.float64),
        )
        and np.array_equal(
            np.asarray(q5["n"].to_numpy())[order],
            o5.sort_values("i_category_id")["n"].to_numpy(np.int64),
        )
    )
    results.append(
        {"name": "tpcds_q5_stream", "seconds": round(q5_s, 3),
         "groups": q5.row_count, "oracle_match": bool(ok5)}
    )

    t0 = time.perf_counter()
    q23 = q23_stream(data_dir)
    np.asarray(q23.columns[1].data.ravel()[-1:])
    q23_s = time.perf_counter() - t0
    o23, null_sum = oracle_q23(data_dir)
    kk = q23["customer_sk"]
    nonnull = (
        np.ones(q23.row_count, bool)
        if kk.validity is None
        else np.asarray(kk.validity)
    )
    totals = _dec_to_float(q23["total"])
    got_tot = totals[nonnull].sum()
    got_null = totals[~nonnull].sum()  # exactly one null-key group
    ok23 = (
        int(nonnull.sum()) == len(o23)
        and int((~nonnull).sum()) <= 1
        and np.isclose(got_tot, o23.sales_price.sum())
        and np.isclose(got_null, null_sum)
    )
    results.append(
        {"name": "tpcds_q23_stream", "seconds": round(q23_s, 3),
         "groups": q23.row_count, "oracle_match": bool(ok23)}
    )

    t0 = time.perf_counter()
    q64 = q64_stream(data_dir)
    np.asarray(q64.columns[2].data.ravel()[-1:])
    q64_s = time.perf_counter() - t0
    o64 = oracle_q64(data_dir)
    ok64 = q64.row_count == len(o64) and np.isclose(
        _dec_to_float(q64["sales"]).sum(), o64.sales.sum()
    )
    results.append(
        {"name": "tpcds_q64_stream", "seconds": round(q64_s, 3),
         "groups": q64.row_count, "oracle_match": bool(ok64)}
    )
    return results
