"""Synthetic TPC-DS-like star schema at a row-count scale.

Shapes mirror the tables q5/q23/q64 touch (store_sales, web_sales,
item, customer, date_dim) with the key distributions that matter for
the ops under test: skewed fact keys, dense dimension keys, date
windows. Pure numpy; upload happens in Table.from_pydict.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table


def generate(sales_rows: int = 100_000, seed: int = 0) -> dict:
    """Star schema sized off the fact-table row count.

    items ~ rows/50, customers ~ rows/20, dates = 2 years daily.
    """
    rng = np.random.default_rng(seed)
    n_items = max(sales_rows // 50, 8)
    n_cust = max(sales_rows // 20, 8)
    n_dates = 730

    # Zipf-ish item popularity: the skew that stresses hash partitioning
    item_pop = rng.zipf(1.3, sales_rows) % n_items

    def fact(n):
        # TPC-DS money columns are DECIMAL(7,2): unscaled cents carried
        # at scale -2 (the representation the reference round-trips,
        # RowConversionTest.java:37-38), not floats
        return Table(
            [
                Column.from_numpy(item_pop[:n].astype(np.int64)),
                Column.from_numpy(
                    rng.integers(0, n_cust, n, dtype=np.int64)
                ),
                Column.from_numpy(
                    rng.integers(0, n_dates, n, dtype=np.int64)
                ),
                Column.from_numpy(
                    rng.integers(1, 100, n, dtype=np.int64)
                ),
                Column.from_numpy(
                    rng.integers(50, 30_000, n, dtype=np.int64),
                    dtype=dt.decimal64(-2),
                ),
                Column.from_numpy(
                    rng.integers(-5_000, 12_000, n, dtype=np.int64),
                    dtype=dt.decimal64(-2),
                ),
            ],
            ["item_sk", "customer_sk", "date_sk", "quantity",
             "sales_price", "net_profit"],
        )

    store_sales = fact(sales_rows)
    web_sales = fact(max(sales_rows // 4, 8))

    item = Table(
        [
            Column.from_numpy(np.arange(n_items, dtype=np.int64)),
            Column.from_numpy(
                rng.integers(0, 100, n_items, dtype=np.int64)
            ),
            Column.from_numpy(
                rng.integers(0, 12, n_items, dtype=np.int64)
            ),
            Column.from_numpy(
                rng.integers(50, 30_000, n_items, dtype=np.int64),
                dtype=dt.decimal64(-2),
            ),
            # string dimension attribute: rides joins and the shuffle
            Column.from_strings(
                [f"brand#{i % 100:02d}" for i in range(n_items)]
            ),
        ],
        ["item_sk", "brand_id", "category_id", "current_price", "brand"],
    )
    customer = {
        "customer_sk": np.arange(n_cust, dtype=np.int64),
        "birth_year": rng.integers(1930, 2005, n_cust, dtype=np.int64),
        "state_id": rng.integers(0, 50, n_cust, dtype=np.int64),
    }
    date_dim = {
        "date_sk": np.arange(n_dates, dtype=np.int64),
        "year": 2000 + np.arange(n_dates, dtype=np.int64) // 365,
        "moy": (np.arange(n_dates, dtype=np.int64) // 30) % 12 + 1,
    }
    return {
        "store_sales": store_sales,
        "web_sales": web_sales,
        "item": item,
        "customer": Table.from_pydict(customer),
        "date_dim": Table.from_pydict(date_dim),
    }
