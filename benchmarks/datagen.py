"""Synthetic TPC-DS-like star schema at a row-count scale.

Shapes mirror the tables q5/q23/q64 touch (store_sales, web_sales,
item, customer, date_dim) with the key distributions that matter for
the ops under test: skewed fact keys, dense dimension keys, date
windows. Pure numpy; upload happens in Table.from_pydict.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_jni_tpu.column import Table


def generate(sales_rows: int = 100_000, seed: int = 0) -> dict:
    """Star schema sized off the fact-table row count.

    items ~ rows/50, customers ~ rows/20, dates = 2 years daily.
    """
    rng = np.random.default_rng(seed)
    n_items = max(sales_rows // 50, 8)
    n_cust = max(sales_rows // 20, 8)
    n_dates = 730

    # Zipf-ish item popularity: the skew that stresses hash partitioning
    item_pop = rng.zipf(1.3, sales_rows) % n_items

    def fact(n):
        return {
            "item_sk": item_pop[:n].astype(np.int64),
            "customer_sk": rng.integers(0, n_cust, n, dtype=np.int64),
            "date_sk": rng.integers(0, n_dates, n, dtype=np.int64),
            "quantity": rng.integers(1, 100, n, dtype=np.int64),
            "sales_price": np.round(rng.uniform(0.5, 300.0, n), 2),
            "net_profit": np.round(rng.uniform(-50.0, 120.0, n), 2),
        }

    store_sales = fact(sales_rows)
    web_sales = fact(max(sales_rows // 4, 8))

    item = {
        "item_sk": np.arange(n_items, dtype=np.int64),
        "brand_id": rng.integers(0, 100, n_items, dtype=np.int64),
        "category_id": rng.integers(0, 12, n_items, dtype=np.int64),
        "current_price": np.round(rng.uniform(0.5, 300.0, n_items), 2),
    }
    customer = {
        "customer_sk": np.arange(n_cust, dtype=np.int64),
        "birth_year": rng.integers(1930, 2005, n_cust, dtype=np.int64),
        "state_id": rng.integers(0, 50, n_cust, dtype=np.int64),
    }
    date_dim = {
        "date_sk": np.arange(n_dates, dtype=np.int64),
        "year": 2000 + np.arange(n_dates, dtype=np.int64) // 365,
        "moy": (np.arange(n_dates, dtype=np.int64) // 30) % 12 + 1,
    }
    return {
        "store_sales": Table.from_pydict(store_sales),
        "web_sales": Table.from_pydict(web_sales),
        "item": Table.from_pydict(item),
        "customer": Table.from_pydict(customer),
        "date_dim": Table.from_pydict(date_dim),
    }
