"""Benchmark runner: one JSON line per configuration.

Usage:
  python -m benchmarks.run [--rows N] [--devices D] [--configs 3,4]

Config 3 (single-chip joins/queries) runs on the default device (the
real TPU under the driver). Config 4 (distributed q5/q23/q64) needs a
multi-device mesh — on a one-chip box, run with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
to exercise the shuffle path; the numbers are then CPU-simulation
numbers and are labeled as such.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax

from . import datagen, queries


def _time(fn, *args, repeats=1):
    out = fn(*args)  # warmup/compile (eager queries cache per-shape)
    jax.block_until_ready(jax.tree.leaves(out))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(jax.tree.leaves(out))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--devices", type=int, default=0,
                    help="mesh size for distributed configs (0 = skip)")
    ap.add_argument("--configs", default="3")
    ap.add_argument("--repeats", type=int, default=1)
    args = ap.parse_args()
    configs = {c.strip() for c in args.configs.split(",")}
    unknown = configs - {"3", "4", "skew"}
    if unknown:
        raise SystemExit(
            f"unknown configs {sorted(unknown)}: this runner implements 3 "
            "(single-chip), 4 (distributed) and skew (distributed zipf "
            "groupby at 1e7 rows); config 5 is config 4 at full scale on "
            "real hardware"
        )
    if "skew" in configs and not args.devices:
        raise SystemExit("--configs skew needs --devices N")
    if "4" in configs and not args.devices:
        raise SystemExit("--configs 4 needs --devices N")

    # Platform forcing must happen after argparse (so abbreviations like
    # --device work) but before anything touches the backend. Explicit
    # "cpu": the env pins JAX_PLATFORMS to the TPU plugin and overrides
    # don't stick (see tests/conftest.py), so on a one-chip box a
    # multi-device run means the forced host platform.
    if args.devices and "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""
    ):
        jax.config.update("jax_platforms", "cpu")

    # Persistent compilation cache: the eager query DAGs compile dozens
    # of per-shape executables; caching makes repeat runs start hot.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "SRT_COMPILE_CACHE", os.path.expanduser("~/.cache/srt-xla")
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    tables = datagen.generate(args.rows)
    platform = jax.devices()[0].platform

    if "3" in configs:
        for name, fn in [("q5", queries.q5), ("q23", queries.q23),
                         ("q64", queries.q64)]:
            secs = _time(fn, tables, repeats=args.repeats)
            print(json.dumps({
                "config": 3, "query": name, "rows": args.rows,
                "seconds": round(secs, 4),
                "rows_per_sec": round(args.rows / secs),
                "platform": platform,
            }))

    if "skew" in configs:
        # Round-3 VERDICT item 5: the r2 skew-OOM shape at real size.
        # Zipf(1.3) keys over >=1e7 rows through the ragged-compact
        # exchange; records wall-clock, the per-device received-buffer
        # rows (must track the hot partition's REAL total, not
        # P x the hottest pair), and peak RSS.
        import resource

        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
        from spark_rapids_jni_tpu.parallel import distributed_groupby
        from spark_rapids_jni_tpu.parallel.mesh import make_mesh

        from spark_rapids_jni_tpu.utils import config as srt_config
        from spark_rapids_jni_tpu.utils import metrics as srt_metrics

        srt_config.set_flag("METRICS", "1")
        n = max(args.rows, 10_000_000)
        n -= n % args.devices
        rng = np.random.default_rng(5)
        k = np.minimum(rng.zipf(1.3, n), 100_000).astype(np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        t = Table.from_pydict({"k": k, "v": v})
        mesh = make_mesh(args.devices)

        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
        distributed_groupby(t, ["k"], aggs, mesh)  # compile warmup
        t0 = time.perf_counter()
        agg, ngroups, overflow = distributed_groupby(t, ["k"], aggs, mesh)
        total_groups = int(np.asarray(ngroups).sum())
        secs = time.perf_counter() - t0
        hot = int(np.asarray(agg["count_v"].data).max())
        buf_rows = int(agg["k"].data.shape[0]) // args.devices
        peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
        assert int(np.asarray(overflow).max()) <= 0
        want_groups = len(np.unique(k))
        assert total_groups == want_groups, (total_groups, want_groups)
        # destination balance after planning: exact planned recv totals
        # when the adaptive splitter fired (gauges), else derived from
        # the raw key distribution (hash skew the planner saw)
        snap = srt_metrics.snapshot()
        gauges = snap.get("gauges") or {}
        splits = int((snap.get("counters") or {}).get(
            "shuffle.skew_splits", 0))

        def _gauge(name):
            g = gauges.get(name)
            return None if g is None else float(g.get("value", 0.0))

        post_ratio = _gauge("shuffle.skew_post_ratio_x100")
        recv_max = _gauge("shuffle.skew_recv_after")
        if splits and post_ratio is not None:
            max_over_mean = post_ratio / 100.0
        else:
            from spark_rapids_jni_tpu.ops.partition import (
                partition_ids_hash,
            )

            pids = np.asarray(partition_ids_hash(t, ["k"], args.devices))
            dest_rows = np.bincount(pids, minlength=args.devices)
            max_over_mean = float(dest_rows.max() / dest_rows.mean())
            recv_max = float(dest_rows.max())
        print(json.dumps({
            "config": "4-skew", "rows": n, "devices": args.devices,
            "seconds": round(secs, 3), "groups": total_groups,
            "hot_key_rows": hot, "recv_buffer_rows_per_device": buf_rows,
            "peak_rss_mb": peak_mb, "platform": platform,
            "skew_splits": splits,
            "max_recv_rows": None if recv_max is None else int(recv_max),
            "max_over_mean": round(max_over_mean, 3),
        }))

    if "4" in configs and args.devices:
        from spark_rapids_jni_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.devices)
        for name, fn in [
            ("q5", queries.q5_distributed),
            ("q23", queries.q23_distributed),
            ("q64", queries.q64_distributed),
        ]:
            secs = _time(fn, tables, mesh, repeats=args.repeats)
            print(json.dumps({
                "config": 4, "query": name, "rows": args.rows,
                "devices": args.devices, "seconds": round(secs, 4),
                "rows_per_sec": round(args.rows / secs),
                "platform": platform,
            }))


if __name__ == "__main__":
    main()
