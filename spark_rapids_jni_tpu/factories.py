"""Column factories & utilities — the cudf factory/primitive surface.

TPU-native equivalents of the cudf factories and utilities the reference
binds to (SURVEY.md §2.3 "Column factories & utilities":
``make_fixed_width_column`` / ``make_numeric_column`` at
row_conversion.cu:392-394,551-552, ``cudf::detail::sequence`` at :390,
scalars at :494-502, plus the copying/reshape family the vendored cudf
Java test suite exercises: concatenate, slice/split, interleave).

All constructors return device-resident Columns and are jit-friendly
(static shapes; no host syncs).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dt
from .column import Column, Table
from .ops import compute


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

def sequence(n: int, start=0, step=1, dtype: dt.DType = dt.INT32) -> Column:
    """0, step, 2*step, ... — cudf::detail::sequence (row_conversion.cu:389-390),
    the arithmetic progression behind list offsets."""
    vals = start + step * jnp.arange(n, dtype=jnp.int64)
    return compute.from_values(vals, dtype, None)


def full(n: int, value, dtype: dt.DType) -> Column:
    """A column of ``n`` copies of ``value`` (cudf make_*_scalar + fill)."""
    if dtype.is_string:
        if isinstance(value, str):
            value = value.encode("utf-8", "surrogateescape")
        return Column.from_strings([value] * n)
    vals = jnp.full((n,), value, dtype=np.dtype(dtype.device_dtype))
    return compute.from_values(vals, dtype, None)


def full_null(n: int, dtype: dt.DType) -> Column:
    """An all-null column (payload zeros, validity all-False)."""
    valid = jnp.zeros((n,), dtype=jnp.bool_)
    if dtype.is_string:
        return Column(
            jnp.zeros((n, 1), dtype=jnp.uint8),
            dt.STRING,
            valid,
            jnp.zeros((n,), dtype=jnp.int32),
        )
    data = jnp.zeros((n,), dtype=dtype.storage_dtype)
    return Column(data, dtype, valid)


def empty_like(col: Column, n: Optional[int] = None) -> Column:
    """An uninitialized-contents column with the same dtype/layout
    (cudf make_fixed_width_column with UNINITIALIZED masks,
    row_conversion.cu:546-557 — here zeros, XLA has no uninitialized)."""
    rows = col.row_count if n is None else n
    if col.dtype.is_string:
        return Column(
            jnp.zeros((rows, col.pad_width), dtype=jnp.uint8),
            dt.STRING,
            None,
            jnp.zeros((rows,), dtype=jnp.int32),
        )
    return Column(jnp.zeros((rows,), dtype=col.data.dtype), col.dtype, None)


# ---------------------------------------------------------------------------
# copying / reshape
# ---------------------------------------------------------------------------

def concatenate(cols: Sequence[Column]) -> Column:
    """Vertical concatenation (cudf::concatenate)."""
    if not cols:
        raise ValueError("concatenate of no columns")
    dtype = cols[0].dtype
    for c in cols[1:]:
        if c.dtype != dtype:
            raise TypeError(f"dtype mismatch: {c.dtype!r} vs {dtype!r}")
    n_total = sum(c.row_count for c in cols)

    if dtype.is_string:
        pad = max(c.pad_width for c in cols)
        mats = [
            jnp.pad(c.data, ((0, 0), (0, pad - c.pad_width)))
            if c.pad_width < pad
            else c.data
            for c in cols
        ]
        data = jnp.concatenate(mats, axis=0)
        lengths = jnp.concatenate([c.lengths for c in cols])
    else:
        data = jnp.concatenate([c.data for c in cols])
        lengths = None

    if any(c.validity is not None for c in cols):
        validity = jnp.concatenate(
            [
                c.validity
                if c.validity is not None
                else jnp.ones((c.row_count,), dtype=jnp.bool_)
                for c in cols
            ]
        )
    else:
        validity = None
    out = Column(data, dtype, validity, lengths)
    assert out.row_count == n_total
    return out


def concatenate_tables(tables: Sequence[Table]) -> Table:
    """Row-wise table concatenation (schema must match)."""
    if not tables:
        raise ValueError("concatenate of no tables")
    k = tables[0].num_columns
    for t in tables[1:]:
        if t.num_columns != k:
            raise ValueError("column count mismatch")
    cols = [
        concatenate([t.columns[i] for t in tables]) for i in range(k)
    ]
    return Table(cols, tables[0].names)


def slice_column(col: Column, start: int, end: int) -> Column:
    """Zero-copy-ish contiguous row slice (cudf::slice)."""
    data = col.data[start:end]
    validity = None if col.validity is None else col.validity[start:end]
    lengths = None if col.lengths is None else col.lengths[start:end]
    return Column(data, col.dtype, validity, lengths)


def slice_table(table: Table, start: int, end: int) -> Table:
    return Table(
        [slice_column(c, start, end) for c in table.columns], table.names
    )


def split_table(table: Table, splits: Sequence[int]) -> list:
    """cudf::split — cut points -> list of contiguous sub-tables."""
    bounds = [0, *splits, table.row_count]
    return [
        slice_table(table, bounds[i], bounds[i + 1])
        for i in range(len(bounds) - 1)
    ]


def interleave_columns(cols: Sequence[Column]) -> Column:
    """Row-interleave equal-length same-type columns
    (cudf::interleave_columns: out[i*k+j] = cols[j][i])."""
    if not cols:
        raise ValueError("interleave of no columns")
    dtype = cols[0].dtype
    if dtype.is_string:
        raise TypeError("interleave_columns: fixed-width only")
    n = cols[0].row_count
    for c in cols:
        if c.dtype != dtype or c.row_count != n:
            raise ValueError("interleave requires same dtype and length")
    k = len(cols)
    data = jnp.stack([c.data for c in cols], axis=1).reshape(n * k)
    if any(c.validity is not None for c in cols):
        validity = jnp.stack(
            [
                c.validity
                if c.validity is not None
                else jnp.ones((n,), dtype=jnp.bool_)
                for c in cols
            ],
            axis=1,
        ).reshape(n * k)
    else:
        validity = None
    return Column(data, dtype, validity)


def copy_if_else(lhs: Column, rhs: Column, mask: Column) -> Column:
    """Per-row select: mask ? lhs : rhs (cudf::copy_if_else). Null mask
    rows follow Spark CASE WHEN: a null predicate selects ``rhs``."""
    if not mask.dtype.is_boolean:
        raise TypeError("copy_if_else mask must be BOOL8")
    if lhs.dtype != rhs.dtype:
        raise TypeError("copy_if_else requires matching dtypes")
    take_l = mask.data
    if mask.validity is not None:
        take_l = jnp.logical_and(take_l, mask.validity)
    if lhs.dtype.is_string:
        pad = max(lhs.pad_width, rhs.pad_width)
        lmat = jnp.pad(lhs.data, ((0, 0), (0, pad - lhs.pad_width)))
        rmat = jnp.pad(rhs.data, ((0, 0), (0, pad - rhs.pad_width)))
        data = jnp.where(take_l[:, None], lmat, rmat)
        lengths = jnp.where(take_l, lhs.lengths, rhs.lengths)
    else:
        data = jnp.where(take_l, lhs.data, rhs.data)
        lengths = None
    lv = (
        lhs.validity
        if lhs.validity is not None
        else jnp.ones((lhs.row_count,), dtype=jnp.bool_)
    )
    rv = (
        rhs.validity
        if rhs.validity is not None
        else jnp.ones((rhs.row_count,), dtype=jnp.bool_)
    )
    validity = jnp.where(take_l, lv, rv)
    if lhs.validity is None and rhs.validity is None:
        validity = None
    return Column(data, lhs.dtype, validity, lengths)


# ---------------------------------------------------------------------------
# shape buckets (utils/buckets.py applied at the Python level)
#
# The dispatch plane (runtime_bridge._dispatch) buckets automatically;
# these are the Python-level entry points for callers that drive the op
# library directly and want the same compiled-shape reuse: pad once,
# run the *_capped ops with `row_valid`, unpad at the end.
# ---------------------------------------------------------------------------


def pad_to_bucket(table: Table, bucket: Optional[int] = None) -> Table:
    """Pad ``table`` to its row-count bucket (or an explicit ``bucket``),
    carrying the logical row count on the result (``Table.logical_rows``).
    Returns the input unchanged when bucketing is disabled
    (``SPARK_RAPIDS_TPU_BUCKETS=off``) or the size has no bucket."""
    from .utils import buckets

    if bucket is None:
        bucket = buckets.bucket_for(table.logical_row_count)
        if bucket is None:
            return table
    return buckets.pad_table(table, bucket)


def unpad_table(table: Table) -> Table:
    """Exact-shape view of a possibly bucket-padded table (inverse of
    :func:`pad_to_bucket`; identity for exact tables)."""
    from .utils import buckets

    return buckets.unpad_table(table)


def run_plan(
    ops: Sequence[dict],
    table: Table,
    rest: Sequence[Table] = (),
    unpad: bool = True,
    donate_input: bool = False,
) -> Table:
    """Python-level plan entry: execute a JSON-able op LIST (the
    ``table_plan_wire``/``table_plan_resident`` format) over
    device-resident Tables. Maximal runs of fusable ops compile into
    single cached executables (plan.py) — one launch per segment —
    and boundary ops dispatch per-op. ``unpad=True`` (default) returns
    an exact-shape result; pass ``unpad=False`` to keep the
    bucket-padded table (``Table.logical_rows`` carries the real
    count) when feeding another plan or bucketed op.

    ``donate_input=True`` declares ``table`` consumed by this plan:
    nothing else references its buffers, so the first fused segment may
    donate them and update HBM in place (``hbm.donated_bytes``). The
    caller must not touch ``table`` afterwards."""
    from . import plan as plan_mod
    from .utils import buckets, profiler

    ops = list(ops)
    schema = None
    report = None
    if profiler.enabled():
        # key the plan-stats record like the wire entries do; static
        # analysis here is observational only — plan.run_plan stays the
        # loud validator for this path
        from . import plancheck

        try:
            schema = plancheck.schema_of_table(table)
            report = plancheck.analyze(
                ops, schema=schema, rows=int(table.logical_row_count),
            )
        # srt: allow-broad-except(stats keying is best-effort; plan.run_plan still validates loudly)
        except Exception:
            schema = report = None
    with profiler.maybe_session(
        ops, label="plan_python", schema=schema, static=report,
    ):
        out = plan_mod.run_plan(
            ops, table, tuple(rest), donate_input=donate_input
        )
        return buckets.unpad_table(out) if unpad else out


# ---------------------------------------------------------------------------
# validity bitmask packing (Arrow wire form <-> device bool vectors)
# ---------------------------------------------------------------------------

def pack_bitmask(valid: jax.Array) -> jax.Array:
    """(n,) bool -> ceil(n/8) uint8, LSB-first (Arrow/cudf bitmask_type
    layout; the device-side analog of interop.pack_validity). Jittable.

    Delegates to the row codec's bit packer (rows._pack_validity_bytes) —
    one normative implementation of the LSB-first layout, shared with the
    packed-row validity tail."""
    from . import rows

    n = valid.shape[0]
    # one "row" whose columns are the n bits
    return rows._pack_validity_bytes(valid[None, :], n)[0]


def unpack_bitmask(packed: jax.Array, n: int) -> jax.Array:
    """ceil(n/8) uint8 LSB-first -> (n,) bool (inverse of pack_bitmask,
    same shared core as the row codec)."""
    from . import rows

    return rows._unpack_validity_bytes(packed[None, :], n)[0]
