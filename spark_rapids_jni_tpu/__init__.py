"""spark_rapids_jni_tpu — TPU-native columnar backend for the RAPIDS
Accelerator for Apache Spark.

A ground-up re-design of the capability surface of ``spark-rapids-jni``
(+ its pinned libcudf) for JAX/XLA/Pallas on TPU: Arrow-layout device
buffers in HBM, the reference's packed row format (RowConversion.java:43-102)
as compiled XLA computations, a null-aware columnar op library, and
partition-exchange over ICI collectives instead of UCX/NCCL.

Layer map (mirrors SURVEY.md §1, re-architected):
  Java facade (java/)             — ai.rapids.cudf-compatible API
  JNI/C ABI native runtime (src/) — handle registry, host row codec
  Python runtime (this package)   — Column/Table pytrees + op library
  XLA/Pallas kernels              — the compute path on TPU
"""

# Spark's data model is int64/float64-centric; enable 64-bit types unless the
# embedder opts out. (TPU executes f64 via software emulation — ops that care
# about throughput should cast to f32/bf16 explicitly.) The flag rides the
# config plane like every other knob — srt-check (SRT001) keeps raw
# SPARK_RAPIDS_TPU_* environ reads out of everything but utils/config.py.
from .utils import config as _config

if not _config.get_flag("DISABLE_X64"):
    import jax

    jax.config.update("jax_enable_x64", True)

from . import dtype
from .dtype import DType, TypeId
from .column import Column, Table

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy subpackage access (keeps `import spark_rapids_jni_tpu` light and
    # avoids import cycles: io/parallel/ops pull in the op library).
    if name in (
        "io",
        "ops",
        "parallel",
        "utils",
        "interop",
        "rows",
        "factories",
        "struct",
    ):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "dtype",
    "DType",
    "TypeId",
    "Column",
    "Table",
    "io",
    "__version__",
]
