"""STRUCT columns: typed child columns + top-level validity.

The cudf surface the reference artifact ships includes STRUCT columns
(``cudf::make_structs_column``, struct gather/sort/filter — SURVEY.md
§2.3 columnar-type-system row; Spark reaches them for nested schemas and
``struct(...)`` expressions). cudf lays a struct out as parallel child
columns plus a struct-level null mask — exactly Arrow's layout — and the
TPU design keeps that: a ``StructColumn`` owns one device ``Column`` per
field and an optional validity vector. There is no single flat device
buffer (children have heterogeneous dtypes), so a struct is a pytree of
its children and composes with jit/shard_map like a small Table.

MVP scope (documented): flat structs over fixed-width/string/decimal
children; struct-of-struct nesting is not supported yet. Struct columns
live standalone or packed/unpacked from Table columns via
``pack``/``unpack``; ordering follows cudf struct semantics —
lexicographic over fields in declaration order.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dt
from .column import Column, Table


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class StructColumn:
    """One STRUCT column: parallel children + struct-level validity.

    A null struct row is null at THIS level; children keep whatever
    validity they carry (cudf semantics: child nulls under a valid
    struct are visible, children under a null struct are undefined)."""

    children: tuple
    names: tuple
    validity: Optional[jax.Array] = None

    def tree_flatten(self):
        return (tuple(self.children), self.validity), tuple(self.names)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        children, validity = leaves
        return cls(children=children, names=aux, validity=validity)

    # -- construction -----------------------------------------------------

    @staticmethod
    def from_children(
        children: Sequence[Column],
        names: Optional[Sequence[str]] = None,
        validity=None,
    ) -> "StructColumn":
        """cudf ``make_structs_column``: zip existing columns into a
        struct."""
        children = tuple(children)
        if not children:
            raise ValueError("struct needs at least one field")
        n = children[0].data.shape[0]
        for c in children:
            if c.data.shape[0] != n:
                raise ValueError("struct children must share row count")
        names = tuple(
            names if names is not None
            else (f"f{i}" for i in range(len(children)))
        )
        if len(names) != len(children):
            raise ValueError("one name per child")
        if validity is not None and not isinstance(validity, jax.Array):
            validity = jnp.asarray(np.asarray(validity, dtype=np.bool_))
        return StructColumn(children, names, validity)

    @staticmethod
    def from_pylist(
        rows: Sequence[Optional[dict]],
        dtypes: Optional[dict] = None,
    ) -> "StructColumn":
        """Build from a list of dicts (None = null struct row). Field
        set is taken from the first non-null row; missing keys in later
        rows become child nulls."""
        first = next((r for r in rows if r is not None), None)
        if first is None:
            raise ValueError("all-null struct needs explicit dtypes/fields")
        names = list(first.keys())
        valid = np.array([r is not None for r in rows], dtype=np.bool_)
        cols = []
        for name in names:
            vals = [None if r is None else r.get(name) for r in rows]
            want = (dtypes or {}).get(name)
            if want is not None and want.id != dt.TypeId.STRING:
                arr = np.array(
                    [0 if v is None else v for v in vals],
                    dtype=np.dtype(want.storage_dtype)
                    if want.id != dt.TypeId.FLOAT64
                    else np.float64,
                )
                v_mask = np.array([v is not None for v in vals], np.bool_)
                cols.append(
                    Column.from_numpy(
                        arr,
                        validity=None if v_mask.all() else v_mask,
                        dtype=want,
                    )
                )
            elif isinstance(first.get(name), str) or (
                want is not None and want.id == dt.TypeId.STRING
            ):
                cols.append(Column.from_strings(vals))
            else:
                tbl = Table.from_pydict({name: vals})
                cols.append(tbl.columns[0])
        return StructColumn.from_children(
            cols, names, None if valid.all() else valid
        )

    # -- basic accessors --------------------------------------------------

    @property
    def dtype(self) -> dt.DType:
        return dt.DType(dt.TypeId.STRUCT)

    @property
    def row_count(self) -> int:
        return int(self.children[0].data.shape[0])

    @property
    def num_fields(self) -> int:
        return len(self.children)

    def field(self, key: Union[int, str]) -> Column:
        """Child extraction (cudf struct ``get_child`` / Spark
        ``struct.field``): child nulls OR struct-level nulls."""
        i = self.names.index(key) if isinstance(key, str) else key
        c = self.children[i]
        if self.validity is None:
            return c
        v = (
            self.validity
            if c.validity is None
            else jnp.logical_and(c.validity, self.validity)
        )
        return Column(c.data, c.dtype, v, c.lengths)

    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(jnp.sum(jnp.logical_not(self.validity)))

    def to_pylist(self) -> list:
        fields = [c.to_pylist() for c in self.children]
        valid = (
            [True] * self.row_count
            if self.validity is None
            else np.asarray(self.validity).tolist()
        )
        return [
            dict(zip(self.names, vals)) if ok else None
            for ok, *vals in zip(valid, *fields)
        ]

    # -- row selection ----------------------------------------------------

    def gather(self, indices, index_valid=None) -> "StructColumn":
        from .ops.gather import gather_column

        children = tuple(
            gather_column(c, indices, None) for c in self.children
        )
        valid = None
        if self.validity is not None:
            valid = jnp.take(self.validity, indices, mode="clip")
        if index_valid is not None:
            valid = (
                index_valid
                if valid is None
                else jnp.logical_and(valid, index_valid)
            )
        return StructColumn(children, self.names, valid)

    def filter(self, mask: Column) -> "StructColumn":
        """Eager row filter by a BOOL8 mask column (host-syncs the
        count, like filter_table)."""
        from .ops import compute

        keep = jnp.logical_and(mask.data, compute.valid_mask(mask))
        total = int(jnp.sum(keep))
        idx = jnp.nonzero(keep, size=total)[0].astype(jnp.int32)
        return self.gather(idx)

    # -- ordering ---------------------------------------------------------

    def order_keys(self) -> list:
        """u64 order-key words: struct-level null word (nulls first),
        then each field's words with field-null words interleaved —
        cudf's lexicographic struct comparator, flattened for lexsort."""
        from .ops import keys as keys_mod

        n = self.row_count
        words: list[jax.Array] = []
        if self.validity is not None:
            words.append(
                jnp.where(self.validity, jnp.uint64(1), jnp.uint64(0))
            )
        for c in self.children:
            if c.validity is not None:
                words.append(
                    jnp.where(c.validity, jnp.uint64(1), jnp.uint64(0))
                )
            words.extend(keys_mod.column_order_keys(c))
        return words

    def argsort(self, ascending: bool = True) -> jax.Array:
        """Stable permutation ordering rows by lexicographic field
        comparison (struct-level nulls first when ascending)."""
        words = self.order_keys()
        if not ascending:
            words = [~w for w in words]
        return jnp.lexsort(words[::-1])


def pack(table: Table, columns: Sequence[Union[int, str]],
         name: str = "s") -> StructColumn:
    """Zip table columns into a StructColumn (Spark ``struct(cols...)``)."""
    cols = [table.column(c) for c in columns]
    names = [
        c if isinstance(c, str) else (
            table.names[c] if table.names else f"f{c}"
        )
        for c in columns
    ]
    return StructColumn.from_children(cols, names)


def unpack(struct: StructColumn) -> Table:
    """Flatten a StructColumn into a Table of its fields (struct-level
    validity folded into every child)."""
    return Table(
        [struct.field(i) for i in range(struct.num_fields)],
        list(struct.names),
    )


def struct_from_arrow(arr) -> StructColumn:
    """Arrow StructArray -> device StructColumn (flat structs)."""
    import pyarrow as pa

    from .interop import column_from_arrow

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if not pa.types.is_struct(arr.type):
        raise TypeError(f"expected a struct array, got {arr.type}")
    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    children = []
    names = []
    for i, f in enumerate(arr.type):
        names.append(f.name)
        children.append(column_from_arrow(arr.field(i)))
    return StructColumn.from_children(children, names, validity)


def struct_to_arrow(sc: StructColumn):
    """Device StructColumn -> Arrow StructArray."""
    import pyarrow as pa

    from .interop import column_to_arrow

    fields = [column_to_arrow(c) for c in sc.children]
    mask = None
    if sc.validity is not None:
        mask = pa.array(~np.asarray(sc.validity), type=pa.bool_())
    return pa.StructArray.from_arrays(
        fields, names=list(sc.names), mask=mask
    )
