"""Bucketed op runners: the dispatch plane's pad-to-bucket fast path.

``runtime_bridge._dispatch`` routes every bucketable op through
:func:`dispatch_bucketed` before falling back to the exact-shape
``_dispatch_impl``. A runner:

1. pads its input tables to their row-count buckets
   (``utils/buckets.pad_table``; wire uploads arrive pre-padded on the
   host side, so this is usually a no-op),
2. fetches the op's compiled executable from the
   ``(op, schema signature, bucket)`` cache (``utils/buckets.cached_jit``)
   — a ragged stream of N batch sizes costs O(#buckets) compiles
   instead of O(N),
3. runs the op at the BUCKET shape with the logical row count passed as
   a device scalar; padded rows are dead via validity-aware tail
   masking: the ``row_valid`` occupancy machinery the capped two-phase
   ops already grew for shuffle padding (ops/groupby.py
   ``groupby_aggregate_capped(row_valid=...)``, ops/join.py
   ``left_valid``/``right_valid``, ops/sort.py ``row_valid``,
   ops/compaction.py ``_first_of_run_mask(row_valid=...)``),
4. returns a PADDED result carrying ``Table.logical_rows`` — the wire
   boundary slices host-side (zero extra compiles) and a downstream
   bucketed op consumes the padding directly.

Semantics contract: for the first ``logical_rows`` rows the result is
bit-identical to the exact path (``tests/test_buckets.py`` pins this at
bucket-boundary row counts). Any runner failure falls back to the exact
path, which remains the semantic reference — bucketing can change
performance, never results.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from . import dtype as dt
from .column import Column, Table
from .utils import buckets, log, metrics, profiler


class _Decline(Exception):
    """Internal: this op/shape opts out of bucketing (exact path runs)."""


_WARNED_OPS = set()


def dispatch_bucketed(
    op: dict, table: Table, rest: Sequence[Table], name: str
) -> Optional[Table]:
    """Run one op through the bucket plane. Returns the (possibly
    padded) result Table, or None when the op/shape isn't bucketable —
    the caller then unpads the inputs and runs the exact path."""
    runner = _RUNNERS.get(name)
    if runner is None:
        return None
    # the span makes the bucket plane its own flight-recorder/trace
    # track (nested inside dispatch.<op>); declines and fallbacks are
    # handled INSIDE it so they exit the span cleanly instead of
    # counting as span errors
    with metrics.span("bucketed." + name):
        try:
            out = runner(op, table, tuple(rest))
        except _Decline:
            metrics.counter_add("bucket.declined")
            return None
        # srt: allow-broad-except(semantics-preserving fallback: the exact path re-runs the op and raises the real error)
        except Exception as e:
            # bucketing must never change semantics: any runner failure
            # falls back to the exact path, which raises the real error
            # if the op itself is at fault
            metrics.counter_add("bucket.fallback_errors")
            profiler.note_fallback("bucketed")
            if name not in _WARNED_OPS:
                _WARNED_OPS.add(name)
                log.log(
                    "WARN", "buckets", "bucketed_runner_failed", op=name,
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
            return None
    metrics.counter_add("bucket.dispatched")
    return out


def dispatch_bucketed_donated(
    op: dict, table: Table, name: str
) -> Optional[Table]:
    """Run ONE op whose input table is CONSUMED (the caller released
    its resident id) with the padded input donated to the executable —
    the single-op flavor of plan-segment donation, built on the same
    fused-applier machinery so the donated executable shares
    ``plan._run_fused``'s cache keying. Returns None when the op/shape
    can't take the donated path (the caller then runs the normal
    dispatch on the still-intact input); raises only when the donated
    launch failed AFTER consuming its buffers."""
    from . import plan as plan_mod

    if not buckets.enabled() or not plan_mod.op_fusable(op):
        return None
    with metrics.span("bucketed.donated." + name):
        try:
            return plan_mod._run_fused([op], table, donate=True)
        except _Decline:
            metrics.counter_add("bucket.declined")
            return None
        except Exception as e:
            if plan_mod._input_consumed(table):
                raise
            metrics.counter_add("bucket.fallback_errors")
            profiler.note_fallback("bucketed")
            if name not in _WARNED_OPS:
                _WARNED_OPS.add(name)
                log.log(
                    "WARN", "buckets", "donated_runner_failed", op=name,
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
            return None


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------


def _padded_input(t: Table) -> Table:
    """The bucketed view of an input table: pre-padded tables pass
    through (their physical size keys the cache), exact tables pad to
    their bucket; shapes with no bucket decline."""
    n = t.logical_row_count
    if n <= 0:
        raise _Decline
    if t.logical_rows is not None:
        return t
    b = buckets.bucket_for(n)
    if b is None:
        raise _Decline
    return buckets.pad_table(t, b)


def _strip(t: Table) -> Table:
    """Drop the logical-row metadata before a jit call: the count
    travels as a device scalar instead, so every logical size within a
    bucket shares ONE traced program (pytree aux must not vary)."""
    return Table(t.columns, t.names)


def _n_dev(t: Table):
    return jnp.asarray(t.logical_row_count, jnp.int32)


def _finish(padded_out: Table, logical) -> Table:
    return Table(
        padded_out.columns, padded_out.names, logical_rows=int(logical)
    )


def _key(kind: str, op: dict, *tables: Table, extra: tuple = ()) -> tuple:
    return buckets.cache_key(kind, op, tables, extra)


# ---------------------------------------------------------------------------
# runners
# ---------------------------------------------------------------------------


def _r_cast(op: dict, table: Table, rest) -> Table:
    pt = _padded_input(table)
    ci = int(op["column"])
    target = dt.DType(dt.TypeId(op["type_id"]), op.get("scale", 0))

    def build():
        def fn(t):
            src = t.columns[ci]
            if src.dtype.is_string or target.is_string:
                from .ops import strings as strings_mod

                out = strings_mod.cast(src, target)
            else:
                from .ops.cast import cast as cast_fn

                out = cast_fn(src, target)
            cols = list(t.columns)
            cols[ci] = out
            return Table(cols, t.names)

        return fn

    fn = buckets.cached_jit(_key("cast", op, pt), build, "srt_bucketed_cast")
    return _finish(fn(_strip(pt)), pt.logical_row_count)


def _r_filter(op: dict, table: Table, rest) -> Table:
    pt = _padded_input(table)
    mi = int(op["mask"])

    def build():
        def fn(t, n):
            from .ops.filter import filter_table_capped

            mask = t.columns[mi]
            rv = buckets.tail_valid(t.row_count, n)
            # padding tails of RE-padded tables can hold arbitrary
            # garbage (e.g. a prior capped filter clones kept rows), so
            # the occupancy mask must gate the selection explicitly
            keep = Column(
                jnp.logical_and(mask.data, rv), mask.dtype, mask.validity
            )
            kept = Table(
                [c for i, c in enumerate(t.columns) if i != mi]
            )  # names dropped exactly like the exact-path dispatch
            return filter_table_capped(kept, keep, capacity=t.row_count)

        return fn

    fn = buckets.cached_jit(
        _key("filter", op, pt), build, "srt_bucketed_filter"
    )
    out, count = fn(_strip(pt), _n_dev(pt))
    # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
    return _finish(out, int(count))


def _r_sort(op: dict, table: Table, rest) -> Table:
    pt = _padded_input(table)

    def build():
        def fn(t, n):
            from .ops.sort import SortKey, sort_table

            ks = [
                SortKey(k["column"], ascending=k.get("ascending", True))
                for k in op["keys"]
            ]
            rv = buckets.tail_valid(t.row_count, n)
            return sort_table(t, ks, row_valid=rv)

        return fn

    fn = buckets.cached_jit(
        _key("sort_by", op, pt), build, "srt_bucketed_sort"
    )
    return _finish(fn(_strip(pt), _n_dev(pt)), pt.logical_row_count)


def _r_groupby(op: dict, table: Table, rest) -> Table:
    from .ops.groupby import (
        _COLLECT_OPS,
        GroupbyAgg,
        groupby_aggregate_capped,
    )

    aggs = [GroupbyAgg(a["column"], a["agg"]) for a in op["aggs"]]
    if any(a.op in _COLLECT_OPS for a in aggs):
        # collect_* needs a data-dependent list capacity pre-pass —
        # exact path owns that sizing
        raise _Decline
    pt = _padded_input(table)
    by = list(op["by"])

    def build():
        def fn(t, n):
            rv = buckets.tail_valid(t.row_count, n)
            return groupby_aggregate_capped(
                t, by, aggs, num_segments=t.row_count, row_valid=rv
            )

        return fn

    fn = buckets.cached_jit(
        _key("groupby", op, pt), build, "srt_bucketed_groupby"
    )
    out, num_groups = fn(_strip(pt), _n_dev(pt))
    # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
    return _finish(out, int(num_groups))


def _r_distinct(op: dict, table: Table, rest) -> Table:
    pt = _padded_input(table)
    keyspec = op.get("keys")

    def build():
        def fn(t, n):
            from .ops.compaction import distinct_capped

            rv = buckets.tail_valid(t.row_count, n)
            return distinct_capped(
                t, keyspec, capacity=t.row_count, row_valid=rv
            )

        return fn

    fn = buckets.cached_jit(
        _key("distinct", op, pt), build, "srt_bucketed_distinct"
    )
    out, count = fn(_strip(pt), _n_dev(pt))
    # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
    return _finish(out, int(count))


def _r_rlike(op: dict, table: Table, rest) -> Table:
    pt = _padded_input(table)
    ci = int(op["column"])
    pattern = op["pattern"]

    def build():
        def fn(t, n):
            from .ops import regex as regex_mod
            from .ops.filter import filter_table_capped

            rv = buckets.tail_valid(t.row_count, n)
            mask = regex_mod.contains_re(t.columns[ci], pattern)
            # padding rows are zero-length strings: a pattern matching
            # the empty string would select them without the gate
            keep = Column(
                jnp.logical_and(mask.data, rv), mask.dtype, mask.validity
            )
            return filter_table_capped(t, keep, capacity=t.row_count)

        return fn

    fn = buckets.cached_jit(
        _key("rlike", op, pt), build, "srt_bucketed_rlike"
    )
    out, count = fn(_strip(pt), _n_dev(pt))
    # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
    return _finish(out, int(count))


_BUCKETED_JOIN_HOWS = frozenset({"inner", "left", "semi", "anti"})


def _r_join(op: dict, table: Table, rest) -> Table:
    how = op.get("how", "inner")
    if how not in _BUCKETED_JOIN_HOWS or not rest:
        # right/full build on the exact outer machinery; argument
        # errors surface from the exact path
        raise _Decline
    lt = _padded_input(table)
    rt = _padded_input(rest[0])
    on = list(op["on"])

    if how in ("semi", "anti"):
        anti = how == "anti"

        def build_sa():
            def fn(l, r, ln, rn):
                from .ops.filter import filter_table_capped
                from .ops.join import _match_ranges

                lv = buckets.tail_valid(l.row_count, ln)
                rv = buckets.tail_valid(r.row_count, rn)
                _, _, counts, lvalid = _match_ranges(l, r, on, on, lv, rv)
                has = jnp.logical_and(counts > 0, lvalid)
                if anti:
                    # null-key rows match nothing -> kept by ANTI;
                    # padding rows (lv False) emit nothing
                    keep = jnp.logical_and(jnp.logical_not(has), lv)
                else:
                    keep = has
                return filter_table_capped(
                    l, Column(keep, dt.BOOL8, None), capacity=l.row_count
                )

            return fn

        fn = buckets.cached_jit(
            _key("join." + how, op, lt, rt), build_sa,
            "srt_bucketed_join_" + how,
        )
        out, count = fn(_strip(lt), _strip(rt), _n_dev(lt), _n_dev(rt))
        # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
        return _finish(out, int(count))

    # inner/left: two-phase sizing. Phase 1 (probe) compiles per input
    # bucket pair; phase 2 (materialize) per OUTPUT capacity bucket —
    # the output size is bucketed too, so both phases cost O(#buckets)
    # executables across a ragged stream.
    def build_probe():
        def fn(l, r, ln, rn):
            from .ops.join import _left_emit, _match_ranges

            lv = buckets.tail_valid(l.row_count, ln)
            rv = buckets.tail_valid(r.row_count, rn)
            perm_r, lo, counts, _ = _match_ranges(l, r, on, on, lv, rv)
            return (
                perm_r, lo, counts,
                jnp.sum(counts),
                jnp.sum(_left_emit(counts, lv)),
            )

        return fn

    p1 = buckets.cached_jit(
        _key("join.ranges", {"on": on}, lt, rt), build_probe,
        "srt_bucketed_join_probe",
    )
    perm_r, lo, counts, inner_total, left_total = p1(
        _strip(lt), _strip(rt), _n_dev(lt), _n_dev(rt)
    )
    # srt: allow-host-sync(bucketed-runner boundary: the compiled launch is done; one count read sizes the logical rows of the padded result)
    total = int(left_total if how == "left" else inner_total)
    cap = buckets.bucket_for(total)
    if cap is None:
        # no output bucket (empty result, or a fan-out past the ladder
        # cap): materializing at the exact total would compile one
        # executable per distinct size AND build the oversized fused
        # graphs the cap exists to avoid — the exact path (with its
        # fenced batched-probe routing) owns those shapes
        raise _Decline
    left_outer = how == "left"

    def build_mat():
        def fn(l, r, perm_r, lo, counts, ln):
            from .ops.join import _expand, _join_output, _left_emit

            if left_outer:
                lv = buckets.tail_valid(l.row_count, ln)
                emit = _left_emit(counts, lv)
                left_idx, right_idx, matched, _ = _expand(
                    perm_r, lo, counts, cap, left_outer=True, emit=emit
                )
                return _join_output(
                    l, r, on, left_idx, right_idx, matched, None
                )
            left_idx, right_idx, _, _ = _expand(
                perm_r, lo, counts, cap, left_outer=False
            )
            # no matched/row_valid masks, matching the exact-path
            # inner_join output schema; rows past ``total`` are garbage
            # behind the logical row count
            return _join_output(l, r, on, left_idx, right_idx, None, None)

        return fn

    p2 = buckets.cached_jit(
        _key("join.mat." + how, {"on": on}, lt, rt, extra=(cap,)),
        build_mat, "srt_bucketed_join_mat",
    )
    out = p2(_strip(lt), _strip(rt), perm_r, lo, counts, _n_dev(lt))
    return _finish(out, total)


_RUNNERS = {
    "cast": _r_cast,
    "filter": _r_filter,
    "sort_by": _r_sort,
    "groupby": _r_groupby,
    "distinct": _r_distinct,
    "rlike": _r_rlike,
    "join": _r_join,
}


def is_bucketable(op: dict) -> bool:
    """Cheap pre-check: could this op take the bucketed path at all?
    The wire layer uses it to skip host-side padding (and the extra
    upload bytes it costs) for ops that would immediately unpad."""
    name = op.get("op")
    if name not in _RUNNERS:
        return False
    if name == "join":
        return op.get("how", "inner") in _BUCKETED_JOIN_HOWS
    if name == "groupby":
        from .ops.groupby import _COLLECT_OPS

        # collect_* groupbys decline in the runner (data-dependent
        # list capacity) — don't pay the padded upload for them
        return not any(
            a.get("agg") in _COLLECT_OPS for a in op.get("aggs", ())
        )
    return True
