"""Pallas TPU kernel: batched VMEM-resident bitonic sort of key chunks.

The chunked groupby (ops/groupby_chunked.py) turns one n-row sort into
C independent T-row sorts, betting that XLA's batched ``lax.sort``
keeps each small sort VMEM-resident. This kernel removes the bet: each
grid step sorts ONE chunk entirely inside VMEM with an unrolled bitonic
network — compare-exchange partners reached by ``pltpu.roll`` (partner
``i XOR j`` is ``i+j`` for the low element and ``i-j`` for the high
one, so two circular shifts plus a parity select cover every pair), the
TPU translation of the shared-memory tiled sorts GPU libraries use.

Mosaic constraints shape the interface (same discipline as
row_transpose.py's "no Mosaic i64 paths"): 64-bit keys and payloads are
split into u32 (hi, lo) halves OUTSIDE the kernel (free bitcasts under
XLA) and compared lexicographically inside. A per-row index rides as
the final tiebreaker, making the network deterministic and
order-stable for equal keys despite bitonic's inherent instability.

Used today as a measured A/B against ``jax.lax.sort`` on the chunk
shapes (bench config ``chunk_sort_ab``); flips on as the groupby
phase-1 engine only if the chip says it wins (r4 measurement pending —
tunnel outage; see BASELINE.md round-4 status).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import default_interpret


def _check_pow2(t: int) -> None:
    if t & (t - 1) or t < 2:
        raise ValueError(f"chunk length must be a power of two, got {t}")


def _kernel(n_payload: int, t: int):
    """Kernel body closure: refs = [hi, lo] keys + n_payload u32
    payloads, each (1, T); same layout out."""
    from jax.experimental.pallas import tpu as pltpu

    def body(*refs):
        ins = refs[: 2 + n_payload]
        outs = refs[2 + n_payload :]
        hi = ins[0][...]
        lo = ins[1][...]
        ps = [r[...] for r in ins[2:]]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1)
        i = idx

        ops = [hi, lo, idx] + ps
        k = 2
        while k <= t:
            j = k // 2
            while j >= 1:
                # pltpu.roll wants non-negative shifts: a left shift by
                # j is a right shift by t - j on the circle
                rolled_up = [pltpu.roll(x, t - j, axis=1) for x in ops]
                rolled_dn = [pltpu.roll(x, j, axis=1) for x in ops]
                is_low = (i & j) == 0  # lower index of the pair
                partner = [
                    jnp.where(is_low, u, d)
                    for u, d in zip(rolled_up, rolled_dn)
                ]
                p_hi, p_lo, p_idx = partner[0], partner[1], partner[2]
                hi_, lo_, idx_ = ops[0], ops[1], ops[2]
                # lexicographic (hi, lo, idx): partner strictly smaller?
                p_lt = (
                    (p_hi < hi_)
                    | ((p_hi == hi_) & (p_lo < lo_))
                    | ((p_hi == hi_) & (p_lo == lo_) & (p_idx < idx_))
                )
                asc = (i & k) == 0  # ascending block of this stage
                keep_min = is_low == asc
                take_partner = jnp.where(keep_min, p_lt, ~p_lt)
                ops = [
                    jnp.where(take_partner, pv, xv)
                    for pv, xv in zip(partner, ops)
                ]
                j //= 2
            k *= 2

        outs[0][...] = ops[0]
        outs[1][...] = ops[1]
        for r, v in zip(outs[2:], [ops[2]] + ops[3:]):
            r[...] = v

    return body


#: Chunks per grid step. Mosaic requires the sublane (second-to-last)
#: block dim be a multiple of 8; each of the 8 rows runs the same
#: network independently (rolls are along axis 1), so batching them in
#: one block costs nothing and satisfies the tiling rule.
_ROWS_PER_BLOCK = 8


@functools.lru_cache(maxsize=64)
def _sort_call(n_payload: int, t: int, interpret: bool):
    spec = pl.BlockSpec((_ROWS_PER_BLOCK, t), lambda c: (c, 0))
    n_ops = 2 + n_payload

    def fn(*arrays):
        c = arrays[0].shape[0]
        out_shapes = [
            jax.ShapeDtypeStruct((c, t), jnp.uint32) for _ in range(2)
        ] + [jax.ShapeDtypeStruct((c, t), jnp.int32)] + [
            jax.ShapeDtypeStruct((c, t), jnp.uint32)
            for _ in range(n_payload)
        ]
        return pl.pallas_call(
            _kernel(n_payload, t),
            grid=(c // _ROWS_PER_BLOCK,),
            in_specs=[spec] * n_ops,
            out_specs=[spec] * (n_ops + 1),  # +1: the permutation index
            out_shape=out_shapes,
            interpret=interpret,
        )(*arrays)

    return jax.jit(fn)


def batched_sort_u64(
    key: jax.Array, *payloads: jax.Array, interpret: bool | None = None
):
    """Sort each row of ``key`` (C, T) u64 ascending, carrying payloads.

    Returns ``(sorted_key, perm int32, *sorted_payloads)`` where perm is
    the within-chunk source index (the iota that rode the network — the
    same contract as carrying an iota operand through ``lax.sort``).
    Equal keys keep their original relative order (index tiebreaker).
    Payloads may be u64/i64 (split into u32 halves around the kernel)
    or <=32-bit (widened)."""
    if interpret is None:
        interpret = default_interpret()
    c, t = key.shape
    _check_pow2(t)
    # Mosaic block tiling: pad the chunk count to the 8-row block and
    # strip after (padding chunks sort all-max garbage, discarded).
    pad_c = (-c) % _ROWS_PER_BLOCK
    if pad_c:
        key = jnp.concatenate(
            [key, jnp.full((pad_c, t), ~jnp.uint64(0))], axis=0
        )
        payloads = tuple(
            jnp.concatenate(
                [p, jnp.zeros((pad_c, t), p.dtype)], axis=0
            )
            for p in payloads
        )
    hi = (key >> jnp.uint64(32)).astype(jnp.uint32)
    lo = key.astype(jnp.uint32)

    split = []
    wide = []
    for p in payloads:
        if p.dtype.itemsize == 8:
            pb = jax.lax.bitcast_convert_type(p, jnp.uint64)
            split.append((pb >> jnp.uint64(32)).astype(jnp.uint32))
            split.append(pb.astype(jnp.uint32))
            wide.append(True)
        elif p.dtype.itemsize == 4:
            # bitcast, not astype: a value cast truncates float32
            # payloads (1.5 -> 1) where the 8-byte path bit-preserves
            split.append(jax.lax.bitcast_convert_type(p, jnp.uint32))
            wide.append(False)
        else:
            if jnp.issubdtype(p.dtype, jnp.floating):
                raise TypeError(
                    f"narrow float payload {p.dtype} would lose bits "
                    "through the u32 widening; cast it to float32 first"
                )
            # integer widen/narrow round-trips exactly (two's complement
            # wrap on the way back)
            split.append(p.astype(jnp.uint32))
            wide.append(False)

    out = _sort_call(len(split), t, bool(interpret))(hi, lo, *split)
    if pad_c:
        out = tuple(o[:c] for o in out)
    s_hi, s_lo, perm = out[0], out[1], out[2]
    s_key = (s_hi.astype(jnp.uint64) << jnp.uint64(32)) | s_lo.astype(
        jnp.uint64
    )
    outp = []
    k = 3
    for p, w in zip(payloads, wide):
        if w:
            v = (
                out[k].astype(jnp.uint64) << jnp.uint64(32)
            ) | out[k + 1].astype(jnp.uint64)
            outp.append(jax.lax.bitcast_convert_type(v, p.dtype))
            k += 2
        elif p.dtype.itemsize == 4:
            outp.append(jax.lax.bitcast_convert_type(out[k], p.dtype))
            k += 1
        else:
            outp.append(out[k].astype(p.dtype))
            k += 1
    return (s_key, perm, *outp)


# ---------------------------------------------------------------------------
# u32 single-word variant — the packed-key fast path's engine. When the
# sort key fits ONE u32 (key-range x chunk-rows <= 2^32, the packed
# groupby/ORDER BY word with its embedded per-chunk iota), the network
# compares one word with NO tiebreaker: the embedded iota makes keys
# unique, so stability is structural and the (hi, lo, idx) lexicographic
# compare — and two thirds of the VMEM traffic — vanish.
# ---------------------------------------------------------------------------


def _kernel_u32(n_payload: int, t: int):
    """refs = key + n_payload u32 payloads in, same out; (8, T) blocks.

    Requires every key in a row to be DISTINCT (packed iota contract):
    with distinct keys a bitonic network is deterministic, so no index
    tiebreaker rides."""
    from jax.experimental.pallas import tpu as pltpu

    def body(*refs):
        ins = refs[: 1 + n_payload]
        outs = refs[1 + n_payload:]
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1)
        ops = [r[...] for r in ins]
        k = 2
        while k <= t:
            j = k // 2
            while j >= 1:
                rolled_up = [pltpu.roll(x, t - j, axis=1) for x in ops]
                rolled_dn = [pltpu.roll(x, j, axis=1) for x in ops]
                is_low = (idx & j) == 0
                partner = [
                    jnp.where(is_low, u, d)
                    for u, d in zip(rolled_up, rolled_dn)
                ]
                p_lt = partner[0] < ops[0]
                asc = (idx & k) == 0
                keep_min = is_low == asc
                take_partner = jnp.where(keep_min, p_lt, ~p_lt)
                ops = [
                    jnp.where(take_partner, pv, xv)
                    for pv, xv in zip(partner, ops)
                ]
                j //= 2
            k *= 2
        for r, v in zip(outs, ops):
            r[...] = v

    return body


@functools.lru_cache(maxsize=64)
def _sort_call_u32(n_payload: int, t: int, interpret: bool):
    spec = pl.BlockSpec((_ROWS_PER_BLOCK, t), lambda c: (c, 0))
    n_ops = 1 + n_payload

    def fn(*arrays):
        c = arrays[0].shape[0]
        return pl.pallas_call(
            _kernel_u32(n_payload, t),
            grid=(c // _ROWS_PER_BLOCK,),
            in_specs=[spec] * n_ops,
            out_specs=[spec] * n_ops,
            out_shape=[
                jax.ShapeDtypeStruct((c, t), jnp.uint32)
                for _ in range(n_ops)
            ],
            interpret=interpret,
        )(*arrays)

    return jax.jit(fn)


def batched_sort_u32(
    key: jax.Array, *payloads: jax.Array, interpret: bool | None = None
):
    """Sort each row of ``key`` (C, T) u32 ascending, carrying payloads.

    Keys within a row MUST be distinct (the packed-word-with-iota
    contract) — with ties the network's output order is undefined.
    Payloads must be 4-byte (bitcast around the kernel) or narrower
    integers (widened). Returns ``(sorted_key, *sorted_payloads)``; the
    caller recovers the permutation from the embedded iota bits."""
    if interpret is None:
        interpret = default_interpret()
    c, t = key.shape
    _check_pow2(t)
    if key.dtype != jnp.uint32:
        raise TypeError(f"key must be uint32, got {key.dtype}")
    for p in payloads:  # validate before any device work
        if p.dtype.itemsize > 4 or (
            p.dtype.itemsize < 4 and jnp.issubdtype(p.dtype, jnp.floating)
        ):
            raise TypeError(
                f"u32 network payload must be <=4-byte int or any "
                f"4-byte dtype, got {p.dtype}"
            )
    pad_c = (-c) % _ROWS_PER_BLOCK
    if pad_c:
        key = jnp.concatenate(
            [key, jnp.full((pad_c, t), ~jnp.uint32(0))], axis=0
        )
        payloads = tuple(
            jnp.concatenate(
                [p, jnp.zeros((pad_c, t), p.dtype)], axis=0
            )
            for p in payloads
        )
    split = [
        jax.lax.bitcast_convert_type(p, jnp.uint32)
        if p.dtype.itemsize == 4
        else p.astype(jnp.uint32)
        for p in payloads
    ]
    out = _sort_call_u32(len(split), t, bool(interpret))(key, *split)
    if pad_c:
        out = tuple(o[:c] for o in out)
    outp = []
    for p, s in zip(payloads, out[1:]):
        if p.dtype.itemsize == 4:
            outp.append(jax.lax.bitcast_convert_type(s, p.dtype))
        else:
            outp.append(s.astype(p.dtype))
    return (out[0], *outp)


# ---------------------------------------------------------------------------
# loop-form variant — the kernel tier's engine. The unrolled networks
# above trace one program op per compare-exchange (log2(T)^2 / 2 stages
# x rolls x operands), which Mosaic wants but which makes interpret-mode
# tracing quadratically expensive (minutes at T=1024 — unusable for the
# CPU tier-1 parity gate). This variant runs the SAME network as two
# nested lax loops with gather-by-computed-partner (i XOR j) inside the
# kernel body: tracing is O(1) in T, so the registry's interpret path
# compiles in seconds. The vector gathers put it in the same Mosaic
# bucket as hash_table.py (may refuse to lower on a real TPU today) —
# the kernel tier's fallback discipline absorbs that; the roll-based
# networks above remain the Mosaic-native engines for the bench arms.
# ---------------------------------------------------------------------------


def _kernel_u64_looped(n_payload: int, c: int, t: int):
    """refs = hi, lo + payloads in (C, T); out adds the perm. One
    program over the whole batch, stable via the riding iota."""

    def body(*refs):
        ins = refs[: 2 + n_payload]
        outs = refs[2 + n_payload:]
        i = jax.lax.broadcasted_iota(jnp.int32, (c, t), 1)
        ops0 = (ins[0][...], ins[1][...], i) + tuple(
            r[...] for r in ins[2:]
        )

        def stage(ops, k, j):
            p = jnp.bitwise_xor(i, j)  # partner index, same for every row
            partner = tuple(
                jnp.take_along_axis(x, p, axis=1) for x in ops
            )
            hi_, lo_, idx_ = ops[0], ops[1], ops[2]
            p_hi, p_lo, p_idx = partner[0], partner[1], partner[2]
            p_lt = (
                (p_hi < hi_)
                | ((p_hi == hi_) & (p_lo < lo_))
                | ((p_hi == hi_) & (p_lo == lo_) & (p_idx < idx_))
            )
            is_low = (i & j) == 0
            asc = (i & k) == 0
            keep_min = is_low == asc
            take = jnp.where(keep_min, p_lt, ~p_lt)
            return tuple(
                jnp.where(take, pv, xv) for pv, xv in zip(partner, ops)
            )

        n_k = max(t.bit_length() - 1, 0)  # log2(t) outer stages

        def outer(kk, ops):
            k = jnp.int32(1) << (kk + 1)

            def inner(s, ops):
                j = jnp.int32(1) << (kk - s)
                return stage(ops, k, j)

            return jax.lax.fori_loop(0, kk + 1, inner, ops)

        ops = jax.lax.fori_loop(0, n_k, outer, ops0)
        for r, v in zip(outs, (ops[0], ops[1], ops[2]) + ops[3:]):
            r[...] = v

    return body


@functools.lru_cache(maxsize=64)
def _sort_call_looped(n_payload: int, c: int, t: int, interpret: bool):
    def fn(*arrays):
        return pl.pallas_call(
            _kernel_u64_looped(n_payload, c, t),
            out_shape=[
                jax.ShapeDtypeStruct((c, t), jnp.uint32) for _ in range(2)
            ] + [jax.ShapeDtypeStruct((c, t), jnp.int32)] + [
                jax.ShapeDtypeStruct((c, t), jnp.uint32)
                for _ in range(n_payload)
            ],
            interpret=interpret,
        )(*arrays)

    return jax.jit(fn)


def batched_sort_u64_looped(
    key: jax.Array, *payloads: jax.Array, interpret: bool | None = None
):
    """:func:`batched_sort_u64` semantics (stable, same payload dtype
    rules) on the loop-form kernel — O(1) tracing cost in T."""
    if interpret is None:
        interpret = default_interpret()
    c, t = key.shape
    _check_pow2(t)
    hi = (key >> jnp.uint64(32)).astype(jnp.uint32)
    lo = key.astype(jnp.uint32)
    split = []
    wide = []
    for p in payloads:
        if p.dtype.itemsize == 8:
            pb = jax.lax.bitcast_convert_type(p, jnp.uint64)
            split.append((pb >> jnp.uint64(32)).astype(jnp.uint32))
            split.append(pb.astype(jnp.uint32))
            wide.append(True)
        elif p.dtype.itemsize == 4:
            split.append(jax.lax.bitcast_convert_type(p, jnp.uint32))
            wide.append(False)
        else:
            if jnp.issubdtype(p.dtype, jnp.floating):
                raise TypeError(
                    f"narrow float payload {p.dtype} would lose bits "
                    "through the u32 widening; cast it to float32 first"
                )
            split.append(p.astype(jnp.uint32))
            wide.append(False)
    out = _sort_call_looped(len(split), c, t, bool(interpret))(
        hi, lo, *split
    )
    s_key = (out[0].astype(jnp.uint64) << jnp.uint64(32)) | out[1].astype(
        jnp.uint64
    )
    perm = out[2]
    outp = []
    k = 3
    for p, w in zip(payloads, wide):
        if w:
            v = (
                out[k].astype(jnp.uint64) << jnp.uint64(32)
            ) | out[k + 1].astype(jnp.uint64)
            outp.append(jax.lax.bitcast_convert_type(v, p.dtype))
            k += 2
        elif p.dtype.itemsize == 4:
            outp.append(jax.lax.bitcast_convert_type(out[k], p.dtype))
            k += 1
        else:
            outp.append(out[k].astype(p.dtype))
            k += 1
    return (s_key, perm, *outp)
