"""Pallas TPU kernel: fused multi-column Spark Murmur3 table hash.

The XLA path (ops/hashing.py) expresses the per-column hash chain as a
sequence of elementwise ops that XLA fuses per column; this kernel fuses
the ENTIRE chain across columns into one VMEM pass — each row tile is
read once per column word and the running h1 never leaves registers.
Bit-identical to ``ops.hashing.murmur3_table`` (same Spark
``Murmur3_x86_32`` algorithm, seed chaining, null-skipping); the test
suite asserts equality against it and against the CPU oracle.

Column wire format into the kernel (prepared by ``_column_words``, all
cheap bitcasts XLA fuses into the feeding computation):

* int-family  -> one (n,) uint32 word  (hashInt)
* long-family -> two (n,) uint32 words, low then high (hashLong)
* strings     -> unsupported here; ``murmur3_table_fused`` falls back to
  the XLA path when any key column is variable-width.

Rows are processed as (grid, TILE) 2-D tiles so every in-kernel array is
rank-2 with a 128-multiple lane dimension (Mosaic's preferred shape).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import dtype as dt
from ..column import Column, Table

TILE = 1024  # lanes per row-tile; multiple of 128
SUBLANES = 8  # second-to-last block dim (int32 min tile is (8, 128))

# Typed zero for BlockSpec index maps (bare 0 traces as i64 under x64,
# which Mosaic's index tuple rejects).
_Z = np.int32(0)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(0xE6546B64)

_INT_IDS = frozenset(
    {
        dt.TypeId.INT8,
        dt.TypeId.INT16,
        dt.TypeId.INT32,
        dt.TypeId.UINT8,
        dt.TypeId.UINT16,
        dt.TypeId.UINT32,
        dt.TypeId.TIMESTAMP_DAYS,
        dt.TypeId.DURATION_DAYS,
        dt.TypeId.DICTIONARY32,
        dt.TypeId.BOOL8,
        dt.TypeId.FLOAT32,
    }
)


def _rotl(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix_k1(k1):
    return _rotl(k1 * _C1, 15) * _C2


def _mix_h1(h1, k1):
    return _rotl(h1 ^ k1, 13) * np.uint32(5) + _M5


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> jnp.uint32(13))
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> jnp.uint32(16))


def _column_words(col: Column) -> tuple[str, list[jax.Array]]:
    """Column -> ("int"|"long", [uint32 word arrays]) per the Spark rules
    of ops/hashing.py:100-132 (float -0.0 normalization included)."""
    d = col.dtype
    if d.is_string:
        raise TypeError("string columns take the XLA hash path")
    if d.id == dt.TypeId.FLOAT32:
        bits = jax.lax.bitcast_convert_type(
            jnp.where(col.data == 0, jnp.float32(0), col.data), jnp.uint32
        )
        return "int", [bits]
    if d.id in _INT_IDS:
        return "int", [col.data.astype(jnp.int32).astype(jnp.uint32)]
    if d.id == dt.TypeId.FLOAT64:
        neg_zero = jnp.uint64(0x8000000000000000)
        bits = jnp.where(col.data == neg_zero, jnp.uint64(0), col.data)
    else:
        bits = col.data.astype(jnp.int64).astype(jnp.uint64)
    low = bits.astype(jnp.uint32)
    high = (bits >> jnp.uint64(32)).astype(jnp.uint32)
    return "long", [low, high]


def _hash_kernel(kinds: tuple[str, ...], seed: int, *refs):
    """One grid step over a (SUBLANES, TILE) row tile: chain all columns.

    refs = word refs (1 per int column, 2 per long column), then one
    validity ref per column, then the output ref.
    """
    num_words = sum(1 if k == "int" else 2 for k in kinds)
    word_refs = refs[:num_words]
    valid_refs = refs[num_words : num_words + len(kinds)]
    out_ref = refs[-1]
    h1 = jnp.full((SUBLANES, TILE), np.uint32(seed), dtype=jnp.uint32)
    w = 0
    for ci, kind in enumerate(kinds):
        prev = h1
        if kind == "int":
            h1 = _fmix(_mix_h1(h1, _mix_k1(word_refs[w][...])), 4)
            w += 1
        else:
            h1 = _mix_h1(h1, _mix_k1(word_refs[w][...]))
            h1 = _mix_h1(h1, _mix_k1(word_refs[w + 1][...]))
            h1 = _fmix(h1, 8)
            w += 2
        # null rows leave the running hash unchanged (typed zero: bare
        # python ints promote via i64 under x64, which Mosaic rejects)
        h1 = jnp.where(valid_refs[ci][...] != jnp.uint8(0), h1, prev)
    out_ref[...] = h1.astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("kinds", "seed", "interpret")
)
def _hash_words_pallas(
    words: tuple[jax.Array, ...],
    valids: tuple[jax.Array, ...],
    kinds: tuple[str, ...],
    seed: int,
    interpret: bool = False,
) -> jax.Array:
    n = words[0].shape[0]
    block = SUBLANES * TILE
    n_padded = max((n + block - 1) // block * block, block)
    grid = n_padded // block
    rows = n_padded // TILE

    def shape2d(x):
        return jnp.pad(x, (0, n_padded - n)).reshape(rows, TILE)

    args = [shape2d(x) for x in words] + [shape2d(v) for v in valids]
    in_specs = [
        pl.BlockSpec((SUBLANES, TILE), lambda i: (i, _Z)) for _ in args
    ]
    out = pl.pallas_call(
        functools.partial(_hash_kernel, kinds, seed),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((SUBLANES, TILE), lambda i: (i, _Z)),
        out_shape=jax.ShapeDtypeStruct((rows, TILE), jnp.int32),
        interpret=interpret,
    )(*args)
    return out.reshape(n_padded)[:n]


def supports(cols) -> bool:
    """True when every key column has a kernel wire format."""
    return all(not c.dtype.is_string for c in cols)


def murmur3_table_fused(
    table: Table,
    columns=None,
    seed: int = 42,
    interpret: bool | None = None,
) -> Column:
    """Fused-kernel ``murmur3_table``; falls back to the XLA path for
    schemas with string keys."""
    cols = (
        [table.column(c) for c in columns]
        if columns is not None
        else list(table.columns)
    )
    # empty key set: the kernel has no words to read; XLA path returns
    # the seed-filled column
    if not cols or not supports(cols):
        from ..ops import hashing as xla_hashing

        return xla_hashing.murmur3_table(table, columns, seed)
    if interpret is None:
        from . import default_interpret

        interpret = default_interpret()
    kinds, words = [], []
    for c in cols:
        kind, ws = _column_words(c)
        kinds.append(kind)
        words.extend(ws)
    n = table.row_count
    valids = tuple(
        c.validity.astype(jnp.uint8)
        if c.validity is not None
        else jnp.ones((n,), jnp.uint8)
        for c in cols
    )
    h = _hash_words_pallas(
        tuple(words), valids, tuple(kinds), seed, interpret=interpret
    )
    return Column(h, dt.INT32, None)
