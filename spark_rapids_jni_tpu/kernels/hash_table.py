"""Pallas TPU kernel: VMEM-resident open-addressing hash build/probe.

The join/groupby inner loop of the reference stack is cuco's device
hash table (insert_and_find / contains under warp-cooperative probing).
TPUs have no device-wide atomics, so this kernel re-expresses the same
table as a *vectorized leader election* over linear-probe rounds: every
live row proposes itself for its current slot, the lowest row id wins
the claim (a functional ``.at[slot].min`` — the deterministic stand-in
for ``atomicCAS``), and all rows then re-read the slot to check for a
key match. Rows carrying the same key walk the same probe sequence in
lockstep, so the winning claimant is always the LOWEST original row id
of its key group — exactly the stable representative the sort-based
exact path elects, which is what makes byte-parity provable.

Layout: inputs arrive as (C, T) chunks with a per-chunk table of
``S = table_slots`` slots (S a power of two, typically 2T). The whole
batch runs as ONE program over flattened arrays — chunk c's rows index
slots ``c*S + slot``, so chunks never collide and the interpreter path
stays fully vectorized (no per-chunk python loop, no grid unrolling).

Keys are u64 order words (ops/keys.py) split into u32 (hi, lo) halves
OUTSIDE the kernel — the same "no Mosaic i64 paths" discipline as
bitonic_sort.py. The build kernel needs gather/scatter by computed
vectors, which today's Mosaic lowering may refuse; the kernel tier's
fallback discipline (kernels/registry.py) absorbs that as a metered
``kernel.fallbacks`` replay on the exact path, while ``interpret=True``
covers the CPU tier-1 parity fuzz. The probe kernel is gather-only.

Termination is bounded: ``max_probes`` rounds. Rows still live after
the loop are reported in the ``overflow`` scalar; callers MUST treat a
nonzero overflow (or probe ``unresolved``) as a decline — the table
contents are valid, but unplaced rows have no slot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import default_interpret

#: Linear-probe round bound. 64 covers load factors well past 0.5
#: (S = 2T) in practice; clustering beyond it reports overflow and the
#: caller declines to the exact path.
MAX_PROBES = 64


def hash_word(word: jax.Array) -> jax.Array:
    """u64 order word -> u32 slot hash (fmix32 over the folded halves).

    Computed OUTSIDE the kernel (free elementwise ops under XLA) so the
    kernel body only ever sees the initial slot."""
    lo = word.astype(jnp.uint32)
    hi = (word >> jnp.uint64(32)).astype(jnp.uint32)
    h = lo ^ (hi * jnp.uint32(0x9E3779B9))
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _check_pow2(s: int) -> None:
    if s & (s - 1) or s < 2:
        raise ValueError(f"table_slots must be a power of two, got {s}")


def _build_kernel(c: int, t: int, s: int, max_probes: int):
    n = c * t
    ns = c * s

    def body(lo_ref, hi_ref, valid_ref, slot0_ref,
             slot_ref, tlo_ref, thi_ref, trow_ref, ovf_ref, dup_ref):
        lo = lo_ref[...].reshape(n)
        hi = hi_ref[...].reshape(n)
        live0 = valid_ref[...].reshape(n) != 0
        pslot0 = slot0_ref[...].reshape(n)
        rowid = jax.lax.broadcasted_iota(jnp.int32, (c, t), 1).reshape(n)
        base = jax.lax.broadcasted_iota(jnp.int32, (c, t), 0).reshape(n) * s

        def round_(_, st):
            pslot, live, out_slot, tlo, thi, trow, dup = st
            fidx = base + pslot
            empty = trow[fidx] < 0
            # leader election: lowest row id among live rows pointing
            # at an empty slot claims it (rows of one chunk can only
            # collide with each other — fidx is chunk-offset)
            claim = jnp.full((ns,), n, jnp.int32).at[fidx].min(
                jnp.where(live & empty, rowid, n)
            )
            won = live & empty & (claim[fidx] == rowid)
            widx = jnp.where(won, fidx, ns)
            tlo = tlo.at[widx].set(lo, mode="drop")
            thi = thi.at[widx].set(hi, mode="drop")
            trow = trow.at[widx].set(rowid, mode="drop")
            # re-read: freshly claimed or pre-existing entry with our key?
            occ = trow[fidx] >= 0
            hit = live & occ & (tlo[fidx] == lo) & (thi[fidx] == hi)
            out_slot = jnp.where(hit, pslot, out_slot)
            dup = dup + jnp.sum(
                jnp.where(hit & (trow[fidx] != rowid), 1, 0),
                dtype=jnp.int32,
            )
            live = live & ~hit
            pslot = jnp.where(live, (pslot + 1) & (s - 1), pslot)
            return pslot, live, out_slot, tlo, thi, trow, dup

        st = jax.lax.fori_loop(
            0, max_probes, round_,
            (
                pslot0, live0, jnp.full((n,), -1, jnp.int32),
                jnp.zeros((ns,), jnp.uint32), jnp.zeros((ns,), jnp.uint32),
                jnp.full((ns,), -1, jnp.int32), jnp.int32(0),
            ),
        )
        _, live, out_slot, tlo, thi, trow, dup = st
        slot_ref[...] = out_slot.reshape(c, t)
        tlo_ref[...] = tlo.reshape(c, s)
        thi_ref[...] = thi.reshape(c, s)
        trow_ref[...] = trow.reshape(c, s)
        ovf_ref[0, 0] = jnp.sum(live, dtype=jnp.int32)
        dup_ref[0, 0] = dup

    return body


@functools.lru_cache(maxsize=64)
def _build_call(c: int, t: int, s: int, max_probes: int, interpret: bool):
    def fn(lo, hi, valid, slot0):
        return pl.pallas_call(
            _build_kernel(c, t, s, max_probes),
            out_shape=[
                jax.ShapeDtypeStruct((c, t), jnp.int32),
                jax.ShapeDtypeStruct((c, s), jnp.uint32),
                jax.ShapeDtypeStruct((c, s), jnp.uint32),
                jax.ShapeDtypeStruct((c, s), jnp.int32),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
            ],
            interpret=interpret,
        )(lo, hi, valid, slot0)

    return jax.jit(fn)


def build_table(
    lo: jax.Array,
    hi: jax.Array,
    valid: jax.Array,
    *,
    table_slots: int,
    max_probes: int = MAX_PROBES,
    interpret: bool | None = None,
):
    """Build one open-addressing table per chunk.

    ``lo``/``hi``: (C, T) u32 key halves; ``valid``: (C, T) int32
    occupancy (0 = padding/null, never inserted). Returns::

        slot       (C, T) i32  per-row slot in its chunk's table
                               (-1: invalid row, or unplaced overflow)
        table_lo   (C, S) u32  stored key halves per slot
        table_hi   (C, S) u32
        table_row  (C, S) i32  chunk-local row id of the FIRST (lowest
                               row id) inserter; -1 = empty slot
        overflow   ()     i32  valid rows left unplaced after
                               ``max_probes`` rounds (nonzero => the
                               caller must decline)
        dup        ()     i32  valid rows that matched an entry claimed
                               by a DIFFERENT row (== n_valid - distinct
                               when overflow == 0)
    """
    if interpret is None:
        interpret = default_interpret()
    c, t = lo.shape
    s = int(table_slots)
    _check_pow2(s)
    slot0 = (
        hash_word(
            hi.astype(jnp.uint64) << jnp.uint64(32)
            | lo.astype(jnp.uint64)
        )
        & jnp.uint32(s - 1)
    ).astype(jnp.int32)
    out = _build_call(c, t, s, int(max_probes), bool(interpret))(
        lo, hi, valid.astype(jnp.int32), slot0
    )
    slot, tlo, thi, trow, ovf, dup = out
    return slot, tlo, thi, trow, ovf[0, 0], dup[0, 0]


def _probe_kernel(c: int, t: int, s: int, max_probes: int):
    n = c * t

    def body(lo_ref, hi_ref, valid_ref, slot0_ref, tlo_ref, thi_ref,
             trow_ref, found_ref, row_ref, unres_ref):
        lo = lo_ref[...].reshape(n)
        hi = hi_ref[...].reshape(n)
        live0 = valid_ref[...].reshape(n) != 0
        pslot0 = slot0_ref[...].reshape(n)
        tlo = tlo_ref[...].reshape(c * s)
        thi = thi_ref[...].reshape(c * s)
        trow = trow_ref[...].reshape(c * s)
        base = jax.lax.broadcasted_iota(jnp.int32, (c, t), 0).reshape(n) * s

        def round_(_, st):
            pslot, live, found, row = st
            fidx = base + pslot
            occ = trow[fidx] >= 0
            hit = live & occ & (tlo[fidx] == lo) & (thi[fidx] == hi)
            found = found | hit
            row = jnp.where(hit, trow[fidx], row)
            # an empty slot along the probe sequence proves absence
            live = live & occ & ~hit
            pslot = jnp.where(live, (pslot + 1) & (s - 1), pslot)
            return pslot, live, found, row

        st = jax.lax.fori_loop(
            0, max_probes, round_,
            (
                pslot0, live0, jnp.zeros((n,), jnp.bool_),
                jnp.full((n,), -1, jnp.int32),
            ),
        )
        _, live, found, row = st
        found_ref[...] = found.reshape(c, t).astype(jnp.int32)
        row_ref[...] = row.reshape(c, t)
        unres_ref[0, 0] = jnp.sum(live, dtype=jnp.int32)

    return body


@functools.lru_cache(maxsize=64)
def _probe_call(c: int, t: int, s: int, max_probes: int, interpret: bool):
    def fn(lo, hi, valid, slot0, tlo, thi, trow):
        return pl.pallas_call(
            _probe_kernel(c, t, s, max_probes),
            out_shape=[
                jax.ShapeDtypeStruct((c, t), jnp.int32),
                jax.ShapeDtypeStruct((c, t), jnp.int32),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
            ],
            interpret=interpret,
        )(lo, hi, valid, slot0, tlo, thi, trow)

    return jax.jit(fn)


def probe_table(
    lo: jax.Array,
    hi: jax.Array,
    valid: jax.Array,
    table_lo: jax.Array,
    table_hi: jax.Array,
    table_row: jax.Array,
    *,
    max_probes: int = MAX_PROBES,
    interpret: bool | None = None,
):
    """Probe (C, T) query keys against per-chunk tables from
    :func:`build_table` (gather-only — no scatters inside). Returns::

        found       (C, T) i32  1 = key present in the chunk's table
        row         (C, T) i32  ``table_row`` of the matching slot
                                (-1 when not found)
        unresolved  ()     i32  valid queries that neither matched nor
                                hit an empty slot within ``max_probes``
                                (nonzero => the caller must decline)
    """
    if interpret is None:
        interpret = default_interpret()
    c, t = lo.shape
    s = int(table_lo.shape[1])
    _check_pow2(s)
    slot0 = (
        hash_word(
            hi.astype(jnp.uint64) << jnp.uint64(32)
            | lo.astype(jnp.uint64)
        )
        & jnp.uint32(s - 1)
    ).astype(jnp.int32)
    out = _probe_call(c, t, s, int(max_probes), bool(interpret))(
        lo, hi, valid.astype(jnp.int32), slot0,
        table_lo, table_hi, table_row,
    )
    found, row, unres = out
    return found, row, unres[0, 0]
