"""Hand-written Pallas TPU kernels for the framework's hot ops.

The reference hand-writes CUDA for exactly one Spark-specific hot path —
the row⇄columnar transpose (row_conversion.cu:48-304, shared-memory tiled,
warp ballots) — and gets everything else from libcudf's kernels. Here the
split is: XLA fusion covers most of the op library, and this package holds
explicit Pallas kernels for the paths where controlling VMEM tiling and
fusing multi-column passes matters:

* ``row_transpose`` — packed-row assembly/disassembly tiles (the CUDA
  kernel pair's TPU replacement; 48 KB shared memory -> VMEM blocks, warp
  ballots -> vectorized bit-weight reductions).
* ``hashing`` — fused multi-column Murmur3 table hashing in one VMEM pass.

Every kernel has an ``interpret=`` escape hatch so the CPU test tier
(tests/conftest.py) exercises the same code path the TPU runs.
"""

import jax


def on_tpu() -> bool:
    """True when the default backend is a real TPU (including the axon
    tunnel platform, whose platform string is not "tpu")."""
    try:
        d = jax.devices()[0]
        return "tpu" in (d.platform + " " + d.device_kind).lower()
    # srt: allow-broad-except(no usable backend means not-TPU; capability probing must never raise at import)
    except Exception:
        return False


def default_interpret() -> bool:
    """Pallas ``interpret=`` default: Mosaic on TPU, interpreter elsewhere
    (the CPU test tier runs the same kernel code interpreted)."""
    return not on_tpu()


from . import hashing, row_transpose  # noqa: E402,F401

__all__ = ["row_transpose", "hashing", "on_tpu", "default_interpret"]
