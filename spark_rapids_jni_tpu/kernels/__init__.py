"""Hand-written Pallas TPU kernels for the framework's hot ops.

The reference hand-writes CUDA for exactly one Spark-specific hot path —
the row⇄columnar transpose (row_conversion.cu:48-304, shared-memory tiled,
warp ballots) — and gets everything else from libcudf's kernels. Here the
split is: XLA fusion covers most of the op library, and this package holds
explicit Pallas kernels for the paths where controlling VMEM tiling and
fusing multi-column passes matters:

* ``row_transpose`` — packed-row assembly/disassembly tiles (the CUDA
  kernel pair's TPU replacement; 48 KB shared memory -> VMEM blocks, warp
  ballots -> vectorized bit-weight reductions).
* ``hashing`` — fused multi-column Murmur3 table hashing in one VMEM pass.
* ``bitonic_sort`` — batched VMEM-resident bitonic sort networks.
* ``hash_table`` — VMEM-resident open-addressing hash build/probe (the
  join/groupby inner loop).
* ``registry`` — the kernel tier: one dispatchable entry per accelerated
  inner loop, selected under ``SPARK_RAPIDS_TPU_KERNELS`` with
  exact-path-fallback discipline.

Every kernel has an ``interpret=`` escape hatch so the CPU test tier
(tests/conftest.py) exercises the same code path the TPU runs.

Kernel submodules import LAZILY (module ``__getattr__``): environments
whose jax build lacks Pallas support must still import this package —
the registry probes :func:`pallas_capability` and degrades every kernel
to a clean ``kernel.declines`` with a labeled warning instead of an
import-time failure.
"""

import importlib

import jax

_SUBMODULES = ("bitonic_sort", "hash_table", "hashing", "registry",
               "row_transpose")


def on_tpu() -> bool:
    """True when the default backend is a real TPU (including the axon
    tunnel platform, whose platform string is not "tpu")."""
    try:
        d = jax.devices()[0]
        return "tpu" in (d.platform + " " + d.device_kind).lower()
    # srt: allow-broad-except(no usable backend means not-TPU; capability probing must never raise at import)
    except Exception:
        return False


def default_interpret() -> bool:
    """Pallas ``interpret=`` default: Mosaic on TPU, interpreter elsewhere
    (the CPU test tier runs the same kernel code interpreted)."""
    return not on_tpu()


_capability: "tuple[bool, str] | None" = None


def pallas_capability() -> "tuple[bool, str]":
    """(available, detail): can this jax build load Pallas at all?

    Probed once, never raises — a missing/broken Pallas install answers
    ``(False, "<reason>")`` and the kernel tier declines every launch
    (kernels/registry.py) instead of failing at import time."""
    global _capability
    if _capability is None:
        try:
            importlib.import_module("jax.experimental.pallas")
            _capability = (True, "")
        # srt: allow-broad-except(capability probing must never raise; any import failure means "no Pallas" and the registry declines cleanly)
        except Exception as e:
            _capability = (
                False, f"jax.experimental.pallas: {type(e).__name__}: "
                f"{str(e)[:160]}",
            )
    return _capability


def __getattr__(name: str):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))


__all__ = ["bitonic_sort", "hash_table", "hashing", "registry",
           "row_transpose", "on_tpu", "default_interpret",
           "pallas_capability"]
