"""The kernel tier: plan-selectable Pallas kernels with exact fallback.

The reference repo exists to house hand-written kernels too
Spark-specific for the general library (row_conversion.cu is the
survey-snapshot example). This registry is that tier for the TPU
backend: one entry per accelerated inner loop, each declaring

* the dispatch-plane op names it accelerates,
* an **applicability predicate** — dtypes, key widths, bucket-size and
  VMEM-footprint bounds — answering a decline *reason* (metered
  ``kernel.declines``) before any device work, and
* a **runner** that must be byte-identical to the bucketed/exact path
  over the logical rows (the shape-bucket semantics contract,
  bucketed.py): padding-region bytes are free, logical bytes are not.

Dispatch discipline mirrors ``bucketed.dispatch_bucketed``: the tier is
consulted first by ``runtime_bridge._dispatch_once`` under the
``SPARK_RAPIDS_TPU_KERNELS=on|off|auto`` flag; any runner error — a
Mosaic lowering the current toolchain refuses, a seeded ``kernel``
chaos fault, an overflowed probe bound — is caught, metered as
``kernel.fallbacks``, and answered with ``None`` so the caller replays
the op on the existing path. The tier can change performance, never
bytes. Compiled callables live in the shared ``buckets.cached_jit``
cache with the kernel name folded into the cache-key kind
(``"kernel.<name>"``), so kernel and non-kernel programs of the same op
cache independently and the compile-cache hit/miss counters attribute
them separately.

``KERNEL_NAMES`` is the SRT012 parity anchor: srt_check statically
cross-checks it against this module's ``_REGISTRY`` literal, plancheck's
``_KERNEL_RULES`` table, and the registered ``kernel`` metric
namespace, so a kernel added to one registry without the others fails
CI before it can ship.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from ..utils import buckets, config, faults, log, metrics, profiler
from . import default_interpret, pallas_capability

#: Every registered kernel — the SRT012 static parity anchor. Must
#: equal the ``_REGISTRY`` keys below and plancheck's ``_KERNEL_RULES``.
KERNEL_NAMES = frozenset(
    {"packed_sort", "hash_build_probe", "hash_groupby", "row_pack",
     "row_unpack"}
)

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)

# ---------------------------------------------------------------------------
# VMEM / shape bounds (applicability predicates)
# ---------------------------------------------------------------------------

#: packed_sort: (3 fixed words: key hi/lo + iota) + payload words, per
#: row, times the bucket length, times the 8-row Mosaic block and u32
#: in+out copies => 2^17 words ~= 8 MB VMEM of a ~16 MB/core budget.
SORT_MAX_WORDS = 1 << 17
#: packed_sort bucket-length window (bitonic network depth vs VMEM).
SORT_MAX_ROWS = 1 << 16
#: hash_build_probe: build-side bucket bound (table is 2x this).
JOIN_BUILD_MAX_ROWS = 1 << 16
#: hash_build_probe: probe-side bucket bound (~6 u32 words/row).
JOIN_PROBE_MAX_ROWS = 1 << 18
#: hash_groupby: input bucket bound (C chunks of GROUPBY_CHUNK_ROWS).
GROUPBY_MAX_ROWS = 1 << 18
#: hash_groupby chunk length T; per-chunk table is S = 2T slots.
GROUPBY_CHUNK_ROWS = 4096

_AGG_OPS = frozenset({"sum", "count", "min", "max"})


class KernelDecline(Exception):
    """Internal: this op/shape opts out of the kernel tier (the
    bucketed/exact path runs). Carries the decline reason."""


def _pow2(n: int) -> bool:
    return n >= 2 and not (n & (n - 1))


def _order_word_reason(col: Column) -> Optional[str]:
    """Why this column cannot be a single-u64-order-word kernel key
    (ops/keys.py emits exactly one word for it), or None if it can."""
    d = col.dtype
    if d.is_string:
        return "string key (multi-word order key)"
    if d.id == dt.TypeId.DECIMAL128:
        return "DECIMAL128 key (two-word order key)"
    if d.id in (dt.TypeId.LIST, dt.TypeId.STRUCT):
        return f"{d.id.name} key"
    if col.validity is not None:
        return "nullable key (null-placement word)"
    return None


def _padded_rows(table: Table) -> Optional[int]:
    """The physical bucket length the runner's padded input will have
    (pre-padded tables keep their size); None = no bucket (decline)."""
    if table.logical_rows is not None:
        return table.row_count
    n = table.logical_row_count
    if n <= 0:
        return None
    return buckets.bucket_for(n)


def _resolve_col(table: Table, spec) -> Optional[Column]:
    try:
        return table.column(spec)
    except (KeyError, IndexError, TypeError, ValueError):
        return None


def _split_u64(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return w.astype(jnp.uint32), (w >> jnp.uint64(32)).astype(jnp.uint32)


# ---------------------------------------------------------------------------
# packed_sort — sort_by via the batched VMEM bitonic network
# ---------------------------------------------------------------------------


def _sort_payload_words(table: Table) -> Optional[int]:
    """u32 words/row the sort network carries beyond key+iota, or None
    when some buffer cannot ride (narrow float payload)."""
    w = 0
    for c in table.columns:
        if c.data.ndim == 1:
            size = c.data.dtype.itemsize
            if size == 8:
                w += 2
            elif size < 4 and jnp.issubdtype(c.data.dtype, jnp.floating):
                return None  # u32 widening would lose bits
            else:
                w += 1
        # matrix buffers (strings, DECIMAL128) gather through the perm
        if c.validity is not None:
            w += 1
        if c.lengths is not None:
            w += 1
    return w


def _a_packed_sort(op: dict, table: Table, rest) -> Optional[str]:
    ks = op.get("keys") or []
    if len(ks) != 1:
        return "multi-key sort (one packed word per network)"
    col = _resolve_col(table, ks[0].get("column"))
    if col is None:
        return "unresolvable sort key column"
    r = _order_word_reason(col)
    if r is not None:
        return r
    w = _sort_payload_words(table)
    if w is None:
        return "narrow float payload column"
    b = _padded_rows(table)
    if b is None:
        return "no shape bucket"
    if not _pow2(b):
        return f"bucket {b} not a power of two"
    if b > SORT_MAX_ROWS or (3 + w) * b > SORT_MAX_WORDS:
        return f"VMEM bound: {3 + w} words x {b} rows"
    return None


def _r_packed_sort(op: dict, table: Table, rest) -> Table:
    from .. import bucketed as bk

    pt = bk._padded_input(table)
    kspec = op["keys"][0]
    ci = kspec["column"]
    asc = bool(kspec.get("ascending", True))
    interp = default_interpret()

    def build():
        def fn(t, n):
            from ..ops import keys as keys_mod
            from . import bitonic_sort

            w = keys_mod.column_order_keys(t.column(ci))[0]
            if not asc:
                w = ~w
            rv = buckets.tail_valid(t.row_count, n)
            # padding rows sink to the tail regardless of direction —
            # the occupancy word of the exact sort, folded into the key
            w = jnp.where(rv, w, jnp.uint64(_U64_MAX))
            plan: list = []
            payloads: list = []
            for i, c in enumerate(t.columns):
                if c.data.ndim == 1:
                    plan.append((i, "data"))
                    payloads.append(c.data)
                if c.validity is not None:
                    plan.append((i, "validity"))
                    payloads.append(c.validity)
                if c.lengths is not None:
                    plan.append((i, "lengths"))
                    payloads.append(c.lengths)
            out = bitonic_sort.batched_sort_u64_looped(
                w[None, :], *[p[None, :] for p in payloads],
                interpret=interp,
            )
            perm = out[1][0]
            by_col: dict = {}
            for (i, attr), arr in zip(plan, out[2:]):
                by_col.setdefault(i, {})[attr] = arr[0]
            cols = []
            for i, c in enumerate(t.columns):
                got = by_col.get(i, {})
                data = got.get("data")
                if data is None:  # matrix layout: gather through perm
                    data = c.data[perm]
                cols.append(
                    Column(
                        data, c.dtype,
                        got.get("validity")
                        if c.validity is not None else None,
                        got.get("lengths")
                        if c.lengths is not None else None,
                    )
                )
            return Table(cols, t.names)

        return fn

    fn = buckets.cached_jit(
        bk._key("kernel.packed_sort", op, pt), build, "srt_kernel_sort"
    )
    return bk._finish(fn(bk._strip(pt), bk._n_dev(pt)), pt.logical_row_count)


# ---------------------------------------------------------------------------
# hash_build_probe — inner/semi/anti join via the VMEM hash table
# ---------------------------------------------------------------------------

_KERNEL_JOIN_HOWS = frozenset({"inner", "semi", "anti"})


def _a_hash_join(op: dict, table: Table, rest) -> Optional[str]:
    how = op.get("how", "inner")
    if how not in _KERNEL_JOIN_HOWS:
        return f"join how={how!r} (left/outer build on exact machinery)"
    if not rest:
        return "missing build-side table"
    on = op.get("on") or []
    if len(on) != 1:
        return "multi-column join key"
    lcol = _resolve_col(table, on[0])
    rcol = _resolve_col(rest[0], on[0])
    if lcol is None or rcol is None:
        return "unresolvable join key column"
    for side, col in (("probe", lcol), ("build", rcol)):
        r = _order_word_reason(col)
        if r is not None:
            return f"{side} side: {r}"
    lb = _padded_rows(table)
    rb = _padded_rows(rest[0])
    if lb is None or rb is None:
        return "no shape bucket"
    if not (_pow2(lb) and _pow2(rb)):
        return "bucket not a power of two"
    if rb > JOIN_BUILD_MAX_ROWS:
        return f"build side {rb} rows over VMEM table bound"
    if lb > JOIN_PROBE_MAX_ROWS:
        return f"probe side {lb} rows over VMEM bound"
    return None


def _join_words(t: Table, spec, rv):
    from ..ops import keys as keys_mod

    w = keys_mod.column_order_keys(t.column(spec))[0]
    lo, hi = _split_u64(w)
    return lo[None, :], hi[None, :], rv[None, :]


def _r_hash_join(op: dict, table: Table, rest) -> Table:
    from .. import bucketed as bk
    from . import hash_table

    how = op.get("how", "inner")
    lt = bk._padded_input(table)
    rt = bk._padded_input(rest[0])
    on = list(op["on"])
    slots = 2 * rt.row_count
    interp = default_interpret()

    if how in ("semi", "anti"):
        anti = how == "anti"

        def build_sa():
            def fn(l, r, ln, rn):
                from ..ops.filter import filter_table_capped

                lv = buckets.tail_valid(l.row_count, ln)
                rv = buckets.tail_valid(r.row_count, rn)
                blo, bhi, bval = _join_words(r, on[0], rv)
                _, tlo, thi, trow, ovf, _ = hash_table.build_table(
                    blo, bhi, bval, table_slots=slots, interpret=interp
                )
                plo, phi, pval = _join_words(l, on[0], lv)
                found, _, unres = hash_table.probe_table(
                    plo, phi, pval, tlo, thi, trow, interpret=interp
                )
                has = (found[0] != 0) & lv
                keep = jnp.logical_and(jnp.logical_not(has), lv) \
                    if anti else has
                out, count = filter_table_capped(
                    l, Column(keep, dt.BOOL8, None), capacity=l.row_count
                )
                return out, count, ovf, unres

            return fn

        fn = buckets.cached_jit(
            bk._key("kernel.hash_join." + how, op, lt, rt), build_sa,
            "srt_kernel_join_" + how,
        )
        out, count, ovf, unres = fn(
            bk._strip(lt), bk._strip(rt), bk._n_dev(lt), bk._n_dev(rt)
        )
        # srt: allow-host-sync(kernel-runner boundary: the compiled launch is done; the overflow flags decide decline and the count sizes the logical rows)
        if int(ovf) or int(unres):
            raise KernelDecline("hash table probe bound exceeded")
        return bk._finish(out, int(count))

    # inner: two-phase sizing like the bucketed runner — phase 1 probes
    # and counts, phase 2 materializes at the OUTPUT bucket capacity.
    def build_probe():
        def fn(l, r, ln, rn):
            lv = buckets.tail_valid(l.row_count, ln)
            rv = buckets.tail_valid(r.row_count, rn)
            blo, bhi, bval = _join_words(r, on[0], rv)
            _, tlo, thi, trow, ovf, dup = hash_table.build_table(
                blo, bhi, bval, table_slots=slots, interpret=interp
            )
            plo, phi, pval = _join_words(l, on[0], lv)
            found, rrow, unres = hash_table.probe_table(
                plo, phi, pval, tlo, thi, trow, interpret=interp
            )
            keep = (found[0] != 0) & lv
            return (
                keep, rrow[0], jnp.sum(keep, dtype=jnp.int64), ovf, dup,
                unres,
            )

        return fn

    p1 = buckets.cached_jit(
        bk._key("kernel.hash_join.probe", {"on": on}, lt, rt),
        build_probe, "srt_kernel_join_probe",
    )
    keep, rrow, total, ovf, dup, unres = p1(
        bk._strip(lt), bk._strip(rt), bk._n_dev(lt), bk._n_dev(rt)
    )
    # srt: allow-host-sync(kernel-runner boundary: the compiled launch is done; the overflow flags decide decline and the count sizes phase 2)
    if int(ovf) or int(unres):
        raise KernelDecline("hash table probe bound exceeded")
    if int(dup):
        # duplicate build keys fan matches out; the single-slot table
        # holds one right row per key, so only unique-key builds are
        # byte-exact here — the range-based exact path owns the rest
        raise KernelDecline("duplicate build-side keys")
    total = int(total)
    cap = buckets.bucket_for(total)
    if cap is None:
        raise KernelDecline("no output bucket for join result")

    def build_mat():
        def fn(l, r, keep, rrow):
            from ..ops.join import _join_output

            pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
            to = jnp.where(keep, pos, cap)
            left_idx = jnp.zeros((cap,), jnp.int32).at[to].set(
                jnp.arange(l.row_count, dtype=jnp.int32), mode="drop"
            )
            right_idx = jnp.zeros((cap,), jnp.int32).at[to].set(
                rrow, mode="drop"
            )
            # no matched/row_valid masks, matching the bucketed/exact
            # inner output schema; rows past ``total`` are garbage
            # behind the logical row count
            return _join_output(l, r, on, left_idx, right_idx, None, None)

        return fn

    p2 = buckets.cached_jit(
        bk._key("kernel.hash_join.mat", {"on": on}, lt, rt, extra=(cap,)),
        build_mat, "srt_kernel_join_mat",
    )
    out = p2(bk._strip(lt), bk._strip(rt), keep, rrow)
    return bk._finish(out, total)


# ---------------------------------------------------------------------------
# hash_groupby — chunked hash partials + one small exact merge
# ---------------------------------------------------------------------------


def _a_hash_groupby(op: dict, table: Table, rest) -> Optional[str]:
    by = op.get("by") or []
    if len(by) != 1:
        return "multi-column group key"
    aggs = op.get("aggs") or []
    if not aggs:
        return "no aggregations"
    bad = [a.get("agg") for a in aggs if a.get("agg") not in _AGG_OPS]
    if bad:
        return f"non-decomposable agg {bad[0]!r}"
    col = _resolve_col(table, by[0])
    if col is None:
        return "unresolvable group key column"
    r = _order_word_reason(col)
    if r is not None:
        return r
    for a in aggs:
        vc = _resolve_col(table, a.get("column"))
        if vc is None:
            return "unresolvable aggregation column"
        d = vc.dtype
        if d.is_string or d.is_decimal or d.is_floating or vc.data.ndim != 1:
            return f"{d.id.name} aggregation value (order-sensitive or multi-word)"
    b = _padded_rows(table)
    if b is None:
        return "no shape bucket"
    if not _pow2(b):
        return f"bucket {b} not a power of two"
    if b > GROUPBY_MAX_ROWS:
        return f"bucket {b} over chunked-hash bound"
    return None


def _r_hash_groupby(op: dict, table: Table, rest) -> Table:
    from .. import bucketed as bk
    from ..ops import compute
    from ..ops import keys as keys_mod
    from ..ops.groupby import GroupbyAgg, groupby_aggregate_capped
    from . import hash_table

    pt = bk._padded_input(table)
    by0 = op["by"][0]
    aggs = list(op["aggs"])
    b = pt.row_count
    t_chunk = min(b, GROUPBY_CHUNK_ROWS)
    c_chunks = b // t_chunk
    slots = 2 * t_chunk
    ns = c_chunks * slots
    interp = default_interpret()

    # the exact path's output names, rebuilt on the merged table
    names = pt.names
    out_names = [
        by0 if isinstance(by0, str)
        else (names[by0] if names else "key0")
    ]
    for a in aggs:
        ac = a["column"]
        base = ac if isinstance(ac, str) else (
            names[ac] if names else f"c{ac}"
        )
        out_names.append(f"{a['agg']}_{base}")

    merge_op = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}
    merge_aggs = [
        GroupbyAgg(i + 1, merge_op[a["agg"]]) for i, a in enumerate(aggs)
    ]

    def build():
        def fn(t, n):
            key_col = t.column(by0)
            w = keys_mod.column_order_keys(key_col)[0]
            rv = buckets.tail_valid(t.row_count, n)
            lo, hi = _split_u64(w)
            _slot, _, _, trow, ovf, _ = hash_table.build_table(
                lo.reshape(c_chunks, t_chunk),
                hi.reshape(c_chunks, t_chunk),
                rv.reshape(c_chunks, t_chunk),
                table_slots=slots, interpret=interp,
            )
            trow_f = trow.reshape(ns)
            used = trow_f >= 0
            # partial id per input row: chunk * S + slot (unplaced rows
            # scatter to the dropped sentinel NS — only possible when
            # ovf > 0, which declines below)
            slot_f = _slot.reshape(b)
            chunk_of_row = jnp.arange(b, dtype=jnp.int32) // t_chunk
            pid = jnp.where(
                slot_f >= 0, chunk_of_row * slots + slot_f, ns
            )
            # representative row per slot: the claim winner, which is
            # the lowest original row id of its key group — the same
            # representative the exact stable sort elects
            chunk_of_slot = jnp.arange(ns, dtype=jnp.int32) // slots
            rep = jnp.where(used, chunk_of_slot * t_chunk + trow_f, 0)
            part_cols = [Column(key_col.data[rep], key_col.dtype, None)]
            for a in aggs:
                acol = t.column(a["column"])
                vals = compute.values(acol)
                m = jnp.logical_and(compute.valid_mask(acol), rv)
                aop = a["agg"]
                if aop == "count":
                    part = jax.ops.segment_sum(
                        m.astype(jnp.int64), pid, num_segments=ns
                    )
                    # validity None, like the exact count output
                    part_cols.append(Column(part, dt.INT64, None))
                    continue
                pv = jax.ops.segment_max(
                    m.astype(jnp.int32), pid, num_segments=ns
                ) > 0
                if aop == "sum":
                    part = jax.ops.segment_sum(
                        jnp.where(m, vals, 0).astype(jnp.int64), pid,
                        num_segments=ns,
                    )
                    part_cols.append(
                        compute.from_values(part, dt.INT64, pv)
                    )
                    continue
                # min / max: the exact path's masked-sentinel trick
                if acol.dtype.is_boolean:
                    sentinel = aop == "min"
                    work = jnp.where(m, vals, sentinel).astype(jnp.int32)
                else:
                    info = np.iinfo(np.dtype(acol.dtype.storage_dtype))
                    sentinel = info.max if aop == "min" else info.min
                    work = jnp.where(
                        m, vals, jnp.asarray(sentinel, vals.dtype)
                    )
                seg = (
                    jax.ops.segment_min if aop == "min"
                    else jax.ops.segment_max
                )
                part = seg(work, pid, num_segments=ns)
                if acol.dtype.is_boolean:
                    part = part.astype(jnp.bool_)
                part_cols.append(
                    compute.from_values(part, acol.dtype, pv)
                )
            # merge: the EXACT capped groupby over the C*S partials —
            # same sort, same segment reductions, same output layout
            merged, num_groups = groupby_aggregate_capped(
                Table(part_cols), [0], merge_aggs,
                num_segments=t.row_count, row_valid=used,
            )
            return Table(merged.columns, out_names), num_groups, ovf

        return fn

    fn = buckets.cached_jit(
        bk._key("kernel.hash_groupby", op, pt), build,
        "srt_kernel_groupby",
    )
    out, num_groups, ovf = fn(bk._strip(pt), bk._n_dev(pt))
    # srt: allow-host-sync(kernel-runner boundary: the compiled launch is done; the overflow flag decides decline and the count sizes the logical rows)
    if int(ovf):
        raise KernelDecline("chunk hash table overflow")
    return bk._finish(out, int(num_groups))


# ---------------------------------------------------------------------------
# row_pack / row_unpack — the row⇄columnar transpose tiles
# ---------------------------------------------------------------------------


def _a_row_pack(op: dict, table: Table, rest) -> Optional[str]:
    for c in table.columns:
        if not c.dtype.is_fixed_width:
            return f"{c.dtype.id.name} column has no fixed-width row slot"
    return None


def _r_row_pack(op: dict, table: Table, rest) -> Table:
    from .. import rows as rows_mod

    t = buckets.unpad_table(table)
    return Table([rows_mod.to_rows_list(t, backend="pallas")])


def _a_row_unpack(op: dict, table: Table, rest) -> Optional[str]:
    if not table.columns or table.columns[0].dtype.id != dt.TypeId.LIST:
        return "legacy flat row buffer (host decode path)"
    for tid in op.get("type_ids", ()):
        if dt.TypeId(int(tid)) not in dt._WIDTHS:
            return "non-fixed-width target schema"
    return None


def _r_row_unpack(op: dict, table: Table, rest) -> Table:
    from .. import rows as rows_mod

    t = buckets.unpad_table(table)
    schema = [
        dt.DType(dt.TypeId(t_), s_)
        for t_, s_ in zip(op["type_ids"], op["scales"])
    ]
    return rows_mod.from_rows_list(t.columns[0], schema, backend="pallas")


# ---------------------------------------------------------------------------
# the registry + dispatch
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One accelerated inner loop: op coverage + predicate + runner."""

    name: str
    ops: Tuple[str, ...]
    applicable: Callable[[dict, Table, Sequence[Table]], Optional[str]]
    runner: Callable[[dict, Table, Sequence[Table]], Table]
    doc: str


_REGISTRY = {
    "packed_sort": KernelSpec(
        "packed_sort", ("sort_by",), _a_packed_sort, _r_packed_sort,
        "single-key ORDER BY through the batched VMEM bitonic network",
    ),
    "hash_build_probe": KernelSpec(
        "hash_build_probe", ("join",), _a_hash_join, _r_hash_join,
        "inner/semi/anti join through the VMEM open-addressing table",
    ),
    "hash_groupby": KernelSpec(
        "hash_groupby", ("groupby",), _a_hash_groupby, _r_hash_groupby,
        "chunked hash partial aggregation + one small exact merge",
    ),
    "row_pack": KernelSpec(
        "row_pack", ("to_rows",), _a_row_pack, _r_row_pack,
        "columnar -> packed rows via the Pallas transpose tiles",
    ),
    "row_unpack": KernelSpec(
        "row_unpack", ("from_rows",), _a_row_unpack, _r_row_unpack,
        "packed rows -> columnar via the Pallas transpose tiles",
    ),
}

assert KERNEL_NAMES == frozenset(_REGISTRY), "KERNEL_NAMES drifted"

_BY_OP: dict = {}
for _spec in _REGISTRY.values():
    for _op_name in _spec.ops:
        _BY_OP.setdefault(_op_name, []).append(_spec)


def kernel_for_op(name: str):
    """The KernelSpecs covering a dispatch-plane op name (may be [])."""
    return list(_BY_OP.get(name, ()))


# flag gate, re-read only when the config generation moves — the
# disabled path is one int compare + one bool test (<5 µs contract)
_GEN = -1
_TRY = False


def _refresh_gate() -> None:
    global _GEN, _TRY
    g = config.generation()
    if g == _GEN:
        return
    mode = config.get_flag("KERNELS")
    if mode == "on":
        _TRY = True
    elif mode == "off":
        _TRY = False
    else:  # auto: only where Mosaic compiles natively
        from . import on_tpu

        _TRY = on_tpu()
    _GEN = g


_WARNED_CAPABILITY = False
_WARNED_KERNELS = set()


def dispatch_kernel(
    op: dict, table: Table, rest: Sequence[Table], name: str
) -> Optional[Table]:
    """Run one op through the kernel tier. Returns the (possibly
    padded) result Table, or None when no kernel applies / the flag is
    off / the launch failed — the caller then runs the bucketed/exact
    path. Never changes bytes, only performance."""
    global _WARNED_CAPABILITY
    _refresh_gate()
    if not _TRY:
        return None
    specs = _BY_OP.get(name)
    if specs is None:
        return None
    ok, why = pallas_capability()
    if not ok:
        metrics.counter_add("kernel.declines")
        if not _WARNED_CAPABILITY:
            _WARNED_CAPABILITY = True
            log.log(
                "WARN", "kernels", "pallas_unavailable", detail=why,
            )
        return None
    from .. import bucketed as bk

    for spec in specs:
        reason = spec.applicable(op, table, rest)
        if reason is not None:
            metrics.counter_add("kernel.declines")
            continue
        # the span makes each kernel its own flight-recorder/trace
        # track (nested inside dispatch.<op>); declines and fallbacks
        # are handled INSIDE it so they exit the span cleanly
        with metrics.span("kernel." + spec.name):
            try:
                faults.inject("kernel")
                out = spec.runner(op, table, rest)
            except (KernelDecline, bk._Decline):
                metrics.counter_add("kernel.declines")
                continue
            except (faults.Cancelled, faults.DeadlineExceeded):
                raise
            # srt: allow-broad-except(semantics-preserving fallback: the bucketed/exact path re-runs the op and raises the real error)
            except Exception as e:
                # the kernel tier must never change semantics: any
                # runner failure (Mosaic lowering refusal, seeded
                # chaos fault, shape surprise) replays on the exact
                # path, which raises the real error if the op itself
                # is at fault
                metrics.counter_add("kernel.fallbacks")
                profiler.note_fallback("kernel")
                if spec.name not in _WARNED_KERNELS:
                    _WARNED_KERNELS.add(spec.name)
                    log.log(
                        "WARN", "kernels", "kernel_runner_failed",
                        kernel=spec.name, op=name,
                        error=f"{type(e).__name__}: {str(e)[:200]}",
                    )
                return None
        metrics.counter_add("kernel.launches")
        return out
    return None
