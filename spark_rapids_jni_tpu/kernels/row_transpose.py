"""Pallas TPU kernels for the packed-row transpose.

The reference implements this pair as CUDA kernels staging through 48 KB
of shared memory with warp ballots for validity (row_conversion.cu:48-171
``copy_to_fixed_width_columns``, :173-304 ``copy_from_fixed_width_columns``).
The TPU redesign:

* Grid over row tiles; each grid step assembles/disassembles one
  ``(TILE_ROWS, row_size)`` uint8 block entirely in VMEM — the VMEM block
  is the 48 KB shared-memory stage, but sized by BlockSpec instead of a
  hand-tuned ``<<<blocks, threads, shared>>>`` geometry
  (row_conversion.cu:315-367 ``calc_fixed_width_kernel_dims``).
* 64-bit word handling stays outside the kernel: columns arrive as
  little-endian ``(n, width)`` uint8 matrices (bitcast is free/fused in
  XLA), so the kernel body is pure uint8/int32 — no Mosaic i64 paths.
* Validity bits: the CUDA side uses ``__ballot_sync`` + byte atomics
  (row_conversion.cu:158-165, :255-272). Here each row's (num_cols,) 0/1
  validity vector is packed LSB-first into bytes with a bit-weight
  dot-product over 8-wide groups — one vectorized reduction, no atomics
  (SURVEY.md §7 hard part 3).
* Ragged edges: row counts are padded to the tile multiple by the caller
  wrapper, never inside the kernel, so every grid step is full.

Dispatch policy lives in ``rows.py``: XLA fusion is the default backend
(it fuses the same assembly into one HBM-bound kernel); the Pallas pair is
selected explicitly (``backend="pallas"``) or by the auto heuristic for
large batches on TPU. Both produce bit-identical bytes — the golden
round-trip test runs each against the other.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import dtype as dt
from ..rows import RowLayout

# Rows per grid step. Multiple of 32 (the reference's validity-word batch
# alignment, row_conversion.cu:477-479) and of the int8 sublane tile (32).
TILE_ROWS = 512

# Typed zero for BlockSpec index maps: a bare python 0 traces as i64 under
# jax_enable_x64 and Mosaic refuses the (i32, i64) index tuple.
_Z = np.int32(0)


def _pad_rows(arr: jax.Array, n_padded: int) -> jax.Array:
    """Zero-pad axis 0 of ``arr`` to ``n_padded`` rows."""
    pad = n_padded - arr.shape[0]
    if pad == 0:
        return arr
    widths = ((0, pad),) + ((0, 0),) * (arr.ndim - 1)
    return jnp.pad(arr, widths)


def _pack_kernel(layout: RowLayout, *refs):
    """One grid step: assemble (TILE_ROWS, row_size) packed bytes.

    ``refs`` = per-column (TILE_ROWS, width) uint8 byte refs, then the
    (TILE_ROWS, num_cols) uint8 validity ref, then the output ref.
    """
    *col_refs, valid_ref, out_ref = refs
    num_cols = len(layout.dtypes)
    parts = []
    cursor = 0
    for ref, off, w in zip(
        col_refs, layout.column_offsets, layout.column_widths
    ):
        if off > cursor:  # alignment gap -> zero padding bytes
            parts.append(
                jnp.zeros((TILE_ROWS, off - cursor), dtype=jnp.uint8)
            )
        parts.append(ref[...])
        cursor = off + w
    if layout.validity_offset > cursor:
        parts.append(
            jnp.zeros(
                (TILE_ROWS, layout.validity_offset - cursor), dtype=jnp.uint8
            )
        )
    # Validity: (TILE, cols) 0/1 bytes -> LSB-first packed bytes via one
    # matmul against an in-kernel bit-weight selection matrix — the MXU
    # replacement for warp ballots/byte atomics (values <= 255, exact in
    # f32). 3-D reductions are avoided: Mosaic rejects them.
    vbytes = layout.validity_bytes
    v = valid_ref[...]
    if num_cols % 8:
        # no jnp.pad here: its weak-int64 fill value hits an unsupported
        # scalar i64->u8 convert in Mosaic; typed zeros lower cleanly
        v = jnp.concatenate(
            [
                v,
                jnp.zeros(
                    (TILE_ROWS, vbytes * 8 - num_cols), dtype=jnp.uint8
                ),
            ],
            axis=1,
        )
    # All literals below are typed scalars: with jax_enable_x64 on, a bare
    # python int promotes int32 arrays through int64, and Mosaic's i64->i32
    # array convert does not lower.
    vf = v.astype(jnp.int32).astype(jnp.float32)
    r = jax.lax.broadcasted_iota(jnp.int32, (vbytes * 8, vbytes), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (vbytes * 8, vbytes), 1)
    weights = jnp.where(
        r // jnp.int32(8) == c,
        jnp.int32(1) << (r % jnp.int32(8)),
        jnp.int32(0),
    ).astype(jnp.float32)
    packed = jnp.dot(vf, weights, preferred_element_type=jnp.float32)
    parts.append(packed.astype(jnp.int32).astype(jnp.uint8))
    tail = layout.row_size - (layout.validity_offset + vbytes)
    if tail:  # 64-bit row padding (row_conversion.cu:454-455)
        parts.append(jnp.zeros((TILE_ROWS, tail), dtype=jnp.uint8))
    out_ref[...] = jnp.concatenate(parts, axis=1)


@functools.partial(
    jax.jit, static_argnames=("layout", "interpret")
)
def pack_rows_pallas(
    col_bytes: tuple[jax.Array, ...],
    valid: jax.Array,
    layout: RowLayout,
    interpret: bool = False,
) -> jax.Array:
    """(n, w_i) uint8 byte matrices + (n, num_cols) 0/1 validity
    -> (n, row_size) packed rows. ``n`` may be any size; tiles are padded
    internally and the result sliced back.
    """
    n = valid.shape[0]
    n_padded = max((n + TILE_ROWS - 1) // TILE_ROWS * TILE_ROWS, TILE_ROWS)
    grid = n_padded // TILE_ROWS
    col_bytes = tuple(_pad_rows(c, n_padded) for c in col_bytes)
    valid = _pad_rows(valid, n_padded)

    in_specs = [
        pl.BlockSpec((TILE_ROWS, c.shape[1]), lambda i: (i, _Z))
        for c in col_bytes
    ]
    in_specs.append(
        pl.BlockSpec((TILE_ROWS, valid.shape[1]), lambda i: (i, _Z))
    )
    out = pl.pallas_call(
        functools.partial(_pack_kernel, layout),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (TILE_ROWS, layout.row_size), lambda i: (i, _Z)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (n_padded, layout.row_size), jnp.uint8
        ),
        interpret=interpret,
    )(*col_bytes, valid)
    return out[:n]


def _unpack_kernel(layout: RowLayout, rows_ref, *out_refs):
    """One grid step: split a (TILE_ROWS, row_size) block into per-column
    byte matrices + the (TILE_ROWS, num_cols) validity bytes."""
    *col_refs, valid_ref = out_refs
    tile = rows_ref[...]
    for ref, off, w in zip(
        col_refs, layout.column_offsets, layout.column_widths
    ):
        ref[...] = tile[:, off : off + w]
    num_cols = len(layout.dtypes)
    vbytes = layout.validity_bytes
    # Bit unpack without 3-D shapes: replicate each validity byte across
    # its 8 columns with a selection matmul, then shift/mask per column.
    vb = tile[
        :, layout.validity_offset : layout.validity_offset + vbytes
    ]
    # typed scalars throughout: see the weak-literal note in _pack_kernel
    vf = (vb.astype(jnp.int32) & jnp.int32(255)).astype(jnp.float32)
    r = jax.lax.broadcasted_iota(jnp.int32, (vbytes, vbytes * 8), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (vbytes, vbytes * 8), 1)
    expand = jnp.where(
        c // jnp.int32(8) == r, jnp.int32(1), jnp.int32(0)
    ).astype(jnp.float32)
    prod = jnp.dot(vf, expand, preferred_element_type=jnp.float32).astype(
        jnp.int32
    )
    shifts = jax.lax.broadcasted_iota(
        jnp.int32, (1, vbytes * 8), 1
    ) % jnp.int32(8)
    bits = (prod >> shifts) & jnp.int32(1)
    valid_ref[...] = bits[:, :num_cols].astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("layout", "interpret"))
def unpack_rows_pallas(
    rows: jax.Array, layout: RowLayout, interpret: bool = False
) -> tuple[list[jax.Array], jax.Array]:
    """(n, row_size) packed rows -> ([(n, w_i) uint8 ...], (n, cols) 0/1)."""
    n = rows.shape[0]
    n_padded = max((n + TILE_ROWS - 1) // TILE_ROWS * TILE_ROWS, TILE_ROWS)
    grid = n_padded // TILE_ROWS
    rows = _pad_rows(rows, n_padded)
    num_cols = len(layout.dtypes)

    out_shapes = [
        jax.ShapeDtypeStruct((n_padded, w), jnp.uint8)
        for w in layout.column_widths
    ]
    out_shapes.append(jax.ShapeDtypeStruct((n_padded, num_cols), jnp.uint8))
    out_specs = [
        pl.BlockSpec((TILE_ROWS, w), lambda i: (i, _Z))
        for w in layout.column_widths
    ]
    out_specs.append(pl.BlockSpec((TILE_ROWS, num_cols), lambda i: (i, _Z)))

    outs = pl.pallas_call(
        functools.partial(_unpack_kernel, layout),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((TILE_ROWS, layout.row_size), lambda i: (i, _Z))
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(rows)
    *cols, valid = outs
    return [c[:n] for c in cols], valid[:n]


# Single shared byte->storage decode (rows.py owns the rule).
from ..rows import column_bytes_to_storage  # noqa: E402,F401
