"""Device-mesh helpers: row-sharded tables over a 1-D (or the flattened
ICI) mesh — the unit of shuffle parallelism, one shard per chip.

On a v5e-8 pod slice this is an 8-way axis over ICI; across pods a second
DCN axis can be added (mesh shape (pods, chips_per_pod)) and the exchange
keeps partition-heavy traffic on the inner (ICI) axis.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import config, faults, flight, log, metrics

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

import inspect

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, **kwargs):
    """Version-compat chokepoint: jax renamed ``check_rep`` to
    ``check_vma``; callers here use the new name and this wrapper maps
    it onto whichever the installed jax accepts."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


from ..column import Column, Table

SHUFFLE_AXIS = "shuffle"


def make_mesh(
    n_devices: Optional[int] = None, axis: str = SHUFFLE_AXIS
) -> Mesh:
    """Build the 1-D shuffle mesh over the first ``n_devices`` devices.

    Loud-fail contract: a mesh-shape vs device-count mismatch names the
    requested shape AND the remedy instead of whatever XLA error would
    surface from the first collective. ``mesh`` is also an injection
    site — a chaos plan can make construction fail like a dead slice.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else int(n_devices)
    if n <= 0:
        raise ValueError(
            f"mesh axis {axis!r}: requested {n} devices; a mesh needs "
            "at least 1"
        )
    if n > len(devs):
        raise ValueError(
            f"mesh axis {axis!r}: requested {n} devices, have "
            f"{len(devs)} ({devs[0].platform}); shrink the mesh or, on "
            "the CPU test tier, set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    faults.inject("mesh")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_table(table: Table, mesh: Mesh, axis: str = SHUFFLE_AXIS) -> Table:
    """Row-shard every buffer across the mesh (dim 0 split, rest replicated).

    Row count must divide evenly by the axis size (pad upstream if not —
    the IO layer produces evenly-split batches).
    """
    n = table.row_count
    size = mesh.shape[axis]
    if n % size:
        raise ValueError(
            f"mesh axis {axis!r} (size {size}): row count {n} is not "
            f"divisible by the shard axis; pad the table to a multiple "
            f"of {size} (the planmesh wrapper does) or build a mesh "
            "whose size divides the row count"
        )

    def put(x):
        if x is None:
            return None
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, table)


def replicate_table(table: Table, mesh: Mesh) -> Table:
    """Fully replicate a (small, e.g. dimension) table on every device."""
    return jax.tree_util.tree_map(
        lambda x: None
        if x is None
        else jax.device_put(x, NamedSharding(mesh, P())),
        table,
    )


def local_shards(table: Table) -> int:
    """Number of addressable shards of the first buffer (introspection)."""
    return len(table.columns[0].data.addressable_shards)


class MeshHealth:
    """Cheap heartbeat probe for a mesh: one psum all-reduce with a
    deadline (``SPARK_RAPIDS_TPU_MESH_PROBE_S``).

    A mesh whose collective answers (with the right sum) within the
    deadline is healthy; a hang past the deadline or any raise —
    including an injected ``mesh``-site fault — marks it unhealthy.
    The heartbeat runs on a worker thread so a wedged collective costs
    the probe its deadline, never the caller its process.
    """

    def __init__(self, deadline_s: Optional[float] = None):
        self.deadline_s = (
            float(config.get_flag("MESH_PROBE_S"))
            if deadline_s is None else float(deadline_s)
        )

    def probe(self, mesh: Mesh, axis: str = SHUFFLE_AXIS) -> bool:
        """True iff every device on ``mesh`` answered the heartbeat."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as _P

        metrics.counter_add("mesh.probes")
        size = int(mesh.shape[axis])
        verdict = {}

        def beat():
            try:
                faults.inject("mesh")
                fn = shard_map(
                    lambda x: jax.lax.psum(x, axis),
                    mesh=mesh, in_specs=_P(axis), out_specs=_P(),
                    check_vma=False,
                )
                out = fn(jnp.ones((size,), jnp.int32))
                # srt: allow-host-sync(heartbeat verdict: the probe exists to block until the mesh answers)
                verdict["ok"] = int(out[0]) == size
            # srt: allow-broad-except(any heartbeat failure is an unhealthy verdict, classified below by the caller-facing metering)
            except Exception as e:
                verdict["ok"] = False
                verdict["error"] = e
                faults.note_error_class(e, "mesh.probe")

        t = threading.Thread(
            target=beat, name="srt-mesh-probe", daemon=True
        )
        t.start()
        t.join(self.deadline_s)
        if t.is_alive():
            # wedged collective: the deadline IS the verdict
            metrics.counter_add("mesh.probe_timeouts")
            if flight.enabled():
                flight.record("I", "mesh.probe_timeout", size)
            log.log(
                "WARN", "faults", "mesh_probe_timeout",
                devices=size, deadline_s=self.deadline_s,
            )
            return False
        ok = bool(verdict.get("ok"))
        if not ok:
            metrics.counter_add("mesh.probe_failures")
            if flight.enabled():
                flight.record("I", "mesh.probe_failure", size)
            err = verdict.get("error")
            log.log(
                "WARN", "faults", "mesh_probe_failure", devices=size,
                error=(
                    f"{type(err).__name__}: {str(err)[:200]}"
                    if err is not None else None
                ),
            )
        return ok
