"""Device-mesh helpers: row-sharded tables over a 1-D (or the flattened
ICI) mesh — the unit of shuffle parallelism, one shard per chip.

On a v5e-8 pod slice this is an 8-way axis over ICI; across pods a second
DCN axis can be added (mesh shape (pods, chips_per_pod)) and the exchange
keeps partition-heavy traffic on the inner (ICI) axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

import inspect

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, **kwargs):
    """Version-compat chokepoint: jax renamed ``check_rep`` to
    ``check_vma``; callers here use the new name and this wrapper maps
    it onto whichever the installed jax accepts."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


from ..column import Column, Table

SHUFFLE_AXIS = "shuffle"


def make_mesh(
    n_devices: Optional[int] = None, axis: str = SHUFFLE_AXIS
) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_table(table: Table, mesh: Mesh, axis: str = SHUFFLE_AXIS) -> Table:
    """Row-shard every buffer across the mesh (dim 0 split, rest replicated).

    Row count must divide evenly by the axis size (pad upstream if not —
    the IO layer produces evenly-split batches).
    """
    n = table.row_count
    size = mesh.shape[axis]
    if n % size:
        raise ValueError(
            f"row count {n} not divisible by mesh axis size {size}"
        )

    def put(x):
        if x is None:
            return None
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, table)


def replicate_table(table: Table, mesh: Mesh) -> Table:
    """Fully replicate a (small, e.g. dimension) table on every device."""
    return jax.tree_util.tree_map(
        lambda x: None
        if x is None
        else jax.device_put(x, NamedSharding(mesh, P())),
        table,
    )


def local_shards(table: Table) -> int:
    """Number of addressable shards of the first buffer (introspection)."""
    return len(table.columns[0].data.addressable_shards)
