"""Mesh data-parallel plan execution for row-local segments.

``run_plan_mesh`` runs a plan whose every op is row-local (``cast``,
``filter``, ``rlike`` — plan.py's ``_ROW_LOCAL``) as ONE shard_map
stage over a :class:`~.tolerant.MeshRunner`: rows split into contiguous
blocks (one per device), each shard runs the same fused segment body
the single-device path compiles (``plan._run_segment_traced``), and the
host gathers each shard's valid prefix back in mesh order.

Shuffle as a plan op (ISSUE 17): a plan may additionally carry ONE
``partition`` op (``plan._EXCHANGE_OPS``) anywhere in the chain. It is
the mesh segment boundary: the scan-side row-local chain, a two-phase
counts pass, a ragged all-to-all exchange, a device-local stable sort
back into partition order, and the merge-side row-local chain all run
as one planned pipeline under the same ``MeshRunner`` stage. The
exchange launches are ``shuffle``-site replay boundaries inside the
stage, so seeded shuffle faults replay losslessly from the host-side
lineage and persistent failure walks the degradation ladder like any
other stage.

Parity contract: row-local ops neither reorder rows nor look across
them, so block-sharded execution followed by an in-order prefix gather
is byte-identical to the single-device result — at ANY mesh size. The
partition boundary preserves this: the exact path's ``partition`` is a
stable reorder by partition id, and the mesh path maps the contiguous
pid range ``[d*num//size, (d+1)*num//size)`` to device ``d`` (monotonic
in pid), exchanges rows in stable (src, in-src) order, and stable-sorts
each device's received prefix by recomputed pid — so device ``d`` holds
exactly the ``d``-th contiguous slice of the exact path's reordered
table and the in-order gather is byte-identical, again at ANY mesh
size. That mesh-size independence is what makes the degradation ladder
safe here: when the runner remeshes to fewer devices mid-incident and
replays, the stage re-derives shard layout, counts, and capacities from
the captured host-side lineage (the undonated input table + ops) at the
new size and the bytes do not change.

Anything else — multi-table rest inputs, non-row-local chain ops, more
than one partition boundary, padded inputs — raises
:class:`MeshUnsupported` and the caller falls through to the ordinary
single-device plan path.

``run_plan_mesh_stream`` drives a SEQUENCE of batches through the same
plan with exchange/compute overlap: batch N+1's scan-side counts pass
and host-side pack are staged on the pipeline workers
(``pipeline.stage_ahead``) while batch N's exchange launch runs on the
caller thread — the overlap shows up as ``pipeline.overlap_ms``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..column import Column, Table
from ..utils import metrics
from .mesh import SHUFFLE_AXIS, shard_map
from .tolerant import MeshRunner, run_collective


class MeshUnsupported(Exception):
    """This plan/input shape has no mesh path; use the exact path."""


def _split_at_exchange(ops: Sequence[dict]):
    """``(pre_ops, partition_op | None, post_ops)`` — the plan split at
    its (single) exchange boundary."""
    from .. import plan as plan_mod

    idx = [
        i for i, o in enumerate(ops)
        if o.get("op") in plan_mod._EXCHANGE_OPS
    ]
    if not idx:
        return list(ops), None, []
    if len(idx) > 1:
        raise MeshUnsupported(
            "mesh path handles one partition boundary per plan; "
            f"got {len(idx)}"
        )
    i = idx[0]
    return list(ops[:i]), ops[i], list(ops[i + 1:])


def _check_supported(ops: Sequence[dict], table: Table,
                     rest: Sequence[Table]):
    from .. import plan as plan_mod

    if rest:
        raise MeshUnsupported("mesh plan path takes no rest tables")
    if not ops:
        raise MeshUnsupported("empty plan")
    if not table.columns or table.logical_row_count == 0:
        raise MeshUnsupported("empty table")
    pre, part, post = _split_at_exchange(ops)
    for op in (*pre, *post):
        name = op.get("op")
        if name not in plan_mod._ROW_LOCAL:
            raise MeshUnsupported(
                f"op {name!r} is not row-local; mesh path handles "
                f"{sorted(plan_mod._ROW_LOCAL)} chains (around one "
                "optional partition boundary) only"
            )
    if part is not None and part.get("kind", "hash") == "range" and pre:
        # range splitters are sampled from the exchange INPUT; with a
        # scan-side chain that input only exists per shard mid-stage,
        # so the deterministic full-table sample the exact path draws
        # is unavailable — decline rather than break byte parity
        raise MeshUnsupported(
            "range partition needs an empty scan-side chain: splitters "
            "are sampled from the full exchange input"
        )
    return pre, part, post


def _pack_sharded(table: Table, mesh, axis: str, n: int):
    """(padded sharded table, per-shard valid counts) for a contiguous
    row-block layout — the host-side pack step."""
    size = int(mesh.shape[axis])
    per = -(-n // size)  # ceil: contiguous row blocks, one per dev
    pad = per * size - n

    def padleaf(x):
        if pad:
            x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
        return jax.device_put(
            x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        )

    pt = jax.tree_util.tree_map(padleaf, table)
    counts = np.clip(n - np.arange(size) * per, 0, per).astype(np.int32)
    cnt = jax.device_put(
        jnp.asarray(counts), NamedSharding(mesh, P(axis))
    )
    return pt, cnt


def _gather_prefix(out_t: Table, out_c, size: int) -> Table:
    """Host-side gather: each shard's valid prefix, in mesh order —
    exactly the single-device result for row-local segments."""
    # srt: allow-host-sync(result materialization: the stage's output IS these host bytes)
    got = np.asarray(jax.device_get(out_c))
    per_out = out_t.row_count // size

    def take(x):
        if x is None:
            return None
        # srt: allow-host-sync(result materialization: gathering the sharded output to host)
        full = np.asarray(jax.device_get(x))
        return np.concatenate(
            [full[i * per_out:i * per_out + int(got[i])]
             for i in range(size)]
        )

    cols = []
    for c in out_t.columns:
        cols.append(Column(
            data=jnp.asarray(take(c.data)),
            dtype=c.dtype,
            validity=(
                None if c.validity is None
                else jnp.asarray(take(c.validity))
            ),
            lengths=(
                None if c.lengths is None
                else jnp.asarray(take(c.lengths))
            ),
        ))
    return Table(cols, names=out_t.names)


def _rowlocal_stage(seg_ops, table: Table, n: int, axis: str):
    """Stage closure for a pure row-local plan (no exchange boundary)."""
    from .. import plan as plan_mod

    def stage(mesh):
        # re-derived per replay: a smaller surviving mesh re-plans the
        # shard layout + per-shard valid counts from the same lineage
        size = int(mesh.shape[axis])
        pt, cnt = _pack_sharded(table, mesh, axis, n)

        def body(local, c):
            t2, n2 = plan_mod._run_segment_traced(seg_ops, local, c[0])
            return t2, jnp.reshape(n2, (1,)).astype(jnp.int32)

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
        out_t, out_c = fn(pt, cnt)
        return _gather_prefix(out_t, out_c, size)

    return stage


def _partition_stage(pre, part, post, table: Table, n: int, axis: str,
                     prepared: Optional[dict] = None):
    """Stage closure for a plan with one partition boundary: scan-side
    chain -> counts pass -> ragged exchange -> stable pid sort ->
    merge-side chain, all re-derivable from the host-side lineage.

    ``prepared`` (from :func:`prepare_exchange`) carries a pack + counts
    pass already run for a specific mesh — reused only when the stage
    executes on that same mesh; any replay on a degraded mesh
    re-derives both.
    """
    from .. import plan as plan_mod
    from ..ops import partition as partition_mod
    from ..utils import config, planstats
    from .shuffle import (
        _ragged_impl,
        _round_capacity,
        check_overflow_compact,
        exchange_ragged,
        total_recv_capacity,
    )

    num = int(part["num"])
    keys = list(part.get("keys", []))
    kind = part.get("kind", "hash")
    impl = _ragged_impl(None)
    # range splitters come from the full host-side exchange input — the
    # same deterministic sample the exact path draws, so partition ids
    # agree byte-for-byte (scan-side chain is empty, per _check_supported)
    splitters = (
        partition_mod.range_splitters(table, keys, num)
        if kind == "range" else None
    )

    def pids_of(local: Table):
        if kind == "hash":
            return partition_mod.partition_ids_hash(
                local, keys or None, num
            )
        return partition_mod.partition_ids_range(local, keys, splitters)

    def counts_pass(mesh, pt, cnt, size):
        """Scan-side chain + per-(src, dst-device) planned send counts
        — the two-phase sizing pass, a shuffle-site replay boundary."""

        def count_body(local, c):
            t2, n2 = plan_mod._run_segment_traced(pre, local, c[0])
            rv = jnp.arange(t2.row_count, dtype=jnp.int32) < n2
            pid = pids_of(t2)
            dd = jnp.where(
                rv, (pid * size) // num, size
            ).astype(jnp.int32)
            return jnp.bincount(dd, length=size + 1)[:size].astype(
                jnp.int32
            )[None, :]

        fn = shard_map(
            count_body, mesh=mesh,
            in_specs=(P(axis), P(axis)), out_specs=P(axis),
            check_vma=False,
        )
        return run_collective(
            "plan.partition_counts", lambda: fn(pt, cnt), site="shuffle"
        )

    def stage(mesh):
        size = int(mesh.shape[axis])
        if (
            prepared is not None
            and prepared.get("mesh") is mesh
            and prepared.get("size") == size
        ):
            pt, cnt = prepared["pt"], prepared["cnt"]
            counts = prepared["counts"]
        else:
            pt, cnt = _pack_sharded(table, mesh, axis, n)
            counts = counts_pass(mesh, pt, cnt, size)
        cap = total_recv_capacity(counts)
        # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
        pair_cap = _round_capacity(int(jnp.max(counts)))
        # observe (not split: a pure redistribution has no agg to make
        # salting lossless) planned recv skew across destinations — the
        # planstats drift surface for partition-op plans
        # srt: allow-host-sync(two-phase sizing: the skew observation reads the planned counts)
        recv = np.asarray(jax.device_get(jnp.sum(counts, axis=0)))
        mean = float(recv.mean()) if recv.size else 0.0
        factor = float(config.get_flag("SKEW_SPLIT_FACTOR"))
        if mean > 0 and float(recv.max()) > factor * mean:
            planstats.note_skew({
                "site": "plan.partition",
                "action": "observed",
                "max_recv": int(recv.max()),
                "mean_recv": mean,
                "ratio": float(recv.max()) / mean,
                "factor": factor,
                "devices": size,
            })

        def body(local, c, C):
            t2, n2 = plan_mod._run_segment_traced(pre, local, c[0])
            rv = jnp.arange(t2.row_count, dtype=jnp.int32) < n2
            pid = pids_of(t2)
            dd = ((pid * size) // num).astype(jnp.int32)
            out, occ, overflow = exchange_ragged(
                t2, dd, C, cap, axis, impl, row_valid=rv,
                pair_capacity=pair_cap,
            )
            # restore the exact path's order: received rows arrive in
            # stable (src, in-src) order; a stable sort by recomputed
            # pid (padding keyed past every real pid) makes this device
            # hold its contiguous slice of the globally pid-sorted table
            pid2 = pids_of(out)
            skey = jnp.where(occ, pid2.astype(jnp.int32), num)
            perm = jnp.argsort(skey, stable=True).astype(jnp.int32)
            sorted_t = jax.tree_util.tree_map(
                lambda x: None if x is None else x[perm], out
            )
            n_recv = jnp.sum(occ.astype(jnp.int32))
            t3, n3 = plan_mod._run_segment_traced(post, sorted_t, n_recv)
            return (
                t3,
                jnp.reshape(n3, (1,)).astype(jnp.int32),
                jnp.reshape(overflow, (1,)).astype(jnp.int32),
            )

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis), P(axis)),
            check_vma=False,
        )
        out_t, out_c, out_ov = run_collective(
            "plan.partition_exchange",
            lambda: fn(pt, cnt, counts),
            site="shuffle",
        )
        # capacity came from the real counts, so overflow means a bug —
        # surface it loudly rather than gathering a truncated result
        check_overflow_compact(out_ov, cap, "plan partition")
        if metrics.enabled():
            metrics.counter_add("partition.mesh_segments")
            metrics.counter_add("partition.rows_exchanged", n)
        return _gather_prefix(out_t, out_c, size)

    return stage


def run_plan_mesh(
    ops: Sequence[dict],
    table: Table,
    runner: MeshRunner,
    rest: Sequence[Table] = (),
) -> Table:
    """Run a row-local plan (optionally around one ``partition``
    boundary) data-parallel over ``runner``'s mesh.

    Never consumes ``table`` (the un-donated input IS the replay
    lineage); returns the exact (unpadded) result table. Raises
    :class:`MeshUnsupported` when the plan has no mesh path and
    :class:`~..utils.faults.Degraded` when the runner's ladder hits
    its device floor.
    """
    from ..utils import buckets

    pre, part, post = _check_supported(ops, table, rest)
    # a bucket-padded wire upload shrinks to its real rows first: the
    # mesh stage derives its own shard padding, and the caller's padded
    # input stays untouched (it is the fallback path's donation)
    table = buckets.unpad_table(table)
    n = int(table.row_count)
    axis = runner.axis
    if part is None:
        return runner.run_stage(
            "plan.mesh", _rowlocal_stage(list(ops), table, n, axis)
        )
    return runner.run_stage(
        "plan.mesh.partition",
        _partition_stage(pre, part, post, table, n, axis),
    )


def prepare_exchange(ops: Sequence[dict], table: Table,
                     runner: MeshRunner) -> Optional[dict]:
    """Stage the host-side pack + scan-side counts pass for ``table``
    at the runner's CURRENT mesh — the work ``run_plan_mesh_stream``
    overlaps with the previous batch's exchange launch. Returns the
    prepared dict ``_partition_stage`` consumes, or None when the plan
    has no partition boundary (nothing worth staging ahead)."""
    from ..utils import buckets

    pre, part, post = _check_supported(ops, table, ())
    if part is None:
        return None
    table = buckets.unpad_table(table)
    n = int(table.row_count)
    axis = runner.axis
    mesh = runner.mesh
    size = int(mesh.shape[axis])
    pt, cnt = _pack_sharded(table, mesh, axis, n)
    from .. import plan as plan_mod
    from ..ops import partition as partition_mod

    num = int(part["num"])  # srt: allow-host-sync(plan literal, not a device value)
    keys = list(part.get("keys", []))
    kind = part.get("kind", "hash")
    splitters = (
        partition_mod.range_splitters(table, keys, num)
        if kind == "range" else None
    )

    def count_body(local, c):
        t2, n2 = plan_mod._run_segment_traced(pre, local, c[0])
        rv = jnp.arange(t2.row_count, dtype=jnp.int32) < n2
        if kind == "hash":
            pid = partition_mod.partition_ids_hash(t2, keys or None, num)
        else:
            pid = partition_mod.partition_ids_range(t2, keys, splitters)
        dd = jnp.where(rv, (pid * size) // num, size).astype(jnp.int32)
        return jnp.bincount(dd, length=size + 1)[:size].astype(
            jnp.int32
        )[None, :]

    fn = shard_map(
        count_body, mesh=mesh,
        in_specs=(P(axis), P(axis)), out_specs=P(axis),
        check_vma=False,
    )
    counts = run_collective(
        "plan.partition_counts", lambda: fn(pt, cnt), site="shuffle"
    )
    return {
        "mesh": mesh, "size": size, "pt": pt, "cnt": cnt,
        "counts": counts,
    }


def run_plan_mesh_stream(
    ops: Sequence[dict],
    batches: Sequence[Table],
    runner: MeshRunner,
) -> list:
    """Drive ``batches`` through one plan with exchange/compute overlap.

    While batch N's exchange launch runs on the caller thread, batch
    N+1's scan-side counts pass and host-side pack run on the pipeline
    workers (``pipeline.stage_ahead``; worker busy time is metered as
    ``pipeline.overlap_ms``). With the pipeline off, batches run
    sequentially — byte-identical results either way, in input order.
    Degradation safety: a prepared pack targets the mesh it was staged
    for; if the runner degraded in between, the stage re-derives from
    the host-side lineage at the new size.
    """
    from .. import pipeline

    batches = list(batches)
    if not batches:
        return []
    pre, part, post = _check_supported(ops, batches[0], ())

    def prepare(b: Table):
        return (b, prepare_exchange(ops, b, runner))

    def execute(prepped):
        b, prepared = prepped
        from ..utils import buckets

        t = buckets.unpad_table(b)
        n = int(t.row_count)
        axis = runner.axis
        if part is None:
            return runner.run_stage(
                "plan.mesh", _rowlocal_stage(list(ops), t, n, axis)
            )
        return runner.run_stage(
            "plan.mesh.partition",
            _partition_stage(pre, part, post, t, n, axis,
                             prepared=prepared),
        )

    return pipeline.stage_ahead(batches, prepare, execute, "mesh.prepare")
