"""Mesh data-parallel plan execution for row-local segments.

``run_plan_mesh`` runs a plan whose every op is row-local (``cast``,
``filter``, ``rlike`` — plan.py's ``_ROW_LOCAL``) as ONE shard_map
stage over a :class:`~.tolerant.MeshRunner`: rows split into contiguous
blocks (one per device), each shard runs the same fused segment body
the single-device path compiles (``plan._run_segment_traced``), and the
host gathers each shard's valid prefix back in mesh order.

Parity contract: row-local ops neither reorder rows nor look across
them, so block-sharded execution followed by an in-order prefix gather
is byte-identical to the single-device result — at ANY mesh size. That
mesh-size independence is what makes the degradation ladder safe here:
when the runner remeshes to fewer devices mid-incident and replays, the
stage re-derives shard layout and per-shard valid counts from the
captured host-side lineage (the undonated input table + ops) at the new
size and the bytes do not change.

Anything else — multi-table rest inputs, non-row-local ops, padded
inputs — raises :class:`MeshUnsupported` and the caller falls through
to the ordinary single-device plan path.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..column import Column, Table
from .mesh import SHUFFLE_AXIS, shard_map
from .tolerant import MeshRunner


class MeshUnsupported(Exception):
    """This plan/input shape has no mesh path; use the exact path."""


def _check_supported(ops: Sequence[dict], table: Table,
                     rest: Sequence[Table]) -> None:
    from .. import plan as plan_mod

    if rest:
        raise MeshUnsupported("mesh plan path takes no rest tables")
    if not ops:
        raise MeshUnsupported("empty plan")
    if not table.columns or table.logical_row_count == 0:
        raise MeshUnsupported("empty table")
    for op in ops:
        name = op.get("op")
        if name not in plan_mod._ROW_LOCAL:
            raise MeshUnsupported(
                f"op {name!r} is not row-local; mesh path handles "
                f"{sorted(plan_mod._ROW_LOCAL)} only"
            )


def run_plan_mesh(
    ops: Sequence[dict],
    table: Table,
    runner: MeshRunner,
    rest: Sequence[Table] = (),
) -> Table:
    """Run a row-local plan data-parallel over ``runner``'s mesh.

    Never consumes ``table`` (the un-donated input IS the replay
    lineage); returns the exact (unpadded) result table. Raises
    :class:`MeshUnsupported` when the plan has no mesh path and
    :class:`~..utils.faults.Degraded` when the runner's ladder hits
    its device floor.
    """
    from .. import plan as plan_mod
    from ..utils import buckets

    _check_supported(ops, table, rest)
    # a bucket-padded wire upload shrinks to its real rows first: the
    # mesh stage derives its own shard padding, and the caller's padded
    # input stays untouched (it is the fallback path's donation)
    table = buckets.unpad_table(table)
    seg_ops = list(ops)
    n = int(table.row_count)
    axis = runner.axis

    def stage(mesh):
        # re-derived per replay: a smaller surviving mesh re-plans the
        # shard layout + per-shard valid counts from the same lineage
        size = int(mesh.shape[axis])
        per = -(-n // size)  # ceil: contiguous row blocks, one per dev
        pad = per * size - n

        def padleaf(x):
            if pad:
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            return jax.device_put(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
            )

        pt = jax.tree_util.tree_map(padleaf, table)
        counts = np.clip(n - np.arange(size) * per, 0, per).astype(
            np.int32
        )
        cnt = jax.device_put(
            jnp.asarray(counts), NamedSharding(mesh, P(axis))
        )

        def body(local, c):
            t2, n2 = plan_mod._run_segment_traced(seg_ops, local, c[0])
            return t2, jnp.reshape(n2, (1,)).astype(jnp.int32)

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
        out_t, out_c = fn(pt, cnt)

        # host-side gather: each shard's valid prefix, in mesh order —
        # exactly the single-device result for row-local segments
        # srt: allow-host-sync(result materialization: the stage's output IS these host bytes)
        got = np.asarray(jax.device_get(out_c))
        per_out = out_t.row_count // size

        def take(x):
            if x is None:
                return None
            # srt: allow-host-sync(result materialization: gathering the sharded output to host)
            full = np.asarray(jax.device_get(x))
            return np.concatenate(
                [full[i * per_out:i * per_out + int(got[i])]
                 for i in range(size)]
            )

        cols = []
        for c in out_t.columns:
            cols.append(Column(
                data=jnp.asarray(take(c.data)),
                dtype=c.dtype,
                validity=(
                    None if c.validity is None
                    else jnp.asarray(take(c.validity))
                ),
                lengths=(
                    None if c.lengths is None
                    else jnp.asarray(take(c.lengths))
                ),
            ))
        return Table(cols, names=out_t.names)

    return runner.run_stage("plan.mesh", stage)
