"""Shuffle exchange: the ICI all-to-all replacement for the RAPIDS
UCX/NCCL shuffle manager (SURVEY.md §2.5, §5.8).

``exchange`` is called *inside* ``shard_map``: each device buckets its
local rows by Spark-compatible partition id (pmod(murmur3)), packs them
into fixed-capacity per-destination send buffers, and one
``jax.lax.all_to_all`` moves every bucket to its owner over ICI. Fixed
capacity keeps shapes static for XLA (the shuffle-side instance of the
two-phase discipline); received padding is tracked with an occupancy mask
that downstream capped ops treat as absent rows.

``shuffle_table`` is the host-level wrapper: shard -> plan capacity
(exact per-(src,dst) counts, the generalization of the reference's
two-phase sizing, row_conversion.cu:505-511) -> shard_map(exchange)
-> globally sharded padded table + occupancy. The default path is
LOSSLESS: capacity is planned from the real counts, and any overflow
(possible only with an explicit undersized ``capacity``) raises
``ShuffleOverflowError`` instead of silently dropping rows.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..column import Column, Table
from ..ops.partition import partition_ids_hash
from ..utils import faults, flight, metrics, profiler
from .mesh import SHUFFLE_AXIS, shard_map, shard_table
from .tolerant import run_collective


class ShuffleOverflowError(faults.PermanentError):
    """An exchange received more rows for a (src, dst) pair than its
    static capacity — rows would have been dropped. Raised by the host
    wrappers; never silent.

    Typed as :class:`~..utils.faults.PermanentError`: a replay at the
    same capacity overflows identically, so retry/breaker accounting
    must not treat it as transient (``faults.retryable_class`` is False
    and the breaker ignores it). Still a ``RuntimeError`` subclass via
    ``FaultError`` for existing callers."""


def validate_on_overflow(on_overflow: str) -> None:
    """Shared host-wrapper argument check: typos must not silently
    disable overflow detection."""
    if on_overflow not in ("raise", "allow"):
        raise ValueError(
            f"on_overflow must be 'raise' or 'allow', got {on_overflow!r}"
        )


def check_overflow(
    overflow,
    capacity: int,
    what: str,
    unit: str = "rows per (src, dst) pair",
    remedy: str = "pass capacity=None to auto-plan",
) -> None:
    """Raise ``ShuffleOverflowError`` if any device reported overflow."""
    # srt: allow-host-sync(lossless-exchange verdict: the overflow check exists to block until the counts land)
    worst = int(jnp.max(overflow))
    if worst > 0:
        raise ShuffleOverflowError(
            f"{what} exchange capacity {capacity} undersized by {worst} "
            f"{unit}; {remedy}"
        )


def check_overflow_compact(overflow, out_size: int, what: str) -> None:
    """Overflow check for the ragged-compact exchange, whose capacity is
    the TOTAL per-device receive buffer (not a per-pair slot count)."""
    check_overflow(
        overflow,
        out_size,
        what,
        unit="rows in the per-device receive buffer",
        remedy="pass out_size=None / capacity=None to auto-plan",
    )


def partition_counts(
    sharded: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    axis: str = SHUFFLE_AXIS,
) -> jax.Array:
    """(num, num) per-(src, dst) row counts — the shuffle planning pass.

    Row [s, d] is how many of source s's rows hash to partition d. The
    max entry is the exact minimal per-pair exchange capacity.
    """
    num = int(mesh.shape[axis])

    def body(local: Table):
        dest = partition_ids_hash(local, columns, num)
        return jnp.bincount(dest, length=num).astype(jnp.int32)[None, :]

    fn = shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    # the counts matrix IS the lineage for everything downstream: its
    # launch gets the same replay boundary as the exchange itself
    return run_collective(
        "shuffle.partition_counts", lambda: fn(sharded), site="shuffle"
    )


def _round_capacity(exact: int) -> int:
    """Round a planned capacity up to the next power of two (min 16) so
    repeated shuffles of similar volume reuse one compiled executable."""
    cap = 16
    while cap < exact:
        cap *= 2
    return cap


def plan_capacity(
    sharded: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    axis: str = SHUFFLE_AXIS,
) -> int:
    """Exact-overflow-free exchange capacity for ``sharded`` (host sync)."""
    with metrics.span("shuffle.plan"):
        counts = partition_counts(sharded, columns, mesh, axis)
        # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
        cap = _round_capacity(int(jnp.max(counts)))
    if metrics.enabled():
        metrics.counter_add("shuffle.plans")
        metrics.gauge_set("shuffle.pair_capacity", cap)
    return cap


def exchange(
    local: Table,
    dest: jax.Array,
    num_partitions: int,
    capacity: int,
    axis: str = SHUFFLE_AXIS,
    row_valid: Optional[jax.Array] = None,
):
    """All-to-all one device's rows to their destination partitions.

    Must run inside ``shard_map`` over ``axis`` (axis size ==
    ``num_partitions``). Returns (received table padded to
    ``num_partitions * capacity`` rows, occupancy mask, overflow counts):
    rows beyond ``capacity`` per (src, dst) pair are DROPPED — callers
    size ``capacity`` from the partitioning stats and must check
    ``overflow`` (max per-dest count) when in doubt.
    """
    n = local.row_count
    ok = (
        row_valid
        if row_valid is not None
        else jnp.ones((n,), dtype=jnp.bool_)
    )
    # invalid rows -> bucket num_partitions (beyond every real partition)
    dest = jnp.where(ok, dest, num_partitions).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    counts = jnp.bincount(dest, length=num_partitions + 1)[
        :num_partitions
    ].astype(jnp.int32)
    start = jnp.cumsum(counts) - counts

    j = jnp.arange(capacity, dtype=jnp.int32)
    flat_idx = jnp.clip(start[:, None] + j[None, :], 0, max(n - 1, 0))
    idx = order[flat_idx]  # (P, cap) source row per slot
    slot_valid = j[None, :] < jnp.minimum(counts[:, None], capacity)

    def pack(x):
        if x is None:
            return None
        return x[idx]  # (P, cap, ...)

    send = jax.tree_util.tree_map(pack, local)
    recv = jax.tree_util.tree_map(
        lambda x: None
        if x is None
        else jax.lax.all_to_all(x, axis, 0, 0),
        send,
    )
    recv_valid = jax.lax.all_to_all(slot_valid, axis, 0, 0)

    def flatten(x):
        if x is None:
            return None
        return x.reshape((num_partitions * capacity,) + x.shape[2:])

    out = jax.tree_util.tree_map(flatten, recv)
    occupancy = recv_valid.reshape((num_partitions * capacity,))
    overflow = jnp.max(counts) - capacity  # > 0 => rows were dropped
    return out, occupancy, overflow


def exchange_by_hash(
    local: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
    capacity: int,
    axis: str = SHUFFLE_AXIS,
    row_valid: Optional[jax.Array] = None,
):
    """exchange() keyed by Spark hash partitioning of ``columns``."""
    dest = partition_ids_hash(local, columns, num_partitions)
    return exchange(local, dest, num_partitions, capacity, axis, row_valid)


def total_recv_capacity(counts) -> int:
    """Per-device compact-exchange buffer size: the max over destinations
    of the TOTAL rows received (host sync), rounded. This is the SPMD
    floor — under a static-shape SPMD program every device materializes
    the same output shape, so the best possible per-device buffer is the
    hottest destination's actual row total, NOT num_partitions x the
    hottest (src, dst) pair (the round-2 skew-OOM failure mode)."""
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    cap = _round_capacity(int(jnp.max(jnp.sum(counts, axis=0))))
    if metrics.enabled():
        metrics.counter_add("shuffle.plans")
        metrics.gauge_set("shuffle.recv_capacity", cap)
    return cap


class SkewPlan:
    """The adaptive-skew decision from the planning counts (ISSUE 17).

    ``engaged`` means at least one destination's planned recv total
    exceeds ``factor x`` the mean — the Spark AQE skew-join-split
    signal, read here from the same two-phase counts the capacity
    sizing already computes. ``k`` is the salt fan-out: hot keys spread
    across ``k`` sub-partitions, sized so each carries roughly a mean
    destination's rows.
    """

    __slots__ = ("engaged", "factor", "k", "hot", "max_recv", "mean_recv")

    def __init__(self, engaged, factor, k, hot, max_recv, mean_recv):
        self.engaged = engaged
        self.factor = factor
        self.k = k
        self.hot = tuple(hot)
        self.max_recv = max_recv
        self.mean_recv = mean_recv

    @property
    def ratio(self) -> float:
        return (
            self.max_recv / self.mean_recv if self.mean_recv > 0 else 0.0
        )

    def to_doc(self) -> dict:
        return {
            "engaged": self.engaged,
            "factor": self.factor,
            "k": self.k,
            "hot_destinations": list(self.hot),
            "max_recv": self.max_recv,
            "mean_recv": self.mean_recv,
            "ratio": self.ratio,
        }


def plan_skew(counts, factor: Optional[float] = None) -> SkewPlan:
    """Skew decision for a planned exchange (host sync, planning pass).

    ``counts`` is the (P, P) per-(src, dst) matrix from
    :func:`partition_counts`. Destinations whose planned recv totals
    (column sums) exceed ``factor x`` the mean are hot; ``factor``
    defaults to the ``SKEW_SPLIT_FACTOR`` flag and the whole machinery
    gates on the ``SKEW_SPLIT`` master switch.
    """
    import numpy as np

    from ..utils import config

    if factor is None:
        factor = float(config.get_flag("SKEW_SPLIT_FACTOR"))
    raw = config.get_flag("SKEW_SPLIT")
    # test overrides arrive unparsed ("0" must read as off, like the env)
    split_on = config._as_bool(raw) if isinstance(raw, str) else bool(raw)
    # srt: allow-host-sync(two-phase sizing: the skew decision is part of the planning pass)
    recv = np.asarray(jax.device_get(jnp.sum(counts, axis=0))).astype(
        np.int64
    )
    num = int(recv.shape[0])
    total = int(recv.sum())
    max_recv = int(recv.max()) if recv.size else 0
    mean = total / num if num else 0.0
    if not split_on or num < 2 or total == 0:
        return SkewPlan(False, factor, 1, (), max_recv, mean)
    hot = [int(d) for d in np.nonzero(recv > factor * mean)[0]]
    if not hot:
        return SkewPlan(False, factor, 1, (), max_recv, mean)
    k = int(min(num, max(2, -(-max_recv // max(int(mean), 1)))))
    if metrics.enabled():
        metrics.gauge_set("shuffle.skew_k", k)
        metrics.gauge_set("shuffle.skew_hot_destinations", len(hot))
    return SkewPlan(True, factor, k, hot, max_recv, mean)


def _ragged_impl(impl: Optional[str]) -> str:
    """Resolve the exchange implementation for the active backend.

    ``ragged`` is the TPU path: one ``jax.lax.ragged_all_to_all``
    collective moving exactly the real rows over ICI. XLA:CPU does not
    implement ragged-all-to-all, so the virtual-mesh test tier uses
    ``dense_compact``: a uniform ``all_to_all`` at per-pair capacity
    followed by an on-device compaction to the identical ragged layout
    (same rows, same order — the impls are interchangeable oracle-wise).
    """
    if impl is not None:
        if impl not in ("ragged", "dense_compact"):
            raise ValueError(f"unknown exchange impl {impl!r}")
        return impl
    platform = jax.devices()[0].platform
    return "ragged" if platform in ("tpu", "axon") else "dense_compact"


def exchange_ragged(
    local: Table,
    dest: jax.Array,
    counts: jax.Array,
    out_size: int,
    axis: str = SHUFFLE_AXIS,
    impl: str = "dense_compact",
    row_valid: Optional[jax.Array] = None,
    pair_capacity: Optional[int] = None,
):
    """Compact all-to-all: each device receives exactly its real rows.

    Must run inside ``shard_map`` over ``axis``. ``counts`` is the global
    (P, P) per-(src, dst) row-count matrix from :func:`partition_counts`
    (replicated). The received layout is ragged-compact: ``[src-0 rows |
    src-1 rows | ...]`` with all padding at the tail — so the per-device
    buffer is ``out_size`` rows total (sized by
    :func:`total_recv_capacity`), not ``P x pair_capacity``. Returns
    (compact table padded to ``out_size`` rows, occupancy mask,
    overflow = rows received beyond ``out_size``).
    """
    num = counts.shape[0]
    s = jax.lax.axis_index(axis)
    n = local.row_count
    ok = (
        row_valid
        if row_valid is not None
        else jnp.ones((n,), dtype=jnp.bool_)
    )
    dest = jnp.where(ok, dest, num).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    csort = jax.tree_util.tree_map(
        lambda x: None if x is None else x[order], local
    )

    C = counts.astype(jnp.int32)
    send_sizes = C[s]  # (P,)
    input_offsets = jnp.cumsum(send_sizes) - send_sizes
    # receiver d lays out sender blocks in src order: sender s's block
    # starts at sum_{s'<s} C[s', d]
    output_offsets_all = jnp.cumsum(C, axis=0) - C  # (src, dst)
    output_offsets = output_offsets_all[s]
    recv_sizes = C[:, s]
    n_recv = jnp.sum(recv_sizes)

    if impl == "ragged":
        # clamp so an explicit undersized out_size can never write out of
        # bounds; the dropped tail is reported via overflow and raised by
        # the host wrappers
        off_c = jnp.minimum(output_offsets, out_size)
        send_c = jnp.minimum(send_sizes, jnp.maximum(out_size - off_c, 0))
        recv_off = jnp.minimum(output_offsets_all[:, s], out_size)
        recv_c = jnp.minimum(
            recv_sizes, jnp.maximum(out_size - recv_off, 0)
        )

        def ex(x):
            if x is None:
                return None
            wire = x.astype(jnp.uint8) if x.dtype == jnp.bool_ else x
            out = jnp.zeros((out_size,) + wire.shape[1:], wire.dtype)
            r = jax.lax.ragged_all_to_all(
                wire, out, input_offsets, send_c, off_c, recv_c,
                axis_name=axis,
            )
            return r.astype(x.dtype) if x.dtype == jnp.bool_ else r

        out_tbl = jax.tree_util.tree_map(ex, csort)
        occupancy = jnp.arange(out_size, dtype=jnp.int32) < n_recv
        overflow = n_recv - out_size
        return out_tbl, occupancy, overflow

    # dense_compact: uniform all_to_all at per-pair capacity, then an
    # on-device compaction to the identical ragged layout (CPU test
    # tier). The transient (P, pair_cap) buffers shrink to the real
    # hottest-pair count when the host wrapper threads it through
    # (pair_capacity from the planning counts); out_size is only the
    # always-correct fallback bound.
    pair_cap = min(pair_capacity or out_size, out_size)
    j = jnp.arange(pair_cap, dtype=jnp.int32)
    start = input_offsets
    flat_idx = jnp.clip(start[:, None] + j[None, :], 0, max(n - 1, 0))
    idx = order[flat_idx]
    slot_valid = j[None, :] < jnp.minimum(send_sizes[:, None], pair_cap)

    def pack(x):
        if x is None:
            return None
        return x[idx]

    send = jax.tree_util.tree_map(pack, local)
    recv = jax.tree_util.tree_map(
        lambda x: None if x is None else jax.lax.all_to_all(x, axis, 0, 0),
        send,
    )
    recv_valid = jax.lax.all_to_all(slot_valid, axis, 0, 0)  # (P, cap)
    # compact: flatten in src order, stable-partition valid slots first.
    # With a tight pair_capacity the slot grid (num * pair_cap) can be
    # SMALLER than out_size — pad the index; the padded tail is masked
    # to zeros by occupancy below (n_recv <= num * pair_cap always).
    flat_valid = recv_valid.reshape(-1)
    comp = jnp.argsort(~flat_valid, stable=True).astype(jnp.int32)
    slots = num * pair_cap
    if slots < out_size:
        comp = jnp.pad(comp, (0, out_size - slots))
    else:
        comp = comp[:out_size]
    occupancy = jnp.arange(out_size, dtype=jnp.int32) < n_recv

    def compact(x):
        if x is None:
            return None
        flat = x.reshape((num * pair_cap,) + x.shape[2:])
        g = flat[comp]
        pad_shape = (1,) * (g.ndim - 1)
        m = occupancy.reshape((out_size,) + pad_shape)
        return jnp.where(m, g, jnp.zeros_like(g))

    out_tbl = jax.tree_util.tree_map(compact, recv)
    overflow = n_recv - out_size
    return out_tbl, occupancy, overflow


def exchange_ragged_by_hash(
    local: Table,
    columns: Optional[Sequence[Union[int, str]]],
    counts: jax.Array,
    out_size: int,
    axis: str = SHUFFLE_AXIS,
    impl: str = "dense_compact",
    row_valid: Optional[jax.Array] = None,
    pair_capacity: Optional[int] = None,
):
    """:func:`exchange_ragged` keyed by Spark hash partitioning."""
    dest = partition_ids_hash(local, columns, counts.shape[0])
    return exchange_ragged(
        local, dest, counts, out_size, axis, impl, row_valid,
        pair_capacity,
    )


@metrics.traced("shuffle.table_compact")
def shuffle_table_compact(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    out_size: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    impl: Optional[str] = None,
    on_overflow: str = "raise",
    donate_input: bool = False,
):
    """Host-level compact shuffle: plan counts, ragged-exchange the rows.

    Unlike :func:`shuffle_table` (uniform per-pair capacity, received
    shape ``P x capacity``), the received buffer is ``out_size`` rows
    total per device — the hottest destination's REAL row total (rounded)
    — so correlated skew (e.g. pre-sorted input where one source feeds
    one destination) no longer inflates every device's allocation by a
    factor of P. Returns (sharded compact table, occupancy, overflow).

    Fault tolerance: the exchange launch is a ``shuffle``-site replay
    boundary — the sharded input + planned counts captured here are the
    lineage, so a transient failure re-runs ONLY this exchange.
    ``donate_input=True`` declares the caller's buffers consumed by the
    exchange and makes it at-most-once (zero retries, PR 10's
    doomed-replay rule).
    """
    metrics.counter_add("shuffle.exchanges")
    metrics.counter_add("shuffle.rows_exchanged", table.row_count)
    profiler.note_shuffle(table.row_count)
    if flight.enabled():
        flight.record("I", "shuffle.exchange", table.row_count)
    validate_on_overflow(on_overflow)
    impl = _ragged_impl(impl)
    sharded = shard_table(table, mesh, axis)
    counts = partition_counts(sharded, columns, mesh, axis)
    size = out_size or total_recv_capacity(counts)
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    pair_cap = _round_capacity(int(jnp.max(counts)))

    def run(local, C):
        out, occ, overflow = exchange_ragged_by_hash(
            local, columns, C, size, axis, impl,
            pair_capacity=pair_cap,
        )
        return out, occ, overflow[None]

    fn = shard_map(
        run,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    out, occ, overflow = run_collective(
        "shuffle.table_compact", lambda: fn(sharded, counts),
        site="shuffle", donated=donate_input,
    )
    if on_overflow == "raise":
        check_overflow_compact(overflow, size, "compact shuffle")
    return out, occ, overflow


@metrics.traced("shuffle.table")
def shuffle_table(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
    donate_input: bool = False,
):
    """Host-level shuffle: row-shard ``table`` and hash-exchange it.

    Returns (globally sharded padded table, occupancy column, overflow).
    ``capacity=None`` (the default) runs the planning pass and sizes the
    exchange exactly — no row can ever be dropped. An explicit capacity
    skips planning; if it turns out undersized, ``on_overflow="raise"``
    (default) raises ``ShuffleOverflowError``; ``"allow"`` opts into the
    caller checking the returned overflow counts itself.

    Fault tolerance: the exchange launch is a ``shuffle``-site replay
    boundary — the sharded input + partition spec captured here are the
    lineage, so a transient failure re-runs ONLY this exchange (never
    upstream work). ``donate_input=True`` declares the caller's buffers
    consumed by the exchange and makes it at-most-once (zero retries,
    PR 10's doomed-replay rule).
    """
    metrics.counter_add("shuffle.exchanges")
    metrics.counter_add("shuffle.rows_exchanged", table.row_count)
    profiler.note_shuffle(table.row_count)
    if flight.enabled():
        flight.record("I", "shuffle.exchange", table.row_count)
    validate_on_overflow(on_overflow)
    num = int(mesh.shape[axis])
    sharded = shard_table(table, mesh, axis)
    if capacity is None:
        capacity = plan_capacity(sharded, columns, mesh, axis)

    def run(local):
        out, occ, overflow = exchange_by_hash(
            local, columns, num, capacity, axis
        )
        return out, occ, overflow[None]

    fn = shard_map(
        run, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    out, occ, overflow = run_collective(
        "shuffle.table", lambda: fn(sharded),
        site="shuffle", donated=donate_input,
    )
    if on_overflow == "raise":
        check_overflow(overflow, capacity, "shuffle")
    return out, occ, overflow
