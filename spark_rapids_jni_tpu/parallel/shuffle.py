"""Shuffle exchange: the ICI all-to-all replacement for the RAPIDS
UCX/NCCL shuffle manager (SURVEY.md §2.5, §5.8).

``exchange`` is called *inside* ``shard_map``: each device buckets its
local rows by Spark-compatible partition id (pmod(murmur3)), packs them
into fixed-capacity per-destination send buffers, and one
``jax.lax.all_to_all`` moves every bucket to its owner over ICI. Fixed
capacity keeps shapes static for XLA (the shuffle-side instance of the
two-phase discipline); received padding is tracked with an occupancy mask
that downstream capped ops treat as absent rows.

``shuffle_table`` is the host-level wrapper: shard -> plan capacity
(exact per-(src,dst) counts, the generalization of the reference's
two-phase sizing, row_conversion.cu:505-511) -> shard_map(exchange)
-> globally sharded padded table + occupancy. The default path is
LOSSLESS: capacity is planned from the real counts, and any overflow
(possible only with an explicit undersized ``capacity``) raises
``ShuffleOverflowError`` instead of silently dropping rows.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..column import Column, Table
from ..ops.partition import partition_ids_hash
from .mesh import SHUFFLE_AXIS, shard_map, shard_table


class ShuffleOverflowError(RuntimeError):
    """An exchange received more rows for a (src, dst) pair than its
    static capacity — rows would have been dropped. Raised by the host
    wrappers; never silent."""


def validate_on_overflow(on_overflow: str) -> None:
    """Shared host-wrapper argument check: typos must not silently
    disable overflow detection."""
    if on_overflow not in ("raise", "allow"):
        raise ValueError(
            f"on_overflow must be 'raise' or 'allow', got {on_overflow!r}"
        )


def check_overflow(overflow, capacity: int, what: str) -> None:
    """Raise ``ShuffleOverflowError`` if any device reported overflow."""
    worst = int(jnp.max(overflow))
    if worst > 0:
        raise ShuffleOverflowError(
            f"{what} exchange capacity {capacity} undersized by {worst} "
            f"rows per (src, dst) pair; pass capacity=None to auto-plan"
        )


def partition_counts(
    sharded: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    axis: str = SHUFFLE_AXIS,
) -> jax.Array:
    """(num, num) per-(src, dst) row counts — the shuffle planning pass.

    Row [s, d] is how many of source s's rows hash to partition d. The
    max entry is the exact minimal per-pair exchange capacity.
    """
    num = int(mesh.shape[axis])

    def body(local: Table):
        dest = partition_ids_hash(local, columns, num)
        return jnp.bincount(dest, length=num).astype(jnp.int32)[None, :]

    fn = shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    return fn(sharded)


def _round_capacity(exact: int) -> int:
    """Round a planned capacity up to the next power of two (min 16) so
    repeated shuffles of similar volume reuse one compiled executable."""
    cap = 16
    while cap < exact:
        cap *= 2
    return cap


def plan_capacity(
    sharded: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    axis: str = SHUFFLE_AXIS,
) -> int:
    """Exact-overflow-free exchange capacity for ``sharded`` (host sync)."""
    counts = partition_counts(sharded, columns, mesh, axis)
    return _round_capacity(int(jnp.max(counts)))


def exchange(
    local: Table,
    dest: jax.Array,
    num_partitions: int,
    capacity: int,
    axis: str = SHUFFLE_AXIS,
    row_valid: Optional[jax.Array] = None,
):
    """All-to-all one device's rows to their destination partitions.

    Must run inside ``shard_map`` over ``axis`` (axis size ==
    ``num_partitions``). Returns (received table padded to
    ``num_partitions * capacity`` rows, occupancy mask, overflow counts):
    rows beyond ``capacity`` per (src, dst) pair are DROPPED — callers
    size ``capacity`` from the partitioning stats and must check
    ``overflow`` (max per-dest count) when in doubt.
    """
    n = local.row_count
    ok = (
        row_valid
        if row_valid is not None
        else jnp.ones((n,), dtype=jnp.bool_)
    )
    # invalid rows -> bucket num_partitions (beyond every real partition)
    dest = jnp.where(ok, dest, num_partitions).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    counts = jnp.bincount(dest, length=num_partitions + 1)[
        :num_partitions
    ].astype(jnp.int32)
    start = jnp.cumsum(counts) - counts

    j = jnp.arange(capacity, dtype=jnp.int32)
    flat_idx = jnp.clip(start[:, None] + j[None, :], 0, max(n - 1, 0))
    idx = order[flat_idx]  # (P, cap) source row per slot
    slot_valid = j[None, :] < jnp.minimum(counts[:, None], capacity)

    def pack(x):
        if x is None:
            return None
        return x[idx]  # (P, cap, ...)

    send = jax.tree_util.tree_map(pack, local)
    recv = jax.tree_util.tree_map(
        lambda x: None
        if x is None
        else jax.lax.all_to_all(x, axis, 0, 0),
        send,
    )
    recv_valid = jax.lax.all_to_all(slot_valid, axis, 0, 0)

    def flatten(x):
        if x is None:
            return None
        return x.reshape((num_partitions * capacity,) + x.shape[2:])

    out = jax.tree_util.tree_map(flatten, recv)
    occupancy = recv_valid.reshape((num_partitions * capacity,))
    overflow = jnp.max(counts) - capacity  # > 0 => rows were dropped
    return out, occupancy, overflow


def exchange_by_hash(
    local: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
    capacity: int,
    axis: str = SHUFFLE_AXIS,
    row_valid: Optional[jax.Array] = None,
):
    """exchange() keyed by Spark hash partitioning of ``columns``."""
    dest = partition_ids_hash(local, columns, num_partitions)
    return exchange(local, dest, num_partitions, capacity, axis, row_valid)


def shuffle_table(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Host-level shuffle: row-shard ``table`` and hash-exchange it.

    Returns (globally sharded padded table, occupancy column, overflow).
    ``capacity=None`` (the default) runs the planning pass and sizes the
    exchange exactly — no row can ever be dropped. An explicit capacity
    skips planning; if it turns out undersized, ``on_overflow="raise"``
    (default) raises ``ShuffleOverflowError``; ``"allow"`` opts into the
    caller checking the returned overflow counts itself.
    """
    validate_on_overflow(on_overflow)
    num = int(mesh.shape[axis])
    sharded = shard_table(table, mesh, axis)
    if capacity is None:
        capacity = plan_capacity(sharded, columns, mesh, axis)

    def run(local):
        out, occ, overflow = exchange_by_hash(
            local, columns, num, capacity, axis
        )
        return out, occ, overflow[None]

    fn = shard_map(
        run, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )
    out, occ, overflow = fn(sharded)
    if on_overflow == "raise":
        check_overflow(overflow, capacity, "shuffle")
    return out, occ, overflow
