"""Fault-tolerant distributed execution: lineage replay + mesh degradation.

The reference stack survives executor loss and shuffle-fetch failure
through Spark's task-retry and shuffle-recovery semantics (the plugin
layer the JNI jar serves): a lost shuffle block re-runs only the map
tasks that produced it, and a lost executor shrinks the pool without
killing the job. This module is that analog for the mesh tier:

* :func:`run_collective` — the retry boundary every host-side shard_map
  launch in the parallel tier routes through. The host wrapper's
  closure IS the recorded lineage: it captures the input shards and the
  partition spec (counts, capacities, splitters), so a transient
  collective failure re-runs only the failed exchange — never upstream
  work. Metered as ``shuffle.retries`` / ``shuffle.giveups``. Donated
  inputs are at-most-once (PR 10's doomed-replay rule): the raw error
  surfaces with ZERO retries because the launch may have consumed its
  buffers.
* :class:`MeshRunner` — the degradation ladder. A stage whose
  collective failures outlive the retry budget probes mesh health
  (:class:`~.mesh.MeshHealth` heartbeat with deadline), remeshes to the
  surviving device count (halving down the power-of-two ladder),
  re-plans partition capacity (the stage closure re-derives it from the
  host-side lineage at the new mesh size) and replays the stage on the
  smaller mesh — surfacing ``mesh.degraded`` instants instead of dying.
  Only below ``min_devices`` does it give up, with the typed
  :class:`~..utils.faults.Degraded` the serving tier catches to fall
  back to the single-device exact path.

Injection sites: ``shuffle`` (parallel/shuffle.py host wrappers),
``collective`` (distributed ops + planmesh stages), ``mesh`` (mesh
construction + health probe) — all through the seeded
``SPARK_RAPIDS_TPU_FAULTS`` grammar, so the whole ladder rehearses
deterministically on a CPU mesh.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..utils import config, faults, flight, lockcheck, log, metrics, tracing
from .mesh import SHUFFLE_AXIS, MeshHealth, make_mesh


def run_collective(
    label: str,
    launch: Callable[[], object],
    site: str = "collective",
    donated: bool = False,
    max_retries: Optional[int] = None,
):
    """Run one host-side collective launch with lineage-replay retry.

    ``launch`` must be re-runnable from host state alone (the closure
    captures the sharded inputs + partition spec — the lineage), which
    every host wrapper in shuffle.py/distributed.py satisfies: nothing
    is consumed until the launch succeeds. ``donated=True`` declares
    the opposite — the launch may consume its input — and makes the
    boundary at-most-once: the first transient surfaces unchanged,
    zero retries (``shuffle.giveups`` still counts the loss).

    Retry policy is transient-only: an OOM collective re-fails at the
    same shape (capacity re-planning is the MeshRunner ladder's job,
    not a same-shape re-run), and permanent/cancel/deadline classes
    keep :func:`~..utils.faults.run_with_retry` semantics — they
    surface unchanged.
    """
    # the exchange span: trace-tagged on the flight ring, so a merged
    # trace shows every collective launch (and its retries — same span,
    # same trace: replay never mints a fresh trace id) under the
    # request that ran it
    tok = tracing.span_begin(label)
    err: Optional[str] = None
    try:
        return _run_collective(label, launch, site, donated, max_retries)
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        tracing.span_end(tok, error=err)


def _run_collective(label, launch, site, donated, max_retries):
    attempt = 0
    while True:
        faults.check_cancel()
        try:
            faults.inject(site)
            return launch()
        except (faults.Cancelled, faults.DeadlineExceeded,
                faults.Degraded):
            raise
        except Exception as e:
            cls = faults.classify(e)
            if cls is not faults.TransientDeviceError:
                faults.note_error_class(e, label)
                raise
            if donated:
                # srt: allow-retry-donated(at-most-once gate: a donated launch surfaces its first transient unchanged — this branch precedes every retry)
                metrics.counter_add("shuffle.giveups")
                if flight.enabled():
                    flight.record("I", "shuffle.giveup", f"{label}:donated")
                raise
            limit = (
                faults.retry_max() if max_retries is None
                else int(max_retries)
            )
            if attempt >= limit:
                metrics.counter_add("shuffle.giveups")
                if flight.enabled():
                    flight.record(
                        "I", "shuffle.giveup", f"{label}:{attempt}"
                    )
                if isinstance(e, faults.FaultError):
                    raise
                raise cls(
                    f"{label}: collective retries exhausted after "
                    f"{attempt} attempt(s): "
                    f"{type(e).__name__}: {str(e)[:200]}"
                ) from e
            attempt += 1
            metrics.counter_add("shuffle.retries")
            faults.sleep_backoff(attempt, label, error=e)


class MeshRunner:
    """Owns a mesh and the ladder that shrinks it under persistent
    collective failure.

    ``run_stage(label, stage)`` runs ``stage(mesh)`` — a callable
    re-runnable from host-side lineage — through
    :func:`run_collective`. When a stage's transient failures outlive
    the retry budget, the runner walks down the device ladder: probe
    the candidate smaller mesh with a deadline heartbeat, remesh to the
    surviving count, and REPLAY the stage there (the stage re-derives
    shard layout and partition capacity from its captured inputs at the
    new size). Each step is metered (``mesh.degraded`` counter +
    flight instant). At ``min_devices`` with failures persisting, the
    typed :class:`~..utils.faults.Degraded` surfaces — the serving
    integration's signal to fall back to the single-device exact path
    instead of shedding the tenant.
    """

    def __init__(self, n_devices: Optional[int] = None,
                 axis: str = SHUFFLE_AXIS, min_devices: int = 1,
                 health: Optional[MeshHealth] = None):
        self.axis = axis
        self.requested = (
            len(jax.devices()) if n_devices is None else int(n_devices)
        )
        self.min_devices = max(int(min_devices), 1)
        self.health = health or MeshHealth()
        self._lock = lockcheck.make_lock("mesh.runner")
        self.mesh = make_mesh(self.requested, axis)
        self.degraded = False
        self.stages = 0
        self.replays = 0
        self.degradations = 0

    @property
    def n_devices(self) -> int:
        with self._lock:
            return int(self.mesh.shape[self.axis])

    def run_stage(self, label: str, stage: Callable[[object], object]):
        """Run ``stage(mesh)`` with retry + degradation-replay. The
        whole ladder — replays and degradations included — runs inside
        ONE trace-tagged ``mesh.stage`` span, so the ``mesh.replay`` /
        ``mesh.degraded`` instants are attributed to the ORIGINAL
        request's trace id (a replay never mints a fresh trace)."""
        with self._lock:
            self.stages += 1
        tok = tracing.span_begin("mesh.stage")
        err: Optional[str] = None
        try:
            return self._run_stage(label, stage)
        except BaseException as e:
            err = type(e).__name__
            raise
        finally:
            tracing.span_end(tok, error=err)

    def _run_stage(self, label: str, stage: Callable[[object], object]):
        while True:
            with self._lock:
                mesh = self.mesh
            try:
                return run_collective(label, lambda: stage(mesh))
            except (faults.Cancelled, faults.DeadlineExceeded,
                    faults.Degraded):
                raise
            except Exception as e:
                if faults.classify(e) is not faults.TransientDeviceError:
                    raise
                # retries exhausted at this mesh size: walk the ladder
                self._degrade(label, mesh, e)
                with self._lock:
                    self.replays += 1
                if flight.enabled():
                    flight.record("I", "mesh.replay", label)

    def _degrade(self, label: str, failed_mesh, cause) -> None:
        """Remesh to the surviving device count (or raise Degraded)."""
        n = int(failed_mesh.shape[self.axis])
        while n > self.min_devices:
            n = max(n // 2, self.min_devices)
            try:
                candidate = make_mesh(n, self.axis)
            except (faults.FaultError, ValueError) as e:
                faults.note_error_class(e, "mesh.remesh")
                continue  # this rung is dead too; keep walking down
            if not self.health.probe(candidate, self.axis):
                continue
            with self._lock:
                # another thread may have degraded further already;
                # never grow the mesh back mid-incident
                if int(self.mesh.shape[self.axis]) > n:
                    self.mesh = candidate
                self.degraded = True
                self.degradations += 1
            metrics.counter_add("mesh.degraded")
            metrics.gauge_set("mesh.devices", n)
            if flight.enabled():
                flight.record("I", "mesh.degraded", f"{label}:{n}")
            log.log(
                "WARN", "faults", "mesh_degraded", stage=label,
                devices=n, was=int(failed_mesh.shape[self.axis]),
                cause=f"{type(cause).__name__}: {str(cause)[:200]}",
            )
            return
        metrics.counter_add("mesh.exhausted")
        if flight.enabled():
            flight.record("I", "mesh.exhausted", label)
        raise faults.Degraded(
            f"mesh stage {label!r}: collective failures persist down "
            f"to the {self.min_devices}-device floor; degrade to the "
            "single-device exact path"
        ) from cause

    def to_doc(self) -> dict:
        with self._lock:
            return {
                "axis": self.axis,
                "requested_devices": self.requested,
                "devices": int(self.mesh.shape[self.axis]),
                "min_devices": self.min_devices,
                "degraded": self.degraded,
                "stages": self.stages,
                "replays": self.replays,
                "degradations": self.degradations,
            }
