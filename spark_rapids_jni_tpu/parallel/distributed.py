"""Distributed relational ops: shuffle + local capped ops under shard_map.

The multi-chip join/aggregation path the GPU stack assembles from
GpuShuffleExchangeExec + per-GPU cudf kernels, here as single jittable
SPMD computations: hash-exchange co-partitions rows over ICI, then each
chip runs the local sort-based op on its partition with padding rows
masked by occupancy. Results stay device-resident and sharded (each chip
owns its key range by hash), exactly how a Spark stage chain consumes
them.

Sizing is LOSSLESS by default: exchange capacities come from the
planning pass (parallel/shuffle.py:partition_counts) and join output
capacity from a jitted count pass (ops/join.py:inner_join_count) — the
distributed instances of the reference's two-phase sizing discipline
(row_conversion.cu:505-511). Explicit undersized capacities raise
``ShuffleOverflowError``/``JoinOverflowError``/``GroupOverflowError``
rather than dropping rows. The join exchanges each side ONCE: the
shuffled shards stay device-resident between the count pass and the
materialize pass.

Fault tolerance: every shard_map launch here is a ``collective``-site
replay boundary (``tolerant.run_collective``) — the host wrapper's
sharded inputs + planned capacities are the lineage, so a transient
collective failure re-runs only the failed launch with backoff
(``shuffle.retries``/``shuffle.giveups``). Overflow errors are typed
``faults.PermanentError``: never retried, never breaker-counted.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import dtype as dt
from ..column import Column, Table
from ..utils import faults, metrics
from ..ops.groupby import GroupbyAgg, groupby_aggregate_capped
from ..ops.join import (
    inner_join_capped,
    inner_join_count,
    left_join_capped,
    left_join_count,
    membership_mask,
)
from .mesh import SHUFFLE_AXIS, shard_map, shard_table
from .tolerant import run_collective
from .shuffle import (
    _ragged_impl,
    _round_capacity,
    check_overflow_compact,
    exchange_ragged,
    exchange_ragged_by_hash,
    partition_counts,
    plan_skew,
    total_recv_capacity,
    validate_on_overflow,
)
from ..ops.partition import partition_ids_hash


def _warn_if_recv_exceeds_hbm(cap: int, table: Table, label: str) -> None:
    """Planned per-device receive buffer vs the HBM budget (round-3
    VERDICT weak item 6: capacity planning had no fit check for real
    chips). The exchanged shard plus its sort working set must fit; a
    plan that can't will OOM-kill the worker mid-collective, which is
    far harder to diagnose than this warning. Warning, not error: the
    budget is conservative and CPU-mesh simulations may legitimately
    exceed a v5e's 16 GB."""
    from ..utils import hbm

    est = 2 * cap * hbm.row_bytes(table)  # shard + sort working copy
    budget = hbm.budget_bytes()
    from ..utils import log as srt_log

    srt_log.log(
        "INFO", "hbm", "recv_buffer_plan", label=label,
        estimated_bytes=int(est), budget_bytes=int(budget),  # srt: allow-host-sync(host-only arithmetic: row_bytes and the budget are host ints)
        fits=bool(est <= budget),  # srt: allow-host-sync(host-only arithmetic: row_bytes and the budget are host ints)
    )
    if metrics.enabled():
        metrics.counter_add("shuffle.recv_plans")
        metrics.bytes_add("shuffle.recv_planned_bytes", int(est))
    if est > budget:
        metrics.counter_add("shuffle.recv_over_budget")
        import warnings

        warnings.warn(
            f"distributed {label}: planned per-device receive capacity "
            f"({cap} rows, ~{est >> 20} MiB with working set) exceeds "
            f"the per-chip HBM budget ({budget >> 20} MiB). Expect "
            "worker OOM on real chips; shard the input further or raise "
            "SPARK_RAPIDS_TPU_HBM_BUDGET_GB.",
            stacklevel=3,
        )


class JoinOverflowError(faults.PermanentError):
    """A capped join produced more matches than its static output
    capacity — rows would have been dropped. Raised by the host
    wrappers; never silent.

    Typed :class:`~..utils.faults.PermanentError` (a replay at the same
    capacity overflows identically — never retried, never counted by
    the breaker); still a ``RuntimeError`` via ``FaultError``."""


class GroupOverflowError(faults.PermanentError):
    """A capped groupby saw more distinct keys than its static segment
    capacity — groups would have been dropped. Raised by the host
    wrappers; never silent.

    Typed :class:`~..utils.faults.PermanentError` like
    :class:`JoinOverflowError`."""


@metrics.traced("distributed.groupby")
def distributed_groupby(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    mesh: Mesh,
    capacity: Optional[int] = None,
    groups_per_device: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Shuffle-then-aggregate GROUP BY over the mesh.

    Returns (sharded padded result table, per-device group counts (P,),
    per-device shuffle overflow (P,)). Groups are complete: each key lives
    on exactly one device, by Spark hash partitioning. The exchange is
    ragged-compact (shuffle.exchange_ragged): each device materializes
    ``capacity`` rows total — the hottest destination's real row count —
    not P x the hottest (src, dst) pair. ``capacity=None`` auto-plans
    from the real partition counts (lossless); an explicit undersized
    ``capacity`` or ``groups_per_device`` raises unless
    ``on_overflow="allow"``.
    """
    validate_on_overflow(on_overflow)
    impl = _ragged_impl(None)
    sharded = shard_table(table, mesh, axis)
    counts = partition_counts(sharded, by, mesh, axis)
    if capacity is None:
        # adaptive skew repartitioning (ISSUE 17): when the planning
        # counts show a destination past SKEW_SPLIT_FACTOR x the mean
        # and every agg decomposes losslessly, salt the hot keys across
        # k sub-partitions with a partial-agg before the exchange — the
        # receive buffers are then sized from the post-split counts
        skew = plan_skew(counts)
        if skew.engaged and _skew_decomposable(table, aggs):
            return _groupby_skew_split(
                table, sharded, by, aggs, mesh, skew, axis, impl,
                on_overflow, groups_per_device,
            )
    cap = capacity or total_recv_capacity(counts)
    _warn_if_recv_exceeds_hbm(cap, table, "groupby")
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    pair_cap = _round_capacity(int(jnp.max(counts)))
    # a device can't see more groups than the rows it receives
    seg_cap = groups_per_device or cap

    def body(local: Table, C):
        shuffled, occ, overflow = exchange_ragged_by_hash(
            local, by, C, cap, axis, impl, pair_capacity=pair_cap
        )
        agg, ngroups = groupby_aggregate_capped(
            shuffled, by, aggs, num_segments=seg_cap, row_valid=occ
        )
        return agg, ngroups[None], overflow[None]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    agg, ngroups, overflow = run_collective(
        "distributed.groupby", lambda: fn(sharded, counts)
    )
    if on_overflow == "raise":
        check_overflow_compact(overflow, cap, "groupby")
        # srt: allow-host-sync(lossless verdict: the overflow check exists to block until the counts land)
        worst_groups = int(jnp.max(ngroups))
        if worst_groups > seg_cap:
            raise GroupOverflowError(
                f"groups_per_device {seg_cap} undersized: a device saw "
                f"{worst_groups} distinct keys; omit groups_per_device "
                f"to auto-size"
            )
    return agg, ngroups, overflow


# aggregations whose merge is lossless AND byte-deterministic: each op
# maps to the op that combines its partials. Float sums are excluded —
# reassociating them changes the bits, and the skew path must stay
# byte-identical to the unsplit one.
_SKEW_MERGE_OPS = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}


def _agg_out_name(table: Table, agg: GroupbyAgg) -> str:
    """The output column name groupby_aggregate_capped will assign."""
    base = (
        agg.column
        if isinstance(agg.column, str)
        else (table.names[agg.column] if table.names else f"c{agg.column}")
    )
    return agg.name or f"{agg.op}_{base}"


def _skew_decomposable(table: Table, aggs: Sequence[GroupbyAgg]) -> bool:
    """True when every agg splits into partial + merge without changing
    a single output byte (the skew-split eligibility gate)."""
    seen = set()
    for a in aggs:
        if a.op not in _SKEW_MERGE_OPS:
            return False
        if a.op == "sum" and table.column(a.column).dtype.is_floating:
            return False
        name = _agg_out_name(table, a)
        if name in seen:
            # merge aggs address partials BY NAME; a collision would
            # merge the wrong column
            return False
        seen.add(name)
    return True


def _groupby_skew_split(
    table: Table,
    sharded: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    mesh: Mesh,
    skew,
    axis: str,
    impl: str,
    on_overflow: str,
    groups_per_device: Optional[int],
):
    """Salted two-phase GROUP BY for skewed keys (the AQE skew-join
    split applied to aggregation).

    Scan side: each device partial-aggregates its rows by
    ``(keys, salt)`` where ``salt = iota % k`` for rows bound to a hot
    destination (0 otherwise), then exchanges the partials to
    ``(hash + salt) % P`` — a hot key's traffic spreads over ``k``
    destinations and every (src, dst) lane carries at most one row per
    (key, salt). Merge side: each device combines the partials it
    received by key, then ONE more (small) exchange on ``hash % P``
    plus a final merge makes every key whole on exactly one device —
    the same placement, local key order, and output bytes as the
    unsplit path. Capacity for both exchanges is sized from their OWN
    planning counts, i.e. from post-split traffic: the 8x worst-case
    receive buffer of BENCH_r04 becomes ~mean-sized.
    """
    from ..utils import planstats

    num = int(mesh.shape[axis])
    nby = len(by)
    k = int(skew.k)
    hot_mask = np.zeros((num,), dtype=bool)
    for d in skew.hot:
        hot_mask[d] = True
    hot = jnp.asarray(hot_mask)

    partial_aggs = [
        GroupbyAgg(a.column, a.op, name=_agg_out_name(table, a))
        for a in aggs
    ]
    merge_aggs = [
        GroupbyAgg(
            _agg_out_name(table, a), _SKEW_MERGE_OPS[a.op],
            name=_agg_out_name(table, a),
        )
        for a in aggs
    ]

    def partial(local: Table):
        """Local (key, salt) partial aggregation — the map-side combine."""
        n = local.row_count
        h = partition_ids_hash(local, by, num)
        iota = jnp.arange(n, dtype=jnp.int32)
        salt = jnp.where(hot[h], iota % k, 0).astype(jnp.int32)
        names = (
            list(local.names) + ["__skew_salt__"] if local.names else None
        )
        pt = Table(
            list(local.columns) + [Column(salt, dt.INT32, None)], names
        )
        pby = list(by) + [len(local.columns)]
        p, pg = groupby_aggregate_capped(
            pt, pby, partial_aggs, num_segments=n
        )
        return p, pg

    def partial_dest(p: Table, pg):
        """Destination of each partial row: (hash(keys) + salt) % P."""
        rv = jnp.arange(p.row_count, dtype=jnp.int32) < pg
        h = partition_ids_hash(p, list(range(nby)), num)
        salt = p.columns[nby].data.astype(jnp.int32)
        return jnp.mod(h + salt, num), rv

    # ---- planning pass 1: post-split counts of the partial exchange
    def count1_body(local: Table):
        p, pg = partial(local)
        dest, rv = partial_dest(p, pg)
        d = jnp.where(rv, dest, num).astype(jnp.int32)
        return jnp.bincount(d, length=num + 1)[:num].astype(jnp.int32)[
            None, :
        ]

    fn1 = shard_map(
        count1_body, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    counts1 = run_collective(
        "shuffle.skew_counts", lambda: fn1(sharded), site="shuffle"
    )
    cap1 = total_recv_capacity(counts1)
    _warn_if_recv_exceeds_hbm(cap1, table, "groupby-skew")
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    pair_cap1 = _round_capacity(int(jnp.max(counts1)))
    # srt: allow-host-sync(two-phase sizing: the post-split skew ratio is a planning readout)
    recv1 = np.asarray(jax.device_get(jnp.sum(counts1, axis=0)))
    post_max = int(recv1.max()) if recv1.size else 0
    post_mean = float(recv1.mean()) if recv1.size else 0.0
    post_ratio = post_max / post_mean if post_mean > 0 else 0.0
    if metrics.enabled():
        metrics.counter_add("shuffle.skew_splits", len(skew.hot))
        metrics.gauge_set("shuffle.skew_recv_before", skew.max_recv)
        metrics.gauge_set("shuffle.skew_recv_after", post_max)
        metrics.gauge_set(
            "shuffle.skew_post_ratio_x100", int(post_ratio * 100)
        )
    planstats.note_skew({
        "site": "distributed.groupby",
        "action": "split",
        "factor": skew.factor,
        "k": k,
        "hot_destinations": list(skew.hot),
        "max_recv": skew.max_recv,
        "mean_recv": skew.mean_recv,
        "ratio": skew.ratio,
        "post_max_recv": post_max,
        "post_mean_recv": post_mean,
        "post_ratio": post_ratio,
        "devices": num,
    })

    # ---- pass 2: exchange the salted partials, merge per device
    def body2(local: Table, C):
        p, pg = partial(local)
        dest, rv = partial_dest(p, pg)
        shuffled, occ, overflow = exchange_ragged(
            p, dest, C, cap1, axis, impl, row_valid=rv,
            pair_capacity=pair_cap1,
        )
        # drop the salt before the key-only merge: partials of one key
        # that landed here (any salt) combine into one row
        cols = (
            list(shuffled.columns[:nby]) + list(shuffled.columns[nby + 1:])
        )
        names = (
            list(shuffled.names[:nby]) + list(shuffled.names[nby + 1:])
            if shuffled.names else None
        )
        mt = Table(cols, names)
        m, mg = groupby_aggregate_capped(
            mt, list(range(nby)), merge_aggs, num_segments=cap1,
            row_valid=occ,
        )
        return m, mg[None], overflow[None]

    fn2 = shard_map(
        body2, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False,
    )
    merged, mgroups, ov1 = run_collective(
        "shuffle.skew_exchange", lambda: fn2(sharded, counts1),
        site="shuffle",
    )
    if on_overflow == "raise":
        check_overflow_compact(ov1, cap1, "skew-split groupby")

    # ---- planning pass 2: counts for the (small) completion exchange
    def count3_body(m_local: Table, g):
        rv = jnp.arange(m_local.row_count, dtype=jnp.int32) < g[0]
        h = partition_ids_hash(m_local, list(range(nby)), num)
        d = jnp.where(rv, h, num).astype(jnp.int32)
        return jnp.bincount(d, length=num + 1)[:num].astype(jnp.int32)[
            None, :
        ]

    fn3 = shard_map(
        count3_body, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False,
    )
    counts3 = run_collective(
        "shuffle.skew_completion_counts",
        lambda: fn3(merged, mgroups), site="shuffle",
    )
    cap3 = total_recv_capacity(counts3)
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    pair_cap3 = _round_capacity(int(jnp.max(counts3)))
    seg_cap = groups_per_device or cap3

    # ---- pass 3: completion exchange + final merge — each key ends on
    # the SAME device the unsplit path would place it (hash % P), in the
    # same local key order, with the same output bytes
    def body4(m_local: Table, g, C):
        rv = jnp.arange(m_local.row_count, dtype=jnp.int32) < g[0]
        h = partition_ids_hash(m_local, list(range(nby)), num)
        shuffled, occ, overflow = exchange_ragged(
            m_local, h, C, cap3, axis, impl, row_valid=rv,
            pair_capacity=pair_cap3,
        )
        agg, ngroups = groupby_aggregate_capped(
            shuffled, list(range(nby)), merge_aggs,
            num_segments=seg_cap, row_valid=occ,
        )
        return agg, ngroups[None], overflow[None]

    fn4 = shard_map(
        body4, mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=P(axis), check_vma=False,
    )
    agg, ngroups, ov2 = run_collective(
        "shuffle.skew_completion",
        lambda: fn4(merged, mgroups, counts3), site="shuffle",
    )
    if on_overflow == "raise":
        check_overflow_compact(ov2, cap3, "skew-split groupby completion")
        # srt: allow-host-sync(lossless verdict: the overflow check exists to block until the counts land)
        worst_groups = int(jnp.max(ngroups))
        if worst_groups > seg_cap:
            raise GroupOverflowError(
                f"groups_per_device {seg_cap} undersized: a device saw "
                f"{worst_groups} distinct keys; omit groups_per_device "
                f"to auto-size"
            )
    return agg, ngroups, ov2


@metrics.traced("distributed.inner_join")
def distributed_inner_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Shuffle-shuffle hash-partitioned inner join over the mesh.

    Both sides are hash-exchanged on the join keys (co-partitioning), then
    each chip joins its partitions locally. Returns (sharded padded join
    output, per-device match counts, left/right shuffle overflows).

    ``capacity=None`` plans both exchanges exactly (ragged-compact:
    per-device buffers are the real received row totals, and the planning
    bincount doubles as the ragged-offset table — one planning pass per
    side, not two); ``out_capacity=None`` counts matches on the
    co-partitioned shards and sizes the output to the real per-device
    maximum (two-phase sizing). Each side crosses the ICI exactly once —
    the count pass and the materialize pass share the shuffled,
    device-resident shards. Explicit undersized values raise unless
    ``on_overflow="allow"``.
    """
    return _shuffle_join(
        left, right, on, mesh, capacity, out_capacity, axis,
        on_overflow, inner_join_count, inner_join_capped, "join",
    )


def _shuffle_join(
    left, right, on, mesh, capacity, out_capacity, axis, on_overflow,
    count_fn, capped_fn, label: str,
):
    """Shared shuffle-join driver: co-partition (count pass fused into
    the exchange), size the output, run the local capped join per chip,
    check overflow — the one copy of the two-phase sizing contract the
    inner and left outer joins share."""
    validate_on_overflow(on_overflow)
    count_pass = out_capacity is None
    ls_g, locc_g, lov, rs_g, rocc_g, rov, cnts = _co_partition(
        left, right, on, mesh, capacity, axis, on_overflow,
        count_fn=(
            (lambda ls, locc, rs, rocc: count_fn(
                ls, rs, on, left_valid=locc, right_valid=rocc
            ))
            if count_pass
            else None
        ),
    )
    if count_pass:
        # srt: allow-host-sync(two-phase sizing: the count pass exists to produce this host capacity)
        ocap = _round_capacity(int(jnp.max(cnts)))
    else:
        ocap = out_capacity

    def join_body(ls: Table, locc, rs: Table, rocc):
        out, count = capped_fn(
            ls, rs, on, capacity=ocap, left_valid=locc, right_valid=rocc
        )
        return out, count[None]

    join_fn = shard_map(
        join_body,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    out, count = run_collective(
        f"distributed.{label}",
        lambda: join_fn(ls_g, locc_g, rs_g, rocc_g),
    )
    if on_overflow == "raise":
        # srt: allow-host-sync(lossless verdict: the overflow check exists to block until the counts land)
        worst = int(jnp.max(count))
        if worst > ocap:
            raise JoinOverflowError(
                f"{label} output capacity {ocap} undersized: a device "
                f"produced {worst} rows; pass out_capacity=None to "
                "auto-size"
            )
    return out, count, lov, rov


def _co_partition(
    left, right, on, mesh, capacity, axis, on_overflow, count_fn=None
):
    """Shared exchange for the shuffle joins: hash-exchange both sides
    on the join keys, returning sharded shards + occupancies (each side
    crosses the ICI exactly once; later passes reuse the shards).

    ``count_fn(ls, locc, rs, rocc)`` optionally fuses a per-device
    scalar count into the same dispatch (the inner join's two-phase
    sizing pass rides the exchange instead of paying its own round
    trip); its per-device results come back as the last element."""
    impl = _ragged_impl(None)
    lsh = shard_table(left, mesh, axis)
    rsh = shard_table(right, mesh, axis)
    lcounts = partition_counts(lsh, on, mesh, axis)
    rcounts = partition_counts(rsh, on, mesh, axis)
    lcap = capacity or total_recv_capacity(lcounts)
    rcap = capacity or total_recv_capacity(rcounts)
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce these host capacities)
    lpair = _round_capacity(int(jnp.max(lcounts)))
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce these host capacities)
    rpair = _round_capacity(int(jnp.max(rcounts)))

    def body(l_local: Table, r_local: Table, lC, rC):
        ls, locc, lov = exchange_ragged_by_hash(
            l_local, on, lC, lcap, axis, impl, pair_capacity=lpair
        )
        rs, rocc, rov = exchange_ragged_by_hash(
            r_local, on, rC, rcap, axis, impl, pair_capacity=rpair
        )
        cnt = (
            count_fn(ls, locc, rs, rocc)
            if count_fn is not None
            else jnp.zeros((), jnp.int64)
        )
        return ls, locc, lov[None], rs, rocc, rov[None], cnt[None]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    ls_g, locc_g, lov, rs_g, rocc_g, rov, cnts = run_collective(
        "distributed.co_partition",
        lambda: fn(lsh, rsh, lcounts, rcounts),
    )
    if on_overflow == "raise":
        check_overflow_compact(lov, lcap, "left side")
        check_overflow_compact(rov, rcap, "right side")
    return ls_g, locc_g, lov, rs_g, rocc_g, rov, cnts


@metrics.traced("distributed.left_join")
def distributed_left_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Shuffle-shuffle LEFT OUTER join over the mesh: co-partition both
    sides, then each chip left-joins its partitions locally (every valid
    left row emits at least once — unmatched rows carry a null right
    side). Two-phase sizing like distributed_inner_join. Returns
    (sharded padded output, per-device row counts, left/right shuffle
    overflows)."""
    return _shuffle_join(
        left, right, on, mesh, capacity, out_capacity, axis,
        on_overflow, left_join_count, left_join_capped, "left join",
    )


def _distributed_membership_join(
    left, right, on, mesh, capacity, axis, on_overflow, anti: bool
):
    validate_on_overflow(on_overflow)
    ls_g, locc_g, lov, rs_g, rocc_g, rov, _ = _co_partition(
        left, right, on, mesh, capacity, axis, on_overflow
    )

    def body(ls: Table, locc, rs: Table, rocc):
        member = membership_mask(
            ls, rs, on, left_valid=locc, right_valid=rocc
        )
        # only the mask leaves the shard_map — returning ls too would
        # materialize a second copy of the co-partitioned fact shards
        return jnp.logical_and(
            locc, jnp.logical_not(member) if anti else member
        )

    fn = shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    occ = run_collective(
        "distributed.membership",
        lambda: fn(ls_g, locc_g, rs_g, rocc_g),
    )
    return ls_g, occ, lov, rov


@metrics.traced("distributed.semi_join")
def distributed_semi_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Distributed LEFT SEMI join: co-partition, then mark each left row
    with membership. Returns (sharded left shards, occupancy of
    surviving rows, left/right shuffle overflows) — the padded-shard
    convention every distributed op here uses (rows stay in place, the
    occupancy column is the result)."""
    return _distributed_membership_join(
        left, right, on, mesh, capacity, axis, on_overflow, anti=False
    )


@metrics.traced("distributed.anti_join")
def distributed_anti_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Distributed LEFT ANTI join (rows of left with NO match)."""
    return _distributed_membership_join(
        left, right, on, mesh, capacity, axis, on_overflow, anti=True
    )


@metrics.traced("distributed.distinct")
def distributed_distinct(
    table: Table,
    keys: Optional[Sequence[Union[int, str]]] = None,
    mesh: Mesh = None,
    capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Distributed DISTINCT (Spark dropDuplicates / cudf distinct):
    hash-exchange by the key columns so every duplicate lands on one
    device, then local dedup — expressed as a groupby with no
    aggregations, which reuses the lossless exchange + occupancy
    machinery wholesale. Returns (sharded padded key table, per-device
    distinct counts, shuffle overflow)."""
    if mesh is None:
        raise TypeError(
            "distributed_distinct: mesh is required "
            "(keys defaults to all columns, mesh does not default)"
        )
    if keys is None:
        keys = (
            list(table.names)
            if table.names is not None
            else list(range(table.num_columns))
        )
    return distributed_groupby(
        table, keys, [], mesh, capacity=capacity, axis=axis,
        on_overflow=on_overflow,
    )


@metrics.traced("distributed.broadcast_join")
def broadcast_inner_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    out_capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Broadcast-hash inner join: the small (dimension) side replicates
    to every device, the big side stays sharded IN PLACE — zero exchange
    of the big side over ICI.

    The Spark plugin picks this plan (BroadcastHashJoinExec) whenever a
    side fits the broadcast threshold — the TPC-DS dimension-table
    pattern (date_dim/item/store joins in q5/q64). On the mesh the
    replicated side rides shard_map's ``P()`` spec, so XLA materializes
    one copy per device and every chip probes its local shard against
    the full small table. Output sizing is the usual two-phase count
    (``out_capacity=None`` auto-sizes to the real per-device maximum).

    Returns (sharded padded join output, per-device match counts).
    """
    from ..ops.join import (
        _prepare_build,
        _probe_build,
        inner_join_from_ranges,
    )

    validate_on_overflow(on_overflow)
    lsh = shard_table(left, mesh, axis)
    count_pass = out_capacity is None
    on_l = list(on)
    if count_pass:
        # the count dispatch keeps its device-resident probe results
        # (lo, counts) so the materialize dispatch reuses them instead
        # of re-sorting the build side and re-probing the fact shards
        def count_body(l_local: Table, r_full: Table):
            _, sw = _prepare_build(r_full, on_l)
            lo, counts, _ = _probe_build(sw, l_local, on_l)
            return lo, counts, jnp.sum(counts)[None]

        cnt_fn = shard_map(
            count_body,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )
        lo_g, counts_g, cnts = run_collective(
            "distributed.broadcast_count", lambda: cnt_fn(lsh, right)
        )
        # srt: allow-host-sync(two-phase sizing: the count pass exists to produce this host capacity)
        ocap = _round_capacity(int(jnp.max(cnts)))

        def body(l_local: Table, r_full: Table, lo, counts):
            # only the (cheap, small-side) build sort re-runs here; the
            # O(n log m) probe of the fact shard does not
            perm_r, _ = _prepare_build(r_full, on_l)
            out, count = inner_join_from_ranges(
                l_local, r_full, on_l, perm_r, lo, counts, ocap
            )
            return out, count[None]

        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(), P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
        out, count = run_collective(
            "distributed.broadcast_join",
            lambda: fn(lsh, right, lo_g, counts_g),
        )
    else:
        ocap = out_capacity

        def body(l_local: Table, r_full: Table):
            out, count = inner_join_capped(
                l_local, r_full, on, capacity=ocap
            )
            return out, count[None]

        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )
        out, count = run_collective(
            "distributed.broadcast_join", lambda: fn(lsh, right)
        )
    if on_overflow == "raise":
        # srt: allow-host-sync(lossless verdict: the overflow check exists to block until the counts land)
        worst = int(jnp.max(count))
        if worst > ocap:
            raise JoinOverflowError(
                f"broadcast join output capacity {ocap} undersized: a "
                f"device produced {worst} matches; pass "
                "out_capacity=None to auto-size"
            )
    return out, count


@metrics.traced("distributed.sort")
def distributed_sort(
    table: Table,
    sort_keys,
    mesh: Mesh,
    capacity: Optional[int] = None,
    sample_size: int = 8192,
    axis: str = SHUFFLE_AXIS,
    on_overflow: str = "raise",
):
    """Distributed ORDER BY: sample -> range partition -> local sort.

    The global sort the GPU stack gets from Spark's range-partitioned
    TotalOrderSort over the shuffle manager: P-1 splitters come from a
    host-side sample of the sort-key order words, every row is
    range-partitioned to the device owning its key range (ragged-compact
    exchange, so buffers track real range sizes), and each device sorts
    its range locally. Reading devices in mesh order (valid prefixes,
    per the occupancy column) yields the total order.

    Returns (sharded sorted padded table, occupancy, overflow).
    """
    from ..ops import keys as keys_mod
    from ..ops.sort import SortKey, _key_words

    validate_on_overflow(on_overflow)
    impl = _ragged_impl(None)
    num = int(mesh.shape[axis])
    sort_keys = [
        k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys
    ]
    if num == 1:
        # one device: the range partition is trivial — local sort
        from ..ops.sort import sort_table

        out = shard_table(sort_table(table, sort_keys), mesh, axis)
        occ = jnp.ones((table.row_count,), jnp.bool_)
        return out, occ, jnp.zeros((1,), jnp.int64)
    sharded = shard_table(table, mesh, axis)

    # splitters from a deterministic host-side sample of the key words
    words = []
    for k in sort_keys:
        words.extend(_key_words(table.column(k.column), k))
    n = table.row_count
    stride = max(n // max(sample_size, 1), 1)
    # srt: allow-host-sync(range-partition sampling: the splitter sample is a deliberate host step)
    samp = [np.asarray(w[::stride]) for w in words]
    order = np.lexsort(samp[::-1])
    m = order.shape[0]
    cut = [order[(i * m) // num] for i in range(1, num)]
    splitters = [
        jnp.asarray(np.stack([s[cut_i] for cut_i in cut]))
        for s in samp
    ]  # per word: (num-1,) splitter values

    def dest_of(local: Table):
        lwords = []
        for k in sort_keys:
            lwords.extend(_key_words(local.column(k.column), k))
        # partition id = number of splitters <= key (lexicographic)
        nloc = local.row_count
        dest = jnp.zeros((nloc,), jnp.int32)
        for i in range(num - 1):
            le = jnp.zeros((nloc,), jnp.bool_)
            eq = jnp.ones((nloc,), jnp.bool_)
            for w, sp in zip(lwords, splitters):
                sv = sp[i]
                le = le | (eq & (sv < w))
                eq = eq & (sv == w)
            dest = dest + (le | eq).astype(jnp.int32)
        return dest

    # planning pass: per-(src,dst) counts under the range partitioning
    def count_body(local: Table):
        dest = dest_of(local)
        return jnp.bincount(dest, length=num).astype(jnp.int32)[None, :]

    count_launch = shard_map(
        count_body, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    counts = run_collective(
        "distributed.sort_counts", lambda: count_launch(sharded)
    )
    cap = capacity or total_recv_capacity(counts)
    _warn_if_recv_exceeds_hbm(cap, table, "sort")
    # srt: allow-host-sync(two-phase sizing: the planning pass exists to produce this host capacity)
    pair_cap = _round_capacity(int(jnp.max(counts)))

    def body(local: Table, C):
        from .shuffle import exchange_ragged

        dest = dest_of(local)
        shuffled, occ, overflow = exchange_ragged(
            local, dest, C, cap, axis, impl, pair_capacity=pair_cap
        )
        # local sort with padding rows (occ False) sorted last
        swords = [jnp.where(occ, jnp.uint64(0), jnp.uint64(1))]
        for k in sort_keys:
            swords.extend(_key_words(shuffled.column(k.column), k))
        iota = jnp.arange(shuffled.row_count, dtype=jnp.int32)
        perm = jax.lax.sort(
            tuple(swords) + (iota,), num_keys=len(swords)
        )[-1]
        out = jax.tree_util.tree_map(
            lambda x: None if x is None else x[perm], shuffled
        )
        return out, occ[perm], overflow[None]

    fn = shard_map(
        body, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False,
    )
    out, occ, overflow = run_collective(
        "distributed.sort", lambda: fn(sharded, counts)
    )
    if on_overflow == "raise":
        check_overflow_compact(overflow, cap, "distributed sort")
    return out, occ, overflow
