"""Distributed relational ops: shuffle + local capped ops under shard_map.

The multi-chip join/aggregation path the GPU stack assembles from
GpuShuffleExchangeExec + per-GPU cudf kernels, here as single jittable
SPMD computations: hash-exchange co-partitions rows over ICI, then each
chip runs the local sort-based op on its partition with padding rows
masked by occupancy. Results stay device-resident and sharded (each chip
owns its key range by hash), exactly how a Spark stage chain consumes
them.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..column import Table
from ..ops.groupby import GroupbyAgg, groupby_aggregate_capped
from ..ops.join import inner_join_capped
from .mesh import SHUFFLE_AXIS, shard_map, shard_table
from .shuffle import exchange_by_hash


def distributed_groupby(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    mesh: Mesh,
    capacity: Optional[int] = None,
    groups_per_device: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
):
    """Shuffle-then-aggregate GROUP BY over the mesh.

    Returns (sharded padded result table, per-device group counts (P,),
    per-device shuffle overflow (P,)). Groups are complete: each key lives
    on exactly one device, by Spark hash partitioning.
    """
    num = int(mesh.shape[axis])
    per_dev = table.row_count // num
    cap = capacity or max(2 * per_dev // num, 16)
    seg_cap = groups_per_device or num * cap
    sharded = shard_table(table, mesh, axis)

    def body(local: Table):
        shuffled, occ, overflow = exchange_by_hash(local, by, num, cap, axis)
        agg, ngroups = groupby_aggregate_capped(
            shuffled, by, aggs, num_segments=seg_cap, row_valid=occ
        )
        return agg, ngroups[None], overflow[None]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    return fn(sharded)


def distributed_inner_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    mesh: Mesh,
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
    axis: str = SHUFFLE_AXIS,
):
    """Shuffle-shuffle hash-partitioned inner join over the mesh.

    Both sides are hash-exchanged on the join keys (co-partitioning), then
    each chip joins its partitions locally. Returns (sharded padded join
    output, per-device match counts, left/right shuffle overflows).
    """
    num = int(mesh.shape[axis])
    lcap = capacity or max(2 * (left.row_count // num) // num, 16)
    rcap = capacity or max(2 * (right.row_count // num) // num, 16)
    ocap = out_capacity or 4 * max(lcap, rcap) * num
    lsh = shard_table(left, mesh, axis)
    rsh = shard_table(right, mesh, axis)

    def body(l_local: Table, r_local: Table):
        ls, locc, lov = exchange_by_hash(l_local, on, num, lcap, axis)
        rs, rocc, rov = exchange_by_hash(r_local, on, num, rcap, axis)
        out, count = inner_join_capped(
            ls,
            rs,
            on,
            capacity=ocap,
            left_valid=locc,
            right_valid=rocc,
        )
        return out, count[None], lov[None], rov[None]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    return fn(lsh, rsh)
