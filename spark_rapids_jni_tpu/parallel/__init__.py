"""Multi-chip execution: mesh management and the shuffle-exchange backend.

The reference repo ships only per-GPU kernels; partition exchange lives in
the downstream spark-rapids plugin's UCX/NCCL shuffle manager (SURVEY.md
§2.5). Here the exchange is a first-class component: Spark-compatible hash
partitioning (ops/partition.py) + ``jax.lax.all_to_all`` over the mesh's
ICI axis under ``shard_map``, with XLA inserting the collective schedule.

Fault tolerance (the distributed analog of Spark's ExecutorLost /
shuffle-fetch retry semantics): ``run_collective`` gives every launch a
lineage-replay retry boundary, ``MeshHealth`` heartbeats a mesh with a
deadline, and ``MeshRunner`` degrades to the surviving device count and
replays instead of dying (tolerant.py, planmesh.py).
"""

from .mesh import (
    MeshHealth,
    make_mesh,
    shard_table,
    replicate_table,
    local_shards,
)
from .tolerant import MeshRunner, run_collective
from .planmesh import (
    MeshUnsupported,
    prepare_exchange,
    run_plan_mesh,
    run_plan_mesh_stream,
)
from .shuffle import (
    ShuffleOverflowError,
    SkewPlan,
    exchange,
    exchange_ragged,
    partition_counts,
    plan_capacity,
    plan_skew,
    shuffle_table,
    shuffle_table_compact,
    total_recv_capacity,
)
from .distributed import (
    GroupOverflowError,
    JoinOverflowError,
    broadcast_inner_join,
    distributed_anti_join,
    distributed_distinct,
    distributed_left_join,
    distributed_semi_join,
    distributed_groupby,
    distributed_inner_join,
    distributed_sort,
)

__all__ = [
    "MeshHealth",
    "MeshRunner",
    "MeshUnsupported",
    "run_collective",
    "run_plan_mesh",
    "run_plan_mesh_stream",
    "prepare_exchange",
    "make_mesh",
    "shard_table",
    "replicate_table",
    "local_shards",
    "exchange",
    "exchange_ragged",
    "partition_counts",
    "plan_capacity",
    "shuffle_table",
    "shuffle_table_compact",
    "total_recv_capacity",
    "plan_skew",
    "SkewPlan",
    "ShuffleOverflowError",
    "GroupOverflowError",
    "JoinOverflowError",
    "broadcast_inner_join",
    "distributed_anti_join",
    "distributed_distinct",
    "distributed_left_join",
    "distributed_semi_join",
    "distributed_groupby",
    "distributed_inner_join",
    "distributed_sort",
]
