"""Multi-chip execution: mesh management and the shuffle-exchange backend.

The reference repo ships only per-GPU kernels; partition exchange lives in
the downstream spark-rapids plugin's UCX/NCCL shuffle manager (SURVEY.md
§2.5). Here the exchange is a first-class component: Spark-compatible hash
partitioning (ops/partition.py) + ``jax.lax.all_to_all`` over the mesh's
ICI axis under ``shard_map``, with XLA inserting the collective schedule.
"""

from .mesh import make_mesh, shard_table, replicate_table, local_shards
from .shuffle import (
    ShuffleOverflowError,
    exchange,
    exchange_ragged,
    partition_counts,
    plan_capacity,
    shuffle_table,
    shuffle_table_compact,
    total_recv_capacity,
)
from .distributed import (
    GroupOverflowError,
    JoinOverflowError,
    broadcast_inner_join,
    distributed_anti_join,
    distributed_distinct,
    distributed_left_join,
    distributed_semi_join,
    distributed_groupby,
    distributed_inner_join,
    distributed_sort,
)

__all__ = [
    "make_mesh",
    "shard_table",
    "replicate_table",
    "local_shards",
    "exchange",
    "exchange_ragged",
    "partition_counts",
    "plan_capacity",
    "shuffle_table",
    "shuffle_table_compact",
    "total_recv_capacity",
    "ShuffleOverflowError",
    "GroupOverflowError",
    "JoinOverflowError",
    "broadcast_inner_join",
    "distributed_anti_join",
    "distributed_distinct",
    "distributed_left_join",
    "distributed_semi_join",
    "distributed_groupby",
    "distributed_inner_join",
    "distributed_sort",
]
