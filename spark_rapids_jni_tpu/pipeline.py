"""Pipelined dispatch plane: host/device overlap for batch streams.

The reference hides host<->device latency behind CUDA streams and async
decompression feeding the GPU decoder (SURVEY §2.3; ``io/parquet.py``
already imitates this for scans). The dispatch plane itself was fully
synchronous until ISSUE 5: every ``table_op_wire`` /
``table_op_resident`` call decoded wire bytes, launched, and blocked
before the next batch's serde could start, so host numpy serde and
device compute never overlapped. This module is the missing async axis:

* a **bounded worker pool** (``SPARK_RAPIDS_TPU_PIPELINE=<depth>|off``,
  default off) running host-side stage work — wire decode
  (``runtime_bridge._table_from_wire``) and wire encode
  (``_table_to_wire``) — on background threads while the caller thread
  drives device compute, with **backpressure** at the configured depth
  (at most ``depth`` stage jobs in flight; submits block past it);
* **ordered completion** via :class:`Pending` handles: results resolve
  in input order at the blocking points (``table_download_wire`` /
  ``table_num_rows`` / the stream driver's final collect);
* a **sync-replay error contract**: ANY worker failure is replayed
  synchronously on the resolving thread, so pipelining can change
  timing, never results or error surfacing — the exact exception the
  synchronous path would raise is the one the blocking point raises
  (the bucketed-runner fallback discipline applied to threads).

FIFO pickup plus capture-at-enqueue input snapshots make the pool
deadlock-free: a job's dependencies are always enqueued before it, so
the earliest unfinished job never waits on anything — see
``runtime_bridge.table_op_resident``.

Telemetry rides the existing planes: a ``pipeline.depth`` gauge,
``pipeline.stall_ms`` (time blocked on backpressure or an unfinished
stage) and ``pipeline.overlap_ms`` (worker busy time, i.e. host work
that ran concurrently with the caller) histograms on the span edges,
``pipeline.enqueued``/``completed``/``stalls``/``replays`` counters,
and per-stage ``pipeline.<stage>`` spans recorded on the WORKER thread
ids — a Chrome trace of a pipelined stream shows the decode/encode
lanes visibly overlapping the compute lane.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

from .utils import (
    config, faults, flight, lockcheck, log, metrics, profiler, tracing,
)

DEFAULT_DEPTH = 2
MAX_DEPTH = 64
# serde stages are numpy/copy-bound: a couple of workers saturate the
# host memory bus; more would only add GIL churn
MAX_WORKERS = 4

_OFF_VALUES = frozenset({"", "off", "none", "false", "disabled", "no", "0"})
_ON_VALUES = frozenset({"on", "true", "yes"})

# marks pool worker threads: a worker resolving a failed dependency
# must PROPAGATE, not replay (see Pending.resolve)
_WORKER_TLS = threading.local()


def in_worker() -> bool:
    """True on a pipeline pool worker thread."""
    return bool(getattr(_WORKER_TLS, "on", False))


class DependencyFailed(Exception):
    """Internal marker: a stage failed while materializing its INPUTS,
    before its own work touched (or consumed) anything. Work closures
    raise it on worker threads so the blocking point knows a sync
    replay is safe even for non-replayable (donated) work — nothing
    was consumed yet. Never surfaces to callers: resolve() unwraps it
    (``__cause__`` carries the real error)."""


def _parse_depth(raw) -> int:
    got = str(raw).strip().lower()
    if got in _OFF_VALUES:
        return 0
    if got in _ON_VALUES:
        return DEFAULT_DEPTH
    try:
        d = int(got)
    except ValueError:
        # a typo'd depth must fail loudly, not silently run sync under
        # the wrong label (the SPARK_RAPIDS_TPU_BUCKETS discipline)
        raise ValueError(
            f"SPARK_RAPIDS_TPU_PIPELINE must be <depth>|on|off, "
            f"got {raw!r}"
        ) from None
    if d < 0 or d > MAX_DEPTH:
        # loud, like a typo'd string: a silently clamped depth would
        # run with a different backpressure bound than configured
        raise ValueError(
            f"SPARK_RAPIDS_TPU_PIPELINE depth must be 0..{MAX_DEPTH}, "
            f"got {d}"
        )
    return d


# depth cache, invalidated by config.generation() (the buckets.policy
# pattern: a dispatch-path check costs an int compare)
_DEPTH = 0
_DEPTH_GEN = -1
_DEPTH_LOCK = lockcheck.make_lock("pipeline.depth")


def depth() -> int:
    """Configured pipeline depth (0 = synchronous dispatch). Flipping
    the flag off also tears the live pool down (workers exit after the
    queued jobs drain; the GIL switch interval is restored)."""
    global _DEPTH, _DEPTH_GEN
    gen = config.generation()
    if _DEPTH_GEN != gen:
        with _DEPTH_LOCK:
            if _DEPTH_GEN != gen:
                _DEPTH = _parse_depth(config.get_flag("PIPELINE"))
                _DEPTH_GEN = gen
        if _DEPTH == 0:
            _teardown_pool()
    return _DEPTH


def _teardown_pool() -> None:
    global _POOL
    with _POOL_LOCK:
        if _POOL is not None:
            _POOL.shutdown()
            _POOL = None


def enabled() -> bool:
    """True when resident dispatch enqueues instead of blocking."""
    return depth() > 0


class Pending:
    """A deferred stage result with the sync-replay error contract.

    ``work`` is a zero-arg closure producing the stage's value; it runs
    once on a worker thread, and — if that run raised — exactly once
    more, synchronously, on the first resolving thread. The replay's
    outcome (value or exception) is terminal and shared by every later
    :meth:`resolve`, so a genuine op error surfaces identically to the
    synchronous path and a parallelism-induced flake self-heals.
    """

    __slots__ = (
        "label", "ctx", "_work", "_event", "_value", "_error",
        "_replayed", "_replayable", "_orphaned", "_lock",
    )

    def __init__(
        self, work: Callable[[], object], label: str,
        replayable: bool = True,
    ):
        self.label = label
        # trace context captured at construction (= enqueue time):
        # contextvars do not flow into the pool threads by themselves,
        # so the worker re-activates the submitter's context around the
        # stage — its span lands in the submitting request's trace
        self.ctx = tracing.current()
        self._work = work
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._replayed = False
        # donated work is at-most-once: a failed run may already have
        # consumed its input buffers, and re-running it would surface a
        # deleted-array error instead of the op's own — the worker run
        # IS authoritative for non-replayable pendings. (A failure
        # while materializing INPUTS arrives wrapped in
        # DependencyFailed and stays replayable: nothing was consumed.)
        self._replayable = replayable
        self._orphaned = False
        self._lock = lockcheck.make_lock("pipeline.pending")

    # -- worker side ------------------------------------------------------
    def _run(self) -> None:
        t0 = time.perf_counter()
        _WORKER_TLS.stall_s = 0.0
        try:
            # the span lands on the WORKER tid: flight/Chrome traces
            # show this stage as its own lane overlapping the caller's
            with tracing.activate(self.ctx), \
                    metrics.span("pipeline." + self.label):
                self._value = self._work()
        except BaseException as e:
            self._error = e
            # classify at the worker boundary (faults.class.* counters)
            # — the error itself still surfaces via the sync-replay
            # contract at the blocking point
            faults.note_error_class(e, "pipeline." + self.label)
            if self._orphaned:
                # fire-and-forget: the caller freed this handle before
                # the failure and no blocking point will ever resolve
                # it — this WARN is the only trace the op ever broke
                _log_dropped_failure(self.label, e)
        else:
            # drop the closure: it pins the captured inputs (and, for
            # a chain, the previous Pending and ITS result) — keeping
            # it would retain every intermediate table until the final
            # blocking point, exactly the peak the plane exists to cut
            self._work = None
        finally:
            # telemetry strictly BEFORE the event: a resolver that
            # snapshots metrics right after resolve() returns must see
            # this stage's overlap/completed already recorded
            try:
                if metrics.enabled():
                    # worker BUSY time == host work overlapped with the
                    # caller; time this job spent blocked on an
                    # unfinished input is stall, not overlap (it is
                    # already recorded in pipeline.stall_ms)
                    busy = (
                        time.perf_counter() - t0
                        - getattr(_WORKER_TLS, "stall_s", 0.0)
                    )
                    metrics.hist_observe(
                        "pipeline.overlap_ms",
                        max(busy, 0.0) * 1e3,
                        bounds=metrics.SPAN_MS_BOUNDS,
                    )
                    metrics.counter_add("pipeline.completed")
            finally:
                self._event.set()

    # -- consumer side ----------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def failed_nowait(self) -> bool:
        """True when the worker run already failed and no replay has
        resolved it (leak/free diagnostics; never blocks)."""
        return (
            self._event.is_set()
            and self._error is not None
            and not self._replayed
        )

    def value_nowait(self):
        """The settled value, or None when unfinished or failed (leak
        report sizing; never blocks, never replays, never raises)."""
        if self._event.is_set() and self._error is None:
            return self._value
        return None

    def orphan(self) -> None:
        """Mark this pending as never-to-be-resolved (its handle was
        freed): a LATER worker failure logs itself instead of vanishing
        (the fire-and-forget case — no blocking point remains)."""
        self._orphaned = True

    def wait_settled(self) -> None:
        """Block until the worker run finished — success OR failure —
        without replaying or raising."""
        if not self._event.is_set():
            t0 = time.perf_counter()
            self._event.wait()
            _note_stall(time.perf_counter() - t0)

    def settle_terminally(self) -> None:
        """The donate barrier: block until this pending can never touch
        its captured buffers again. A failed-but-replayable pending
        would still dereference them at its later blocking-point
        replay, so the barrier runs that replay NOW (outcome stored for
        the blocking point; errors swallowed here — they surface
        there). This is the one sanctioned off-blocking-point replay:
        donation is about to make replaying impossible, which is
        exactly the synchronous ordering (reader completes before the
        consumer starts)."""
        self.wait_settled()
        if self._error is not None and self._replayable:
            try:
                self._replay_locked()
            # srt: allow-broad-except(replay outcome is stored as terminal state; the true blocking point raises it)
            except BaseException:
                pass  # stored as terminal; the blocking point raises it

    def resolve(self):
        """Block until the stage settles; return its value or raise the
        synchronous path's error. The ONLY place worker errors surface.

        A WORKER resolving a failed input does not replay it — it
        propagates the error into its own pending instead, so every
        replay in a failed chain runs on the true blocking point's
        thread (the caller), exactly like the synchronous path would
        have: replays cascade caller-side, input-first. Non-replayable
        (donated) work is replayed only when its failure happened
        BEFORE anything was consumed (a DependencyFailed wrapper from
        input materialization); its own post-consumption error is
        authoritative and raises as-is."""
        self.wait_settled()
        err = self._error
        if err is None:
            return self._value
        if getattr(_WORKER_TLS, "on", False):
            # propagate raw (wrappers included): the blocking point
            # downstream owns all replay decisions
            raise err
        can_replay = self._replayable or isinstance(err, DependencyFailed)
        if not can_replay:
            raise err
        self._replay_locked()
        if self._error is not None:
            err = self._error
            if isinstance(err, DependencyFailed) and err.__cause__:
                raise err.__cause__
            raise err
        return self._value

    def _replay_locked(self) -> None:
        """Run the at-most-one synchronous replay (no-op when already
        settled terminally); the outcome lands in _value/_error."""
        with self._lock:
            if self._error is None or self._replayed:
                return
            self._replayed = True
            err = self._error
            metrics.counter_add("pipeline.replays")
            if flight.enabled():
                flight.record("I", "pipeline.replay", self.label)
            log.log(
                "WARN", "pipeline", "worker_failed_replaying_sync",
                stage=self.label,
                error=f"{type(err).__name__}: {str(err)[:200]}",
            )
            try:
                # the replay stays in the ORIGINAL request's trace —
                # a replay must never mint (or lose) the trace id
                with tracing.activate(self.ctx), \
                        metrics.span("pipeline.replay." + self.label):
                    self._value = self._work()
                self._error = None
            except BaseException as e:
                # terminal: this IS the sync path's own error
                self._error = e
                raise
            finally:
                # settled either way — release the captured inputs
                # (see _run)
                self._work = None


def materialize(value):
    """Resolve a possibly-Pending value (identity for settled ones)."""
    return value.resolve() if isinstance(value, Pending) else value


def materialize_inputs(values: Sequence) -> list:
    """Resolve a stage's input list. On a WORKER thread, any failure is
    wrapped in :class:`DependencyFailed`: it happened before this
    stage's own work ran, so even non-replayable (donated) work is
    safely replayable from the blocking point — nothing was consumed."""
    try:
        return [materialize(v) for v in values]
    except BaseException as e:
        if getattr(_WORKER_TLS, "on", False):
            raise DependencyFailed(str(e)) from e
        raise


def _log_dropped_failure(label: str, error: BaseException) -> None:
    """A freed (fire-and-forget) pending failed after its handle was
    gone: WARN + flight instant — the only trace left."""
    log.log(
        "WARN", "pipeline", "freed_pending_failed", stage=label,
        error=f"{type(error).__name__}: {str(error)[:200]}",
    )
    if flight.enabled():
        flight.record("I", "pipeline.freed_failed", label)


def _note_stall(seconds: float) -> None:
    profiler.note_stall(seconds)
    if getattr(_WORKER_TLS, "on", False):
        # a worker blocked on an input: subtracted from that job's
        # overlap_ms so the wait isn't double-counted as overlap
        _WORKER_TLS.stall_s = (
            getattr(_WORKER_TLS, "stall_s", 0.0) + seconds
        )
    if metrics.enabled():
        metrics.counter_add("pipeline.stalls")
        metrics.hist_observe(
            "pipeline.stall_ms", seconds * 1e3,
            bounds=metrics.SPAN_MS_BOUNDS,
        )


class _Pool:
    """FIFO worker pool with depth-bounded in-flight jobs.

    The semaphore slot is held from submit until the job FINISHES, so
    at most ``depth`` jobs are queued-or-running and a producer that
    runs ahead blocks in :meth:`submit` — the backpressure that keeps a
    fast wire producer from buffering an unbounded resident set.
    """

    __slots__ = ("depth", "_q", "_slots", "_workers", "_old_switch")

    # CPython's default GIL switch interval is 5ms: a worker that
    # finishes a stage keeps the GIL through its next job's numpy glue
    # while the consumer sits runnable for multiple of those windows —
    # measured ~20% of stream wall on a saturated host. Stage handoffs
    # are the pipeline's heartbeat, so a live pool tightens the
    # interval (restored at shutdown).
    SWITCH_INTERVAL_S = 0.0005

    def __init__(self, d: int):
        self.depth = d
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._slots = threading.BoundedSemaphore(d)
        self._old_switch = sys.getswitchinterval()
        if self._old_switch > self.SWITCH_INTERVAL_S:
            sys.setswitchinterval(self.SWITCH_INTERVAL_S)
        self._workers = [
            threading.Thread(
                target=self._worker_loop,
                name=f"srt-pipeline-{i}",
                daemon=True,
            )
            for i in range(max(1, min(d, MAX_WORKERS)))
        ]
        for w in self._workers:
            w.start()
        metrics.gauge_set("pipeline.depth", d)

    def _worker_loop(self) -> None:
        _WORKER_TLS.on = True
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                item._run()
            finally:
                self._slots.release()

    def submit(self, pending: Pending) -> Pending:
        if not self._slots.acquire(blocking=False):
            t0 = time.perf_counter()
            self._slots.acquire()  # backpressure: depth jobs in flight
            _note_stall(time.perf_counter() - t0)
        metrics.counter_add("pipeline.enqueued")
        self._q.put(pending)
        return pending

    def shutdown(self) -> None:
        """Stop the workers after the queued jobs drain (config-change
        teardown; daemon threads make this best-effort at exit)."""
        for _ in self._workers:
            self._q.put(None)
        if self._old_switch > self.SWITCH_INTERVAL_S:
            sys.setswitchinterval(self._old_switch)


# pool cache keyed on the configured depth; rebuilt (and the old pool
# drained) when the flag changes mid-process (tests flip it freely)
_POOL: Optional[_Pool] = None
_POOL_LOCK = lockcheck.make_lock("pipeline.pool")


def _pool() -> _Pool:
    global _POOL
    d = depth()
    if d <= 0:
        # callers gate on enabled(); a zero-slot pool would deadlock
        # the first submit, so fail loudly instead
        raise RuntimeError("pipeline pool requested while disabled")
    p = _POOL
    if p is not None and p.depth == d:
        return p
    with _POOL_LOCK:
        if _POOL is None or _POOL.depth != d:
            if _POOL is not None:
                _POOL.shutdown()
            _POOL = _Pool(d)
        return _POOL


def submit(
    work: Callable[[], object], label: str, replayable: bool = True
) -> Pending:
    """Enqueue ``work`` on the pipeline pool; returns its Pending.
    Callers must have checked :func:`enabled` (a zero-depth pool cannot
    exist). Pass ``replayable=False`` for work that consumes its inputs
    (donation): its worker error surfaces as-is instead of replaying."""
    return _pool().submit(Pending(work, label, replayable=replayable))


def enqueue(pending: Pending) -> Pending:
    """Submit a pre-built Pending — for callers that must publish the
    handle (e.g. register it as a reader of its inputs) ATOMICALLY with
    capturing those inputs, before any worker can run it."""
    return _pool().submit(pending)


def drain() -> None:
    """Block until every in-flight job has finished (test isolation;
    flag teardown). Acquiring all depth slots means none are held."""
    p = _POOL
    if p is None:
        return
    for _ in range(p.depth):
        p._slots.acquire()
    for _ in range(p.depth):
        p._slots.release()


# ---------------------------------------------------------------------------
# dedicated IO lane: spill writes/restores (utils/spill.py)
#
# Spill IO must overlap compute WITHOUT competing for the dispatch
# pool's depth slots: an eviction triggered from inside a pool job that
# then blocked on the pool's own backpressure semaphore would deadlock
# at depth 1 (the only slot is held by the job doing the evicting), and
# spill traffic should never consume the stream's backpressure budget.
# One FIFO worker thread, created lazily, independent of the PIPELINE
# flag — disk writes overlap compute even when dispatch is synchronous.
# ---------------------------------------------------------------------------

_IO_Q: "queue.SimpleQueue" = queue.SimpleQueue()
_IO_LOCK = lockcheck.make_lock("pipeline.io")
_IO_THREAD: Optional[threading.Thread] = None


def _io_loop() -> None:
    while True:
        item = _IO_Q.get()
        if item is None:
            return
        item._run()


def submit_io(
    work: Callable[[], object], label: str, replayable: bool = True
) -> Pending:
    """Enqueue host-side I/O work on the dedicated IO worker; returns
    its Pending (same sync-replay error contract as pool stages —
    failures surface at ``resolve``)."""
    global _IO_THREAD
    p = Pending(work, label, replayable=replayable)
    with _IO_LOCK:
        if _IO_THREAD is None or not _IO_THREAD.is_alive():
            _IO_THREAD = threading.Thread(
                target=_io_loop, name="srt-io", daemon=True
            )
            _IO_THREAD.start()
    metrics.counter_add("pipeline.io_enqueued")
    _IO_Q.put(p)
    return p


def drain_io() -> None:
    """Block until every queued IO job has finished (test isolation):
    the lane is FIFO, so a no-op fence job is a barrier."""
    with _IO_LOCK:
        t = _IO_THREAD
    if t is None or not t.is_alive():
        return
    submit_io(lambda: None, "io.fence").wait_settled()


def stage_ahead(
    items: Sequence,
    prepare: Callable,
    execute: Callable,
    label: str = "prepare",
    lookahead: int = 1,
) -> List:
    """Drive ``items`` through prepare -> execute with the prepares run
    ahead on pool workers.

    ``prepare(item)`` is host-side staging work (a pack, a counts pass)
    that is safe to run for item N+1 while the caller thread is inside
    ``execute`` for item N — the mesh path's exchange/compute overlap
    (ISSUE 17). Up to ``lookahead`` prepares run ahead of the execute
    cursor; their worker busy time is the ``pipeline.overlap_ms``
    evidence that exchange launches and next-batch staging actually
    overlapped. Results return in input order; with the pipeline off
    both stages run inline per item — byte-identical, same errors.
    """
    items = list(items)
    if depth() == 0:
        out = []
        for it in items:
            faults.check_cancel()
            out.append(execute(prepare(it)))
        return out
    pool = _pool()
    n = len(items)
    ahead = max(1, min(int(lookahead) + 1, depth()))
    prepped: List[Optional[Pending]] = [None] * n
    submitted = 0
    out = []
    for i in range(n):
        faults.check_cancel()
        while submitted < min(n, i + ahead):
            j = submitted
            prepped[j] = pool.submit(
                Pending(lambda it=items[j]: prepare(it), label)
            )
            submitted += 1
        ready = prepped[i].resolve()
        prepped[i] = None  # drop the ref: consumed by execute below
        out.append(execute(ready))
    return out


def run_stream(
    items: Sequence,
    decode: Callable,
    compute: Callable,
    encode: Callable,
) -> List:
    """Drive ``items`` through decode -> compute -> encode with
    host/device overlap and ordered completion.

    ``decode`` (wire bytes -> device table) and ``encode`` (result
    table -> wire bytes) run on pool workers; ``compute`` (the
    fused-plan launch) runs on the CALLER thread in input order, so
    batch N+1's decode and batch N-1's encode overlap batch N's
    executable. Results return in input order. With the pipeline off
    the three stages run inline per item — byte-identical, same errors,
    no threads.
    """
    items = list(items)
    d = depth()
    if d == 0:
        out = []
        for it in items:
            # the cooperative cancellation checkpoint between batches
            # (no-op without a bound faults.CancelToken)
            faults.check_cancel()
            out.append(encode(compute(decode(it))))
        return out
    pool = _pool()
    n = len(items)
    decoded: List[Optional[Pending]] = [None] * n
    encoded: List[Optional[Pending]] = [None] * n
    submitted = 0
    for i in range(n):
        faults.check_cancel()  # between-batch cancellation checkpoint
        # keep up to `depth` decodes in flight INCLUDING the current
        # one (submitting depth+1 against a depth-slot semaphore would
        # block every iteration and record phantom backpressure stalls)
        while submitted < min(n, max(i + d, i + 1)):
            j = submitted
            decoded[j] = pool.submit(
                Pending(lambda it=items[j]: decode(it), "decode")
            )
            submitted += 1
        tbl = decoded[i].resolve()
        decoded[i] = None  # drop the ref: the table is consumed below
        out = compute(tbl)
        encoded[i] = pool.submit(Pending(lambda o=out: encode(o), "encode"))
    return [p.resolve() for p in encoded]
