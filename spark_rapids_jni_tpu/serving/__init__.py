"""Multi-tenant serving tier: the resident-daemon deployment shape.

The reference stack serves many concurrent Spark tasks from ONE
long-lived device process (the JVM executor holding the shaded
``rapids-4-spark-jni`` artifact). This package is that tier for the
TPU-native backend: a localhost query-stream daemon
(:class:`~.server.Server`) with per-client sessions
(:class:`~.session.Session`: scoped table namespace + HBM budget),
weighted-deficit fair-share scheduling with typed BUSY shedding
(:class:`~.scheduler.FairScheduler`), and a small client
(:class:`~.client.Client`) for tests and bench. See
CONTRIBUTING.md "Serving daemon".
"""

from .client import (  # noqa: F401
    Client,
    ServingBusy,
    ServingCancelled,
    ServingCheckpointCorrupt,
    ServingDeadlineExceeded,
    ServingDegraded,
    ServingDraining,
    ServingError,
    ServingOverBudget,
    ServingQuarantined,
    ServingResourceExhausted,
    ServingResumeDenied,
    ServingSessionLimit,
    ServingTableError,
    ServingTransientError,
)
from .durable import (  # noqa: F401
    CheckpointCorrupt,
    Draining,
    ResumeDenied,
    SessionQuarantined,
)
from .scheduler import Busy, FairScheduler, Ticket  # noqa: F401
from .server import Server, SessionLimit, serve  # noqa: F401
from .session import (  # noqa: F401
    OverBudget,
    Session,
    SessionClosed,
    estimate_request_bytes,
)
