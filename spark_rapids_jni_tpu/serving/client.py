"""In-process client for the serving daemon (tests + bench).

Speaks the frame protocol of serving/frames.py over a localhost socket
and maps the daemon's typed error responses back onto typed Python
exceptions — so a shed request raises :class:`ServingBusy`, an
admission rejection :class:`ServingOverBudget` (message names the
session budget), and a cross-session table access
:class:`ServingTableError` (a KeyError naming the session), exactly
mirroring what an embedded JNI caller would see as status codes.
"""

from __future__ import annotations

import contextlib
import socket
from typing import List, Optional, Sequence

from ..utils import tracing
from . import frames


class ServingError(RuntimeError):
    """Base typed daemon error. ``type`` is the wire error type."""

    def __init__(self, type_: str, message: str, exception: str = ""):
        super().__init__(message)
        self.type = type_
        self.exception = exception


class ServingBusy(ServingError):
    """The session's queue was at depth: request shed, retry later."""


class ServingOverBudget(ServingError):
    """Admission rejected the request against the session HBM budget."""


class ServingSessionLimit(ServingError):
    """The daemon is at SERVE_MAX_SESSIONS."""


class ServingTableError(ServingError, KeyError):
    """Unknown (or cross-session) table id — labeled per session."""

    def __str__(self) -> str:  # KeyError reprs its arg; keep the label
        return self.args[0] if self.args else ""


class ServingDegraded(ServingError):
    """The daemon's circuit breaker is open: shed without device work.
    The message names when the next recovery probe runs."""


class ServingCancelled(ServingError):
    """The request was cancelled server-side before completing."""


class ServingDeadlineExceeded(ServingError):
    """The request's deadline elapsed before the work finished."""


class ServingResourceExhausted(ServingError):
    """Device memory pressure the daemon could not degrade around."""


class ServingTransientError(ServingError):
    """A transient device failure that outlived the retry budget —
    safe to retry client-side."""


class ServingResumeDenied(ServingError):
    """A reconnect hello carried a missing or wrong resume token."""


class ServingQuarantined(ServingError):
    """The session's durable state was quarantined during restore —
    its tables are unrecoverable; open a fresh session."""


class ServingDraining(ServingError):
    """The daemon is draining for a rolling restart: reconnect to its
    replacement (or retry after the restart)."""


class ServingCheckpointCorrupt(ServingError):
    """Durable state failed an integrity check server-side."""


_ERROR_CLASSES = {
    "busy": ServingBusy,
    "over_budget": ServingOverBudget,
    "session_limit": ServingSessionLimit,
    "unknown_table": ServingTableError,
    "degraded": ServingDegraded,
    "cancelled": ServingCancelled,
    "deadline_exceeded": ServingDeadlineExceeded,
    "resource_exhausted": ServingResourceExhausted,
    "transient_device": ServingTransientError,
    "resume_denied": ServingResumeDenied,
    "session_quarantined": ServingQuarantined,
    "draining": ServingDraining,
    "checkpoint_corrupt": ServingCheckpointCorrupt,
}


def _raise_error(err: dict) -> None:
    type_ = str(err.get("type", "internal"))
    cls = _ERROR_CLASSES.get(type_, ServingError)
    exc = cls(type_, str(err.get("message", "")),
              str(err.get("exception", "")))
    # a pre-admission static rejection ships its tagged plan report
    # (plancheck.analyze shape) alongside the message
    if "plan_report" in err:
        exc.plan_report = err["plan_report"]
    raise exc


class Client:
    """One connection to the daemon. ``with Client(port) as c:`` opens
    a session on connect; pass ``session=`` to attach another
    connection to an existing session (many Spark tasks, one tenant)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 name: Optional[str] = None, weight: float = 1.0,
                 session: Optional[str] = None, timeout: float = 60.0,
                 deadline_s: Optional[float] = None,
                 resume: Optional[str] = None,
                 mesh: Optional[int] = None):
        self._addr = (host, int(port))
        # mesh=N asks for mesh-backed execution over N devices
        # (0/None = single-device); an impossible count is a typed
        # bad_request at hello, naming the remedy
        self._hello = {
            k: v for k, v in (
                ("name", name), ("weight", weight), ("session", session),
                ("deadline_s", deadline_s), ("resume", resume),
                ("mesh", mesh),
            ) if v is not None
        }
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self.session: Optional[str] = None
        self.name: Optional[str] = None
        self.budget_bytes: Optional[int] = None
        self.queue_depth: Optional[int] = None
        # durable daemons hand out a resume token at open: the secret
        # a reconnect presents to re-attach to this session
        self.resume_token: Optional[str] = resume

    # -- lifecycle --------------------------------------------------------
    def connect(self) -> "Client":
        s = socket.create_connection(self._addr, timeout=self._timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        resp = self._rpc({"cmd": "hello", **self._hello})
        self.session = resp.get("session")
        self.name = resp.get("name")
        self.budget_bytes = resp.get("budget_bytes")
        self.queue_depth = resp.get("queue_depth")
        if resp.get("resume_token") is not None:
            self.resume_token = resp["resume_token"]
        return self

    def reconnect(self) -> "Client":
        """Re-attach to the SAME session after a socket loss (or a
        daemon restart): fresh connection, hello carrying the session
        id + resume token. Pair with per-request ids (``req=``) on
        mutating commands for at-most-once semantics across the gap."""
        self.kill()
        if self.session is not None:
            self._hello["session"] = self.session
            if self.resume_token is not None:
                self._hello["resume"] = self.resume_token
        return self.connect()

    def close(self) -> None:
        """Graceful detach: bye + socket close (idempotent)."""
        s = self._sock
        if s is None:
            return
        self._sock = None
        with contextlib.suppress(Exception):
            frames.send_frame(s, {"cmd": "bye"})
            frames.recv_frame(s)
        with contextlib.suppress(OSError):
            s.close()

    def kill(self) -> None:
        """Abrupt disconnect WITHOUT bye — the client-crash path; the
        daemon must tear the session down and reclaim its tables."""
        s = self._sock
        self._sock = None
        if s is not None:
            with contextlib.suppress(OSError):
                s.close()

    def __enter__(self) -> "Client":
        if self._sock is None:
            self.connect()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # -- protocol ---------------------------------------------------------
    def _rpc(self, header: dict, buffers: Sequence[bytes] = ()):
        if self._sock is None:
            raise RuntimeError("client is not connected")
        # trace-context stamp: propagate the ambient context if the
        # caller has one, else mint a fresh per-request trace when the
        # plane is on — the server joins it, so both processes' flight
        # dumps share one trace id (tools/tracequery.py merges them)
        ctx = tracing.current()
        if ctx is None and tracing.context_enabled():
            ctx = tracing.new_context()
        if ctx is not None and "traceparent" not in header:
            header["traceparent"] = ctx.header
        with tracing.activate(ctx):
            tok = tracing.span_begin("client.rpc")
            try:
                frames.send_frame(self._sock, header, buffers)
                resp, payload = frames.recv_frame(self._sock)
            except BaseException as e:
                tracing.span_end(tok, error=type(e).__name__)
                raise
            tracing.span_end(
                tok,
                error=None if resp.get("ok")
                else str((resp.get("error") or {}).get("type", "error")),
            )
        if not resp.get("ok"):
            _raise_error(resp.get("error") or {})
        resp["_payload"] = payload
        return resp

    # -- commands ---------------------------------------------------------
    def stream(self, ops: list, batches: Sequence,
               deadline_s: Optional[float] = None) -> List[tuple]:
        """Run ``ops`` (a plan: JSON-able list of op dicts) over wire
        batches; returns one result 5-tuple per batch, in order.
        ``deadline_s`` bounds this one request (overrides the session
        default from hello)."""
        metas, buffers = frames.batches_to_parts(batches)
        header = {"cmd": "stream", "plan": list(ops), "batches": metas}
        if deadline_s is not None:
            header["deadline_s"] = float(deadline_s)
        resp = self._rpc(header, buffers)
        return frames.batches_from_parts(
            resp.get("results") or [], resp["_payload"]
        )

    def upload(self, batch, req: Optional[str] = None) -> int:
        meta, buffers = frames.batch_to_parts(batch)
        header = {"cmd": "upload", "batch": meta}
        if req is not None:
            header["req"] = str(req)
        resp = self._rpc(header, buffers)
        return int(resp["table"])

    def plan(self, ops: list, tables: Sequence[int],
             donate: bool = False,
             deadline_s: Optional[float] = None,
             req: Optional[str] = None) -> int:
        header = {
            "cmd": "plan", "plan": list(ops),
            "tables": [int(t) for t in tables], "donate": bool(donate),
        }
        if deadline_s is not None:
            header["deadline_s"] = float(deadline_s)
        if req is not None:
            header["req"] = str(req)
        resp = self._rpc(header)
        return int(resp["table"])

    def download(self, table: int) -> tuple:
        resp = self._rpc({"cmd": "download", "table": int(table)})
        batch, _ = frames.batch_from_parts(
            resp["result"], resp["_payload"], 0
        )
        return batch

    def free(self, table: int, req: Optional[str] = None) -> int:
        header = {"cmd": "free", "table": int(table)}
        if req is not None:
            header["req"] = str(req)
        resp = self._rpc(header)
        return int(resp.get("bytes", 0))

    def stats(self) -> dict:
        return self._rpc({"cmd": "stats"})["stats"]

    def trace(self) -> dict:
        """Live introspection plane: the daemon's slow-request log
        (top-K by duration, tail-sampled span detail) plus a
        Prometheus-style text exposition of the metrics snapshot."""
        return self._rpc({"cmd": "trace"})["trace"]

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Rolling-restart drain: the daemon stops admitting, finishes
        in-flight work, checkpoints, answers, and exits. Returns the
        response (``drained`` False = deadline hit with work left)."""
        header = {"cmd": "drain"}
        if deadline_s is not None:
            header["deadline_s"] = float(deadline_s)
        return self._rpc(header)
