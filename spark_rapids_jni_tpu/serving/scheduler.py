"""Admission control + weighted-deficit fair-share scheduling.

The daemon's dispatch discipline: every served request becomes a
:class:`Ticket` in its session's FIFO queue, and a small executor pool
pulls tickets in **deficit-round-robin** order — each sweep credits
every backlogged session ``quantum × weight`` rows of deficit and runs
its head request only once the deficit covers the request's row cost.
A heavy session streaming huge batches therefore cannot starve a light
one: both earn credit at the same rate (scaled by weight), so the light
session's small requests interleave after at most a bounded number of
heavy batches, regardless of how deep the heavy backlog is.

Admission is two-layered:

* **queue depth** — a session may hold at most ``queue_depth`` queued
  tickets (``SPARK_RAPIDS_TPU_SERVE_QUEUE_DEPTH``). A request past that
  is *shed* with the typed :class:`Busy` (the server turns it into a
  BUSY response — the client always gets an answer, never a hang).
* **HBM budget** — enforced by :meth:`session.Session.admit` before the
  ticket is built (see session.py).

The executor threads sit on top of the pipelined dispatch plane: the
work they run is the runtime bridge's own decode → ``run_plan`` →
encode path, so with ``SPARK_RAPIDS_TPU_PIPELINE`` on, wire serde
inside a ticket still overlaps device compute exactly as in
``table_stream_wire``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..utils import faults, flight, lockcheck, metrics, profiler, tracing
from .session import Session, SessionClosed, executing

# deficit credited to a backlogged session per sweep, in rows, before
# the weight multiplier — roughly one large batch
DEFAULT_QUANTUM_ROWS = 65536


class Busy(Exception):
    """Typed shed: the session's queue is at depth. Retry later."""


class Ticket:
    """One schedulable request: closure + cost + settlement event."""

    __slots__ = (
        "session", "fn", "cost", "label", "charge", "prof", "token",
        "ctx", "submit_t", "start_t", "end_t", "value", "error",
        "_event",
    )

    def __init__(self, session: Session, fn: Callable[[], object],
                 cost: int, label: str, charge: int, prof=None,
                 token=None):
        self.session = session
        self.fn = fn
        self.cost = max(int(cost), 1)
        self.label = label
        self.charge = max(int(charge), 0)
        self.prof = prof
        self.token = token  # faults.CancelToken or None
        # trace context captured at SUBMIT: contextvars do not flow
        # into the executor pool by themselves, so the worker
        # re-activates this around the work (utils/tracing.py)
        self.ctx = tracing.current()
        self.submit_t = time.perf_counter()
        self.start_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.value = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self):
        """Block until executed; return the value or raise the error."""
        self._event.wait()
        if self.error is not None:
            raise self.error
        return self.value

    def _settle(self) -> None:
        self._event.set()


class FairScheduler:
    """Deficit-round-robin scheduler over per-session FIFO queues."""

    def __init__(self, workers: int = 2, queue_depth: int = 16,
                 quantum_rows: int = DEFAULT_QUANTUM_ROWS):
        self.workers = max(int(workers), 1)
        self.queue_depth = max(int(queue_depth), 1)
        self.quantum_rows = max(int(quantum_rows), 1)
        self._lock = lockcheck.make_lock("scheduler.queues")
        self._cv = lockcheck.make_condition(self._lock)
        self._queues: Dict[str, deque] = {}
        self._deficit: Dict[str, float] = {}
        self._sessions: Dict[str, Session] = {}
        self._inflight: Dict[str, int] = {}
        self._order: list = []
        self._rr = 0
        self._stopping = False
        self._threads: list = []

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FairScheduler":
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker_loop,
                name=f"srt-serve-exec-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            dropped = [t for q in self._queues.values() for t in q]
            for q in self._queues.values():
                q.clear()
            self._cv.notify_all()
        for t in dropped:
            t.error = SessionClosed(
                f"session {t.session.name}: scheduler stopped"
            )
            t.session.release(t.charge)
            t._settle()
        for th in self._threads:
            th.join(timeout=10)
        self._threads = []

    # -- session registration --------------------------------------------
    def register(self, session: Session) -> None:
        with self._cv:
            self._queues[session.id] = deque()
            self._deficit[session.id] = 0.0
            self._sessions[session.id] = session
            self._inflight[session.id] = 0
            self._order.append(session.id)

    def unregister(self, session: Session) -> None:
        """Drop the session's queued tickets (settled with the typed
        SessionClosed) and wait for its in-flight ones to finish, so a
        teardown that follows can reclaim tables no executor still
        touches."""
        with self._cv:
            q = self._queues.pop(session.id, None)
            self._deficit.pop(session.id, None)
            self._sessions.pop(session.id, None)
            if session.id in self._order:
                self._order.remove(session.id)
            dropped = list(q) if q else []
            self._cv.notify_all()
        for t in dropped:
            t.error = SessionClosed(
                f"session {session.name} closed while queued"
            )
            t.session.release(t.charge)
            t._settle()
        with self._cv:
            while self._inflight.get(session.id, 0) > 0:
                self._cv.wait()
            self._inflight.pop(session.id, None)

    # -- submission -------------------------------------------------------
    def submit(self, session: Session, fn: Callable[[], object],
               cost: int = 1, label: str = "req", charge: int = 0,
               prof=None, shed: bool = True, token=None) -> Ticket:
        """Queue one request. ``shed=True`` raises the typed
        :class:`Busy` when the session queue is at depth;
        ``shed=False`` (a stream's follow-on batches, whose in-flight
        window the server already bounds) waits for a slot instead —
        executors always drain, so the wait terminates. ``token`` is
        the request's :class:`faults.CancelToken`: the executor binds
        it around the work (so between-segment / between-batch
        checkpoints observe it) and settles an already-cancelled
        ticket without running it at all."""
        t = Ticket(session, fn, cost, label, charge, prof, token)
        shed_now = False
        with self._cv:
            while True:
                if self._stopping:
                    raise SessionClosed(
                        f"session {session.name}: scheduler stopped"
                    )
                q = self._queues.get(session.id)
                if q is None:
                    raise SessionClosed(
                        f"session {session.name} is not registered"
                    )
                if len(q) < self.queue_depth:
                    break
                if shed:
                    # bookkeeping happens OUTSIDE this block:
                    # Session.note_shed takes the session lock, and
                    # session orders BEFORE scheduler in the sanctioned
                    # lock order (lockcheck.LOCK_ORDER) — taking it
                    # here was the inversion srt-check's dynamic shim
                    # flagged across test_serving.py
                    shed_now = True
                    break
                self._cv.wait()
            if not shed_now:
                t.submit_t = time.perf_counter()
                q.append(t)
                self._cv.notify_all()
        if shed_now:
            session.note_shed()
            metrics.counter_add("serving.shed")
            if flight.enabled():
                flight.record("I", "serving.shed", session.name)
            raise Busy(
                f"session {session.name}: queue depth "
                f"{self.queue_depth} reached — request shed, "
                "retry later"
            )
        metrics.counter_add("serving.requests")
        return t

    # -- executor side ----------------------------------------------------
    def _next(self) -> Optional[Ticket]:
        """Pop the next ticket in deficit-round-robin order; None on
        stop. Each visit to a backlogged session credits
        ``quantum_rows × weight``; its head runs once covered."""
        with self._cv:
            while True:
                if self._stopping:
                    return None
                backlog = False
                for _ in range(max(len(self._order), 1)):
                    if not self._order:
                        break
                    sid = self._order[self._rr % len(self._order)]
                    self._rr += 1
                    q = self._queues.get(sid)
                    if not q:
                        continue
                    backlog = True
                    sess = self._sessions[sid]
                    self._deficit[sid] += self.quantum_rows * sess.weight
                    if q[0].cost <= self._deficit[sid]:
                        t = q.popleft()
                        self._deficit[sid] -= t.cost
                        if not q:
                            # standard DRR: an emptied queue forfeits
                            # accumulated credit (no bursting later)
                            self._deficit[sid] = 0.0
                        self._inflight[sid] = (
                            self._inflight.get(sid, 0) + 1
                        )
                        self._cv.notify_all()  # free queue slot
                        return t
                if not backlog:
                    self._cv.wait()
                # else: sweep again — deficits grow each sweep, so some
                # head request becomes runnable in bounded sweeps

    def _worker_loop(self) -> None:
        while True:
            t = self._next()
            if t is None:
                return
            t.start_t = time.perf_counter()
            wait_s = t.start_t - t.submit_t
            sess = t.session
            sess.note_wait(wait_s)
            metrics.hist_observe(
                "serving.queue_wait_ms", wait_s * 1e3,
                bounds=metrics.SPAN_MS_BOUNDS,
            )
            with tracing.activate(t.ctx):
                if flight.enabled():
                    # the wait is only measurable at dequeue: record
                    # the queue-wait span retroactively with backdated
                    # timestamps (both events on THIS thread, so the
                    # exporter's per-tid B/E pairing holds)
                    tp = None if t.ctx is None else t.ctx.header
                    flight.record("B", "serving.queue_wait", tp,
                                  t_ns=int(t.submit_t * 1e9))
                    flight.record("E", "serving.queue_wait",
                                  t_ns=int(t.start_t * 1e9))
                try:
                    if t.token is not None:
                        t.token.check()  # cancelled while queued
                    with executing(sess, t), \
                            profiler.bound_session(t.prof), \
                            faults.scoped_token(t.token):
                        with metrics.span(
                            "serving." + t.label, session=sess.name
                        ):
                            t.value = t.fn()
                except BaseException as e:
                    t.error = e
                    faults.note_error_class(e, "serving." + t.label)
            t.end_t = time.perf_counter()
            lat_s = t.end_t - t.submit_t
            sess.note_latency(lat_s)
            metrics.hist_observe(
                "serving.latency_ms", lat_s * 1e3,
                bounds=metrics.SPAN_MS_BOUNDS,
            )
            with self._cv:
                self._inflight[sess.id] = max(
                    self._inflight.get(sess.id, 1) - 1, 0
                )
                self._cv.notify_all()
            sess.release(t.charge)
            t._settle()

    # -- introspection ----------------------------------------------------
    def queued(self, session: Session) -> int:
        with self._lock:
            q = self._queues.get(session.id)
            return len(q) if q else 0

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until no queued or in-flight work remains across every
        session — the drain barrier for rolling restarts. Returns False
        if ``timeout`` (seconds) elapsed with work still pending."""
        deadline = (
            None if timeout is None else time.monotonic() + float(timeout)
        )
        with self._cv:
            while True:
                busy = any(self._queues.values()) or any(
                    n > 0 for n in self._inflight.values()
                )
                if not busy:
                    return True
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
