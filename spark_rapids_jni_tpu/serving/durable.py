"""Durable serving plane: crash-safe checkpoint/restore for sessions.

The reference stack ships as a resident substrate inside long-lived
Spark executors, where a JVM restart must not cost the cluster its
tenant state or its latency floor. This module is that durability
contract for the serving daemon: every namespace mutation
(upload / plan-output / free / bye) is journaled to a per-session
write-ahead log before the response leaves the process, table payloads
are checkpointed through the spill tier's ``.npz`` serde
(``spill.save_table_npz``), and on restart the daemon replays journals
to recover session namespaces, HBM accounting, and budgets — then
pre-compiles every previously-served plan from the warm-start manifest
BEFORE the listener accepts traffic, so the second life pays zero
compiles on plans the first life already served.

Journal format (``<sid>.wal`` in the checkpoint directory):

* header: the 6-byte magic ``SRTJ1\\n``
* records: ``u32 LE payload length | u32 LE crc32(payload) | payload``
  where payload is UTF-8 JSON. Appends are flushed + ``fsync``'d;
  payload ``.npz`` files are written tmp + fsync + atomic rename
  BEFORE their journal record, so a record that exists always points
  at a complete payload.

Recovery semantics:

* a **torn tail** (crash mid-append: truncated frame at EOF) recovers
  to the last complete record — the incomplete bytes are truncated
  away and counted (``restore.torn_records``);
* **mid-file corruption** (a bad CRC with more data after it) raises
  the typed :class:`CheckpointCorrupt` and the session is
  **quarantined** (journal renamed ``.quarantined``) — the daemon
  keeps serving every other session and never serves partial tables;
* a journal whose last record is ``bye`` is a cleanly-closed session:
  its files are erased at scan time.

The disabled path (``SPARK_RAPIDS_TPU_DURABLE=off``, the default)
costs one cached generation compare per mutation, the
metrics/faults/spill gate discipline.
"""

from __future__ import annotations

import contextlib
import json
import os
import struct
import tempfile
import uuid
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils import config, faults, flight, lockcheck, log, metrics, spill

_MAGIC = b"SRTJ1\n"
_FRAME = struct.Struct("<II")
DEDUP_CAP = 512  # idempotency window per session (request ids)


# ---------------------------------------------------------------------------
# typed errors (wired into server._ERROR_TYPES / client._ERROR_CLASSES)
# ---------------------------------------------------------------------------


class CheckpointCorrupt(faults.PermanentError):
    """A journal or payload whose integrity check failed mid-file: the
    session's durable state cannot be trusted, so it is quarantined —
    corrupt data is never served, partially or otherwise."""


class ResumeDenied(Exception):
    """A hello named an existing durable session but carried a missing
    or wrong resume token — another client's session is not yours."""


class SessionQuarantined(Exception):
    """The session's durable state was quarantined during restore; its
    tables are unrecoverable and a fresh session must be opened."""


class Draining(Exception):
    """The daemon is draining for a rolling restart: no new sessions or
    device work; in-flight work finishes, then the daemon exits."""


# ---------------------------------------------------------------------------
# flag gate + directory
# ---------------------------------------------------------------------------

_GATE = (None, False)


def enabled() -> bool:
    global _GATE
    gen = config.generation()
    if _GATE[0] != gen:
        _GATE = (gen, bool(config.get_flag("DURABLE")))
    return _GATE[1]


def checkpoint_dir() -> str:
    """Directory for journals, payloads, and the warm-start manifest;
    created lazily. Unlike the spill scratch dir the default is STABLE
    across processes (no pid) — a checkpoint only earns its fsyncs by
    outliving the process that wrote it."""
    d = str(config.get_flag("CHECKPOINT_DIR") or "").strip()
    if not d:
        d = os.path.join(tempfile.gettempdir(), "srt-checkpoint")
    os.makedirs(d, exist_ok=True)
    return d


def new_resume_token() -> str:
    return uuid.uuid4().hex


# ---------------------------------------------------------------------------
# counters: metrics (checkpoint.* / restore.*) + an always-on mirror so
# server.stats() has a durability block even with METRICS off
# ---------------------------------------------------------------------------

_STATS_LOCK = lockcheck.make_lock("durable.stats")
_STATS: Dict[str, int] = {}


def count(name: str, n: int = 1, as_bytes: bool = False) -> None:
    with _STATS_LOCK:
        _STATS[name] = _STATS.get(name, 0) + int(n)
    if as_bytes:
        metrics.bytes_add(name, n)
    else:
        metrics.counter_add(name, n)


def stats_doc() -> dict:
    with _STATS_LOCK:
        doc = dict(sorted(_STATS.items()))
    doc["enabled"] = enabled()
    return doc


def reset() -> None:
    """Test hook: zero the counter mirror (files are the caller's)."""
    with _STATS_LOCK:
        _STATS.clear()


# ---------------------------------------------------------------------------
# journal: CRC-framed, fsync'd, append-only
# ---------------------------------------------------------------------------


class Journal:
    """One append-only record log. Thread-safe appends; each append is
    flushed and fsync'd before returning — a mutation acknowledged to
    the client is on disk. The ``checkpoint`` fault site emulates a
    torn write here: half the frame is persisted, then the typed fault
    raises. A later append self-heals by truncating back to the last
    good offset first (the recover-the-tail discipline of real WALs)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = lockcheck.make_lock("durable.journal")
        self._f = open(path, "ab")
        size = os.fstat(self._f.fileno()).st_size
        if size == 0:
            self._f.write(_MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
            size = len(_MAGIC)
        self._good = size

    def append(self, record: dict) -> None:
        payload = json.dumps(record, sort_keys=True).encode()
        frame = _FRAME.pack(
            len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        ) + payload
        with self._lock:
            if self._f.closed:
                raise CheckpointCorrupt(
                    f"{self.path}: journal is closed"
                )
            size = os.fstat(self._f.fileno()).st_size
            if size != self._good:
                # a previous append tore (injected fault): recover the
                # tail before writing, keeping the journal parseable
                self._f.truncate(self._good)
            try:
                faults.inject("checkpoint")
            except faults.FaultError:
                self._f.write(frame[: max(len(frame) // 2, 1)])
                self._f.flush()
                with contextlib.suppress(OSError):
                    os.fsync(self._f.fileno())
                raise
            self._f.write(frame)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._good = os.fstat(self._f.fileno()).st_size

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def read_journal(path: str) -> Tuple[List[dict], int, int]:
    """Parse a journal. Returns ``(records, torn, good_off)`` where
    ``torn`` counts incomplete trailing records (0 or 1) and
    ``good_off`` is the byte offset of the last complete record's end.
    Raises :class:`CheckpointCorrupt` for a bad magic or a CRC/decode
    failure that is NOT the file tail — torn tails recover, corruption
    quarantines."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        raise CheckpointCorrupt(f"{path}: bad journal magic")
    off = len(_MAGIC)
    n = len(blob)
    records: List[dict] = []
    torn = 0
    while off < n:
        if off + _FRAME.size > n:
            torn = 1  # header truncated mid-append
            break
        length, crc = _FRAME.unpack_from(blob, off)
        end = off + _FRAME.size + length
        if end > n:
            torn = 1  # payload truncated mid-append
            break
        payload = blob[off + _FRAME.size:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            if end == n:
                torn = 1  # full-length tail frame with torn payload
                break
            raise CheckpointCorrupt(
                f"{path}: CRC mismatch at offset {off} with "
                f"{n - end} byte(s) after it — mid-journal corruption"
            )
        try:
            records.append(json.loads(payload.decode()))
        except ValueError:
            if end == n:
                torn = 1
                break
            raise CheckpointCorrupt(
                f"{path}: undecodable record at offset {off}"
            )
        off = end
    return records, torn, off


# ---------------------------------------------------------------------------
# per-session WAL + payload files
# ---------------------------------------------------------------------------


def _payload_name(sid: str, local: int) -> str:
    return f"{sid}-t{int(local)}.npz"


class SessionLog:
    """One session's durable state: ``<sid>.wal`` plus one ``.npz``
    payload per live table. Local ids are never reused within a
    session, so payload filenames never collide."""

    def __init__(self, sid: str, dirpath: Optional[str] = None):
        self.sid = sid
        self.dir = dirpath or checkpoint_dir()
        self.path = os.path.join(self.dir, f"{sid}.wal")
        self._journal = Journal(self.path)

    def _payload_path(self, local: int) -> str:
        return os.path.join(self.dir, _payload_name(self.sid, local))

    def _unlink_payload(self, local: int) -> None:
        with contextlib.suppress(OSError):
            os.unlink(self._payload_path(local))

    def log_open(self, name: str, weight: float, budget: int,
                 token: str) -> None:
        self._journal.append({
            "t": "open", "name": name, "weight": float(weight),
            "budget": int(budget), "token": token,
        })
        count("checkpoint.records")

    def log_put(self, local: int, table, nbytes: int,
                drop: Optional[int] = None, req: Optional[str] = None,
                resp: Optional[dict] = None) -> None:
        """Checkpoint one namespace put: payload first (atomic), then
        the journal record naming it — a record never points at a
        missing or partial payload. ``drop`` is the local id of a
        donated (consumed) plan input, removed in the same record."""
        path = self._payload_path(local)
        with metrics.span("checkpoint.put"):
            faults.inject("checkpoint")
            disk_bytes = spill.save_table_npz(path, table)
            rec = {
                "t": "put", "local": int(local), "bytes": int(nbytes),
                "file": _payload_name(self.sid, local),
            }
            if drop is not None:
                rec["drop"] = int(drop)
            if req:
                rec["req"] = str(req)
                rec["resp"] = dict(resp or {})
            self._journal.append(rec)
        count("checkpoint.records")
        count("checkpoint.tables")
        count("checkpoint.bytes", disk_bytes, as_bytes=True)
        if drop is not None:
            self._unlink_payload(drop)
        if flight.enabled():
            flight.record("I", "checkpoint.put", f"{self.sid}:{local}")

    def log_free(self, local: int, nbytes: int,
                 req: Optional[str] = None,
                 resp: Optional[dict] = None) -> None:
        rec = {"t": "free", "local": int(local), "bytes": int(nbytes)}
        if req:
            rec["req"] = str(req)
            rec["resp"] = dict(resp or {})
        self._journal.append(rec)
        count("checkpoint.records")
        self._unlink_payload(local)

    def log_bye(self) -> None:
        """Clean close: journal the bye, then erase — a byed session
        has nothing to restore."""
        with contextlib.suppress(faults.FaultError, OSError):
            self._journal.append({"t": "bye"})
            count("checkpoint.records")
        self.erase()

    def erase(self) -> None:
        self._journal.close()
        erase_session_files(self.sid, self.dir)

    def close(self) -> None:
        self._journal.close()


def erase_session_files(sid: str, dirpath: Optional[str] = None) -> None:
    d = dirpath or checkpoint_dir()
    prefix = f"{sid}-t"
    for fn in os.listdir(d):
        if fn == f"{sid}.wal" or (
            fn.startswith(prefix) and fn.endswith(".npz")
        ):
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(d, fn))


def quarantine(sid: str, reason: str,
               dirpath: Optional[str] = None) -> None:
    """Set a session's durable state aside: its journal is renamed
    ``.quarantined`` (kept for forensics, never replayed) and the
    daemon keeps serving everything else."""
    d = dirpath or checkpoint_dir()
    src = os.path.join(d, f"{sid}.wal")
    with contextlib.suppress(OSError):
        os.replace(src, src + ".quarantined")
    count("restore.quarantined")
    log.log("ERROR", "serving", "quarantine", session=sid,
            reason=reason)
    if flight.enabled():
        flight.record("I", "restore.quarantine", sid)


# ---------------------------------------------------------------------------
# restore: journal replay -> recovered session state
# ---------------------------------------------------------------------------


class RestoredSession:
    """Final replayed state of one session's journal."""

    __slots__ = ("sid", "name", "weight", "budget", "token", "tables",
                 "dedup", "next_local", "records")

    def __init__(self, sid: str):
        self.sid = sid
        self.name = sid
        self.weight = 1.0
        self.budget = 0
        self.token: Optional[str] = None
        self.tables: Dict[int, Tuple[str, int]] = {}  # local->(file, B)
        self.dedup: Dict[str, dict] = {}
        self.next_local = 1
        self.records = 0


def _replay(sid: str, records: List[dict]) -> Optional[RestoredSession]:
    """Apply journal records in order; ``None`` means cleanly closed
    (``bye`` seen) — nothing to restore."""
    rs = RestoredSession(sid)
    for rec in records:
        rs.records += 1
        t = rec.get("t")
        if t == "open":
            rs.name = str(rec.get("name") or sid)
            rs.weight = float(rec.get("weight", 1.0))
            rs.budget = int(rec.get("budget", 0))
            rs.token = rec.get("token")
        elif t == "put":
            local = int(rec["local"])
            rs.tables[local] = (str(rec["file"]), int(rec["bytes"]))
            rs.next_local = max(rs.next_local, local + 1)
            if rec.get("drop") is not None:
                rs.tables.pop(int(rec["drop"]), None)
            if rec.get("req"):
                rs.dedup[str(rec["req"])] = dict(rec.get("resp") or {})
        elif t == "free":
            rs.tables.pop(int(rec["local"]), None)
            if rec.get("req"):
                rs.dedup[str(rec["req"])] = dict(rec.get("resp") or {})
        elif t == "bye":
            return None
        else:
            raise CheckpointCorrupt(
                f"{sid}.wal: unknown record type {t!r}"
            )
    return rs


def restore_scan(
    dirpath: Optional[str] = None,
) -> Tuple[List[RestoredSession], Dict[str, str]]:
    """Scan the checkpoint dir, replay every session journal. Returns
    ``(restorable sessions, {sid: quarantine reason})``. Torn tails
    are truncated in place (so the reopened journal appends after the
    last complete record); corrupt journals are quarantined, never
    fatal — the daemon must come up with whatever state is sound."""
    d = dirpath or checkpoint_dir()
    sessions: List[RestoredSession] = []
    quarantined: Dict[str, str] = {}
    for fn in sorted(os.listdir(d)):
        if not fn.endswith(".wal") or fn == "manifest.wal":
            continue
        sid = fn[:-len(".wal")]
        path = os.path.join(d, fn)
        try:
            records, torn, good_off = read_journal(path)
            if torn:
                count("restore.torn_records", torn)
                log.log("WARN", "serving", "torn_tail", session=sid,
                        recovered_records=len(records))
                os.truncate(path, good_off)
            rs = _replay(sid, records)
        except (CheckpointCorrupt, OSError) as e:
            quarantined[sid] = str(e)
            quarantine(sid, str(e), d)
            continue
        if rs is None:
            erase_session_files(sid, d)  # clean bye: leftovers only
            continue
        count("restore.records_replayed", rs.records)
        sessions.append(rs)
    return sessions, quarantined


def load_payload(path: str):
    """Restore-time payload read (device Table), under the checkpoint
    fault site — an injected or real read failure surfaces typed and
    quarantines the session, it never serves a partial table."""
    faults.inject("checkpoint")
    try:
        return spill.load_table_npz(path)
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable payload: {e}")


# ---------------------------------------------------------------------------
# warm-start manifest: the compile keys served before the crash
# ---------------------------------------------------------------------------


def _table_record(table) -> dict:
    """Everything needed to synthesize a table with the same compile
    signature: per-column storage dtype/shape (table_signature alone
    does not pin the storage dtype) plus rows and logical rows."""
    cols = []
    rows = 0
    for c in table.columns:
        shape = c.data.shape
        rows = int(shape[0])
        cols.append([
            int(c.dtype.id), int(c.dtype.scale), str(c.data.dtype),
            int(shape[1]) if len(shape) > 1 else 0,
            None if c.validity is None else str(c.validity.dtype),
            None if c.lengths is None else str(c.lengths.dtype),
        ])
    return {
        "cols": cols,
        "names": None if table.names is None else list(table.names),
        "rows": rows,
        "logical": (
            None if table.logical_rows is None
            else int(table.logical_rows)
        ),
    }


def _synth_table(trec: dict):
    """Zero-filled device table matching a manifest record's compile
    signature — one batched device_put, the spill upload discipline."""
    import jax
    import numpy as np

    from .. import dtype as dt
    from ..column import Column, Table

    rows = int(trec["rows"])
    leaves = []
    specs = []
    for ti, sc, dstr, width, vstr, lstr in trec["cols"]:
        shape = (rows, width) if width else (rows,)
        leaves.append(np.zeros(shape, dtype=np.dtype(dstr)))
        if vstr is not None:
            leaves.append(np.ones(rows, dtype=np.dtype(vstr)))
        if lstr is not None:
            leaves.append(np.zeros(rows, dtype=np.dtype(lstr)))
        specs.append((ti, sc, vstr is not None, lstr is not None))
    dev = jax.device_put(leaves) if leaves else []
    it = iter(dev)
    cols = []
    for ti, sc, has_v, has_l in specs:
        d = next(it)
        v = next(it) if has_v else None
        lens = next(it) if has_l else None
        cols.append(Column(d, dt.DType(dt.TypeId(ti), sc), v, lens))
    return Table(cols, trec["names"], trec["logical"])


class Manifest:
    """Journal of unique ``(plan, schema signature, bucket, donation)``
    combinations served while durable. ``warm_start`` replays them
    against zero-filled tables of the recorded signatures — compile
    cache keys depend only on the plan JSON, the table signature, the
    (padded) row count and donation, never the data, so the replay
    reproduces every executable the first life built."""

    def __init__(self, dirpath: Optional[str] = None):
        self.dir = dirpath or checkpoint_dir()
        self.path = os.path.join(self.dir, "manifest.wal")
        self._lock = lockcheck.make_lock("durable.manifest")
        self._seen: set = set()
        self._records: List[dict] = []
        if os.path.exists(self.path):
            try:
                records, torn, good_off = read_journal(self.path)
                if torn:
                    os.truncate(self.path, good_off)
            except (CheckpointCorrupt, OSError) as e:
                # a corrupt manifest only costs warm compiles — set it
                # aside and start fresh, never block the restore
                log.log("ERROR", "serving", "manifest_corrupt",
                        reason=str(e))
                with contextlib.suppress(OSError):
                    os.replace(self.path, self.path + ".quarantined")
                records = []
            for rec in records:
                key = json.dumps(rec, sort_keys=True)
                if key not in self._seen:
                    self._seen.add(key)
                    self._records.append(rec)
        self._journal = Journal(self.path)

    def note(self, ops: list, tables, donate: bool) -> None:
        """Record one served plan invocation (deduped). Failures only
        cost a future warm start — never the serving request."""
        try:
            rec = {
                "t": "plan", "ops": list(ops), "donate": bool(donate),
                "tables": [_table_record(t) for t in tables],
            }
            key = json.dumps(rec, sort_keys=True)
            with self._lock:
                if key in self._seen:
                    return
                self._seen.add(key)
                self._records.append(rec)
            try:
                self._journal.append(rec)
            except (faults.FaultError, OSError):
                count("checkpoint.errors")
                with self._lock:
                    self._seen.discard(key)  # retry on a later serve
                    with contextlib.suppress(ValueError):
                        self._records.remove(rec)
                return
            count("checkpoint.manifest_plans")
        # srt: allow-broad-except(the manifest is a warm-start optimization; a signature it cannot record must never fail the live request)
        except Exception:
            count("checkpoint.errors")

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def warm_start(self) -> Tuple[int, int]:
        """Pre-compile every recorded plan (zero-filled inputs, real
        ``run_plan``) — called before the listener opens. Returns
        ``(compiled, failed)``; a record that cannot replay is counted
        and skipped, never fatal."""
        from .. import plan as plan_mod

        compiled = failed = 0
        with metrics.span("restore.warm_start"):
            for rec in self.records():
                try:
                    tabs = [_synth_table(t) for t in rec["tables"]]
                    plan_mod.run_plan(
                        rec["ops"], tabs[0], tabs[1:],
                        donate_input=bool(rec.get("donate")),
                    )
                    compiled += 1
                # srt: allow-broad-except(warm start is best-effort: one unreplayable plan must not block the listener from opening)
                except Exception as e:
                    failed += 1
                    log.log("WARN", "serving", "warm_start_failed",
                            reason=str(e))
        count("restore.warm_compiles", compiled)
        if failed:
            count("restore.warm_failures", failed)
        if flight.enabled():
            flight.record("I", "restore.warm_start", compiled)
        return compiled, failed

    def close(self) -> None:
        self._journal.close()


flight.register_exit_section("durable", stats_doc)
