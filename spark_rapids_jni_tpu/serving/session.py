"""Per-client sessions: table namespace, HBM budget, teardown.

A session is the serving daemon's tenant unit — the analog of one Spark
task attached to the resident executor process. It owns:

* a **table namespace**: session-local table ids mapping to the global
  resident registry (``runtime_bridge``). Ids are scoped per session;
  a cross-session access raises a labeled KeyError naming the session,
  never another tenant's table.
* an **HBM budget**: a fraction of ``hbm.budget_bytes()``
  (``SPARK_RAPIDS_TPU_SERVE_SESSION_HBM_FRACTION``). Admission charges
  each request's estimate against the remainder; a request that can
  never fit is rejected with a typed OverBudget naming the budget, one
  that is only blocked by in-flight work queues until the in-flight
  charge drains. Donation credits flow back: when a tenant's plan
  donates its buffers (``hbm.note_donation``), the donated bytes are
  credited against that request's in-flight charge.
* **teardown with full reclamation**: on disconnect or crash every
  table the session still holds is reclaimed through
  ``runtime_bridge.table_reclaim`` — the donate-barrier-settling free,
  so an in-flight pipelined reader can never be left dereferencing
  deleted buffers.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from .. import runtime_bridge as rb
from ..utils import buckets, faults, hbm, lockcheck, metrics, spill, tracing

# Global reverse map rb_id -> (owning session, charged bytes): the spill
# tier's residency events carry rb ids, and the owning session credits /
# re-charges its budget from them (listener below). Guarded by its own
# lock — never taken while a Session lock is held, only inside the
# deferred-event flush (spill.flush_events) and the table bookkeeping
# paths, so there is no ordering against Session._cv to get wrong.
_OWNERS_LOCK = lockcheck.make_lock("session.owners")
_RB_OWNERS: Dict[int, Tuple["Session", int]] = {}


class OverBudget(Exception):
    """Typed admission rejection: the request's HBM estimate exceeds
    the session's budget. The message names the session and its budget
    so the client can size down or negotiate a bigger fraction."""


class SessionClosed(Exception):
    """The session was torn down while this request was queued or
    waiting for budget headroom."""


def estimate_request_bytes(batch) -> int:
    """Conservative HBM estimate for serving one wire batch: the wire
    buffer bytes, scaled up to the shape bucket the decode will pad to,
    doubled for input + output resident simultaneously (a donating plan
    never holds both — the donation credit gives the difference back)."""
    type_ids, scales, datas, valids, num_rows = batch
    wire = sum(len(d) for d in datas if d is not None)
    wire += sum(len(v) for v in valids if v is not None)
    n = max(int(num_rows), 1)
    pad = buckets.bucket_for(n) if buckets.enabled() else None
    if pad:
        wire = int(wire * (pad / n))
    return max(2 * wire, 1)


class Session:
    """One tenant: namespace + budget + stats. Thread-safe."""

    def __init__(self, session_id: str, name: str, weight: float,
                 budget_bytes: int):
        self.id = session_id
        self.name = name
        self.weight = max(float(weight), 1e-3)
        self.budget_bytes = int(budget_bytes)
        # session-default request deadline (seconds) from the hello
        # frame; per-command headers override, 0 means none
        self.deadline_s = 0.0
        # mesh-backed execution: hello ``mesh`` header device count; 0
        # (default) = single-device. Streams offer their plans to the
        # server's MeshRunner for that count; the degradation ladder
        # falls back to the single-device exact path rather than
        # shedding this tenant
        self.mesh_devices = 0
        self.created = time.time()
        self.connections = 0
        self.closed = False
        # durable serving (serving/durable.py): the reconnect secret
        # handed out at open (None when durability is off) and the
        # idempotency window mapping request ids of applied mutations
        # to their recorded responses
        self.resume_token: Optional[str] = None
        self._dedup: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = lockcheck.make_lock("session.state")
        self._cv = lockcheck.make_condition(self._lock)
        self._tables: Dict[int, Tuple[int, int]] = {}  # local -> (rb, B)
        self._next_local = itertools.count(1)
        self._resident_bytes = 0
        self._inflight_bytes = 0
        self._spilled_bytes = 0         # charged bytes currently off-device
        self._spilled_rb: set = set()   # rb ids of ours that are spilled
        self._waits = deque(maxlen=4096)  # queue-wait seconds
        self._lats = deque(maxlen=4096)   # submit->done latency seconds
        self.stats = {
            "requests": 0,
            "shed": 0,
            "over_budget": 0,
            "donated_credit_bytes": 0,
            "bytes_in": 0,
            "bytes_out": 0,
        }

    # -- HBM budget -------------------------------------------------------
    def admit(self, estimate: int, wait: bool = True) -> int:
        """Charge ``estimate`` bytes against the budget, queueing behind
        in-flight work when that is what blocks it. Raises the typed
        :class:`OverBudget` when the estimate can never fit (it exceeds
        the budget minus the session's resident tables), and
        :class:`SessionClosed` if torn down while waiting. The whole
        wait — spill rounds included — shows up in the request's trace
        as a ``serving.admission`` span."""
        tok = tracing.span_begin("serving.admission")
        try:
            got = self._admit(estimate, wait)
        except BaseException as e:
            tracing.span_end(tok, error=type(e).__name__)
            raise
        tracing.span_end(tok)
        return got

    def _admit(self, estimate: int, wait: bool) -> int:
        est = max(int(estimate), 0)
        faults.inject("hbm_admit")
        while True:
            with self._cv:
                if self.closed:
                    raise SessionClosed(
                        f"session {self.name} closed while admitting"
                    )
                hard_remaining = self.budget_bytes - self._resident_bytes
                free = hard_remaining - self._inflight_bytes
                if est <= free:
                    self._inflight_bytes += est
                    return est
                deficit = est - max(
                    hard_remaining if est > hard_remaining else free, 0
                )
            # Blocked: before shedding or queueing, ask the spill tier
            # to demote the coldest resident tables (any session's —
            # global LRU) OUTSIDE the session lock. A freed victim of
            # OURS credits _resident_bytes via the residency listener;
            # re-evaluate either way. Terminates: each round either
            # evicts something (the evictable set strictly shrinks) or
            # frees nothing and falls through to the shed/queue verdict.
            if spill.request_headroom(deficit, reason="admit"):
                metrics.counter_add("serving.admit_spills")
                continue
            with self._cv:
                if self.closed:
                    raise SessionClosed(
                        f"session {self.name} closed while admitting"
                    )
                hard_remaining = self.budget_bytes - self._resident_bytes
                if est <= hard_remaining - self._inflight_bytes:
                    self._inflight_bytes += est
                    return est
                if est > hard_remaining:
                    self.stats["over_budget"] += 1
                    metrics.counter_add("serving.over_budget")
                    raise OverBudget(
                        f"session {self.name}: request estimate {est} B "
                        f"exceeds remaining HBM budget {hard_remaining} B "
                        f"(session budget {self.budget_bytes} B, "
                        f"resident {self._resident_bytes} B)"
                    )
                if not wait:
                    self.stats["over_budget"] += 1
                    metrics.counter_add("serving.over_budget")
                    raise OverBudget(
                        f"session {self.name}: request estimate {est} B "
                        f"exceeds free HBM budget "
                        f"{hard_remaining - self._inflight_bytes} B "
                        f"({self._inflight_bytes} B in flight, session "
                        f"budget {self.budget_bytes} B)"
                    )
                # blocked only by in-flight work: queue until it drains
                self._cv.wait()

    def release(self, charge: int) -> None:
        """Return an admitted in-flight charge (request completed)."""
        with self._cv:
            self._inflight_bytes = max(
                self._inflight_bytes - max(int(charge), 0), 0
            )
            self._cv.notify_all()

    def note_donation(self, nbytes: int, ticket=None) -> int:
        """Credit donated bytes back against the in-flight charge (and
        the ticket's remaining charge, so its completion-time release
        doesn't double-credit). Returns the bytes actually credited."""
        n = max(int(nbytes), 0)
        with self._cv:
            if ticket is not None:
                n = min(n, max(getattr(ticket, "charge", 0), 0))
                ticket.charge -= n
            credited = min(n, self._inflight_bytes)
            self._inflight_bytes -= credited
            self.stats["donated_credit_bytes"] += credited
            if credited:
                self._cv.notify_all()
        return credited

    # -- table namespace --------------------------------------------------
    def _unknown_local_error(self, local_id) -> KeyError:
        with self._lock:
            live = len(self._tables)
        return KeyError(
            f"table id {int(local_id)} not found in session {self.name} "
            f"({live} table(s) live in this session; resident table ids "
            "are session-scoped)"
        )

    def put_table(self, rb_id: int, nbytes: int) -> int:
        """Register a resident table under this session; returns its
        session-local id and charges its bytes as resident."""
        with self._cv:
            local = next(self._next_local)
            self._tables[local] = (int(rb_id), int(nbytes))
            self._resident_bytes += int(nbytes)
        with _OWNERS_LOCK:
            _RB_OWNERS[int(rb_id)] = (self, int(nbytes))
        return local

    def _note_residency(self, event: str, rb_id: int,
                        charged: int) -> None:
        """Spill credit (residency listener): a table of ours that left
        the device tier stops counting against the session HBM budget —
        that is WHY admission spills instead of shedding — and
        re-charges when a repage brings it back."""
        with self._cv:
            if event == "out":
                if rb_id in self._spilled_rb:
                    return
                self._spilled_rb.add(rb_id)
                self._spilled_bytes += charged
                self._resident_bytes = max(
                    self._resident_bytes - charged, 0
                )
                self._cv.notify_all()
            else:
                if rb_id not in self._spilled_rb:
                    return
                self._spilled_rb.discard(rb_id)
                self._spilled_bytes = max(
                    self._spilled_bytes - charged, 0
                )
                self._resident_bytes += charged

    def _forget_owner(self, ent) -> None:
        """Drop the reverse-owner entry for a (rb_id, bytes) table
        entry leaving this session (no further residency credits)."""
        with _OWNERS_LOCK:
            _RB_OWNERS.pop(ent[0], None)

    def rb_id(self, local_id: int) -> int:
        """Global resident id for a session-local id; labeled KeyError
        on a miss (including every cross-session access)."""
        with self._lock:
            ent = self._tables.get(int(local_id))
        if ent is None:
            raise self._unknown_local_error(local_id)
        return ent[0]

    def _uncharge_locked(self, ent) -> None:
        """Remove a departing table's budget charge — from the spill
        credit when it is currently off-device, from resident otherwise."""
        if ent[0] in self._spilled_rb:
            self._spilled_rb.discard(ent[0])
            self._spilled_bytes = max(self._spilled_bytes - ent[1], 0)
        else:
            self._resident_bytes = max(self._resident_bytes - ent[1], 0)

    def drop_local(self, local_id: int) -> None:
        """Forget a local id whose global table was CONSUMED (donated
        into a plan) — no reclaim, the bytes moved into the result."""
        with self._cv:
            ent = self._tables.pop(int(local_id), None)
            if ent is not None:
                self._uncharge_locked(ent)
                self._cv.notify_all()
        if ent is not None:
            self._forget_owner(ent)

    def free_table(self, local_id: int) -> int:
        """Reclaim one table's HBM now (donate-barrier-settling free);
        returns bytes reclaimed. Labeled KeyError on a miss."""
        with self._cv:
            ent = self._tables.pop(int(local_id), None)
            if ent is not None:
                self._uncharge_locked(ent)
                self._cv.notify_all()
        if ent is None:
            raise self._unknown_local_error(local_id)
        self._forget_owner(ent)
        try:
            return rb.table_reclaim(ent[0])
        except KeyError:
            return 0  # already consumed by a donating plan

    def table_count(self) -> int:
        with self._lock:
            return len(self._tables)

    # -- durability (serving/durable.py) ----------------------------------
    def dedup_get(self, req) -> Optional[dict]:
        """Recorded response for an already-applied request id, or
        None — the at-most-once check for reconnecting clients."""
        with self._lock:
            hit = self._dedup.get(str(req))
            return None if hit is None else dict(hit)

    def dedup_put(self, req, resp: dict, cap: int = 512) -> None:
        with self._lock:
            self._dedup[str(req)] = dict(resp)
            while len(self._dedup) > cap:
                self._dedup.popitem(last=False)

    def restore_table(self, local: int, rb_id: int,
                      nbytes: int) -> None:
        """Re-register a journal-recovered table under its ORIGINAL
        session-local id, re-charging its bytes as resident (the HBM
        accounting the journal's budget record expects)."""
        local = int(local)
        with self._cv:
            self._tables[local] = (int(rb_id), int(nbytes))
            self._resident_bytes += int(nbytes)
        with _OWNERS_LOCK:
            _RB_OWNERS[int(rb_id)] = (self, int(nbytes))

    def advance_locals(self, next_local: int) -> None:
        """Continue local-id allocation past the journal's high-water
        mark — restored ids and fresh ones must never collide."""
        with self._lock:
            self._next_local = itertools.count(max(int(next_local), 1))

    # -- stats ------------------------------------------------------------
    def note_wait(self, seconds: float) -> None:
        with self._lock:
            self._waits.append(float(seconds))
            self.stats["requests"] += 1

    def note_shed(self) -> None:
        with self._lock:
            self.stats["shed"] += 1

    def note_latency(self, seconds: float) -> None:
        """End-to-end submit->done latency of one scheduled request —
        queue wait PLUS execution, the number the tenant experiences."""
        with self._lock:
            self._lats.append(float(seconds))

    def _percentiles(self, samples) -> dict:
        with self._lock:
            vals = sorted(samples)
        if not vals:
            return {"p50_ms": 0.0, "p95_ms": 0.0, "max_ms": 0.0}

        def pct(p):
            i = min(int(p * (len(vals) - 1) + 0.5), len(vals) - 1)
            return round(vals[i] * 1e3, 3)

        return {
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "max_ms": round(vals[-1] * 1e3, 3),
        }

    def wait_percentiles(self) -> dict:
        return self._percentiles(self._waits)

    def latency_percentiles(self) -> dict:
        return self._percentiles(self._lats)

    def to_doc(self) -> dict:
        with self._cv:
            doc = {
                "session": self.id,
                "name": self.name,
                "weight": self.weight,
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._resident_bytes,
                "inflight_bytes": self._inflight_bytes,
                "spilled_bytes": self._spilled_bytes,
                "spilled_tables": len(self._spilled_rb),
                "tables": len(self._tables),
                "connections": self.connections,
                "mesh_devices": self.mesh_devices,
                **dict(self.stats),
            }
        doc["queue_wait"] = self.wait_percentiles()
        doc["latency"] = self.latency_percentiles()
        return doc

    # -- teardown ---------------------------------------------------------
    def teardown(self) -> int:
        """Reclaim every table this session still holds (disconnect or
        crash path). Safe against in-flight pipelined readers: each
        reclaim settles them via the donation-barrier path before any
        buffer is deleted. Returns total bytes reclaimed."""
        with self._cv:
            self.closed = True
            tables = list(self._tables.values())
            self._tables.clear()
            self._resident_bytes = 0
            self._spilled_bytes = 0
            self._spilled_rb.clear()
            self._cv.notify_all()
        with _OWNERS_LOCK:
            for rb_id, _ in tables:
                _RB_OWNERS.pop(rb_id, None)
        reclaimed = 0
        for rb_id, _ in tables:
            try:
                reclaimed += rb.table_reclaim(rb_id)
            except KeyError:
                pass  # consumed by a donating plan before teardown
        return reclaimed


# ---------------------------------------------------------------------------
# execution-scope binding: which (session, ticket) the calling thread is
# serving — the donation listener credits budgets through this.
# ---------------------------------------------------------------------------

_TLS = threading.local()


class executing:
    """Scope marking the calling thread as executing ``ticket`` for
    ``session`` (scheduler executor threads)."""

    __slots__ = ("_prev", "_cur")

    def __init__(self, session: Optional[Session], ticket=None):
        self._cur = (session, ticket) if session is not None else None

    def __enter__(self):
        self._prev = getattr(_TLS, "current", None)
        _TLS.current = self._cur
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _TLS.current = self._prev
        return False


def _donation_listener(nbytes: int) -> None:
    cur = getattr(_TLS, "current", None)
    if cur is not None:
        sess, ticket = cur
        sess.note_donation(nbytes, ticket)


hbm.register_donation_listener(_donation_listener)


def _residency_listener(event: str, rb_id: int, nbytes: int) -> None:
    """Spill residency events -> session budget credit. Fired from
    spill.flush_events with NO registry lock held (deferred queue), so
    taking the owning session's lock here cannot invert against the
    teardown path that holds a session lock while reclaiming."""
    with _OWNERS_LOCK:
        ent = _RB_OWNERS.get(int(rb_id))
    if ent is None:
        return  # not a serving-owned table (library embedder)
    sess, charged = ent
    sess._note_residency(event, int(rb_id), charged)


spill.register_residency_listener(_residency_listener)
