"""Length-prefixed frame codec for the serving daemon's wire protocol.

One frame is::

    u32_be total_len | u32_be header_len | header (UTF-8 JSON) | buffers

``total_len`` covers everything after itself. The header is a plain
JSON object carrying the command / response fields plus per-batch
buffer metadata; the raw column buffers follow concatenated, in batch
order, data-then-validity per column — exactly the byte strings of the
runtime bridge's wire 5-tuple ``(type_ids, scales, datas, valids,
num_rows)``, so the daemon reuses ``_table_from_wire`` /
``_table_to_wire`` with no re-encoding.

A batch is described in the header as::

    {"type_ids": [...], "scales": [...], "num_rows": n,
     "lens": [[data_len, valid_len_or_-1], ...]}

with ``-1`` meaning "no buffer follows" (a NULL-free column's validity,
or an empty data buffer encoded as length 0 vs. absent as -1).

Hello and command headers may carry ``deadline_s`` (float seconds):
on hello it sets the session's default request deadline, on a
``stream`` / ``plan`` command it bounds that one request — the server
turns it into a ``faults.CancelToken`` checked between plan segments
and stream batches, answering ``deadline_exceeded`` when it elapses.

Hello and command headers may also carry ``traceparent``: the
W3C-style trace-context header (``utils/tracing.py`` —
``00-<32-hex trace_id>-<16-hex span_id>-01``). The client stamps it
per request when the trace plane is on; the server joins the incoming
trace (fresh hop span id, same trace id) and activates it as the
ambient context for the request, so every span/instant either side
records into its flight ring carries the same trace id and
``tools/tracequery.py`` can merge the per-process dumps into one
request timeline. A malformed header is ignored, never an error.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional, Sequence, Tuple

# hard ceiling on one frame: a corrupt / hostile length prefix must
# fail loudly instead of allocating the universe
MAX_FRAME_BYTES = 1 << 30

_U32 = struct.Struct(">I")


class ProtocolError(RuntimeError):
    """Malformed frame: bad length prefix, truncated payload, or a
    header that is not a JSON object."""


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError(
                f"connection closed mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(sock, header: dict, buffers: Sequence[bytes] = ()) -> None:
    """Serialize and send one frame (single ``sendall`` for the prefix +
    header; buffers follow individually to avoid concatenating large
    payloads host-side)."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    total = 4 + len(hdr) + sum(len(b) for b in buffers)
    if total > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {total} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES})"
        )
    sock.sendall(_U32.pack(total) + _U32.pack(len(hdr)) + hdr)
    for b in buffers:
        if b:
            sock.sendall(b)


def recv_frame(sock) -> Tuple[dict, bytes]:
    """Receive one frame -> ``(header, payload)`` where ``payload`` is
    the concatenated buffer bytes after the header."""
    total = _U32.unpack(_recv_exact(sock, 4))[0]
    if total < 4 or total > MAX_FRAME_BYTES:
        raise ProtocolError(f"bad frame length {total}")
    body = _recv_exact(sock, total)
    hdr_len = _U32.unpack_from(body)[0]
    if hdr_len > total - 4:
        raise ProtocolError(
            f"header length {hdr_len} exceeds frame body {total - 4}"
        )
    try:
        header = json.loads(body[4:4 + hdr_len].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"undecodable frame header: {e}")
    if not isinstance(header, dict):
        raise ProtocolError(
            f"frame header must be a JSON object, got {type(header).__name__}"
        )
    return header, body[4 + hdr_len:]


# ---------------------------------------------------------------------------
# batch <-> (meta, buffers)
# ---------------------------------------------------------------------------


def batch_to_parts(batch) -> Tuple[dict, List[bytes]]:
    """Wire 5-tuple -> (header meta dict, ordered buffer list)."""
    type_ids, scales, datas, valids, num_rows = batch
    lens = []
    buffers: List[bytes] = []
    for d, v in zip(datas, valids):
        dl = -1 if d is None else len(d)
        vl = -1 if v is None else len(v)
        lens.append([dl, vl])
        if d is not None:
            buffers.append(bytes(d))
        if v is not None:
            buffers.append(bytes(v))
    return (
        {
            "type_ids": [int(t) for t in type_ids],
            "scales": [int(s) for s in scales],
            "num_rows": int(num_rows),
            "lens": lens,
        },
        buffers,
    )


def batch_from_parts(meta: dict, payload: bytes, offset: int):
    """(header meta, payload, offset) -> (wire 5-tuple, next offset)."""
    try:
        type_ids = meta["type_ids"]
        scales = meta["scales"]
        num_rows = int(meta["num_rows"])
        lens = meta["lens"]
    except (KeyError, TypeError, ValueError) as e:
        raise ProtocolError(f"malformed batch meta: {e}")
    if not (len(type_ids) == len(scales) == len(lens)):
        raise ProtocolError(
            f"batch meta arity mismatch: {len(type_ids)} type_ids, "
            f"{len(scales)} scales, {len(lens)} lens"
        )
    datas: List[Optional[bytes]] = []
    valids: List[Optional[bytes]] = []
    for dl, vl in lens:
        if dl < 0:
            datas.append(None)
        else:
            if offset + dl > len(payload):
                raise ProtocolError("truncated batch payload")
            datas.append(bytes(payload[offset:offset + dl]))
            offset += dl
        if vl < 0:
            valids.append(None)
        else:
            if offset + vl > len(payload):
                raise ProtocolError("truncated batch payload")
            valids.append(bytes(payload[offset:offset + vl]))
            offset += vl
    return (type_ids, scales, datas, valids, num_rows), offset


def batches_to_parts(batches) -> Tuple[List[dict], List[bytes]]:
    """Many wire 5-tuples -> (meta list, one ordered buffer list)."""
    metas: List[dict] = []
    buffers: List[bytes] = []
    for b in batches:
        m, bufs = batch_to_parts(b)
        metas.append(m)
        buffers.extend(bufs)
    return metas, buffers


def batches_from_parts(metas, payload: bytes) -> list:
    """(meta list, payload) -> list of wire 5-tuples."""
    out = []
    offset = 0
    for m in metas:
        b, offset = batch_from_parts(m, payload, offset)
        out.append(b)
    return out
