"""The serving daemon: a long-lived multi-tenant query-stream server.

This is the deployment shape the reference stack assumes — one resident
device process (the JVM executor that loads the shaded
``rapids-4-spark-jni`` artifact once) serving many concurrent Spark
tasks. Here the resident process is this :class:`Server`: it listens on
localhost TCP (length-prefixed JSON+binary frames, serving/frames.py),
gives each client connection a :class:`~.session.Session` (namespace +
HBM budget), runs every request through the weighted-deficit
:class:`~.scheduler.FairScheduler`, and executes through the existing
runtime bridge — so shape buckets, plan fusion, the pipelined dispatch
plane and buffer donation all apply per request, and the compiled-
executable cache (``buckets.cached_jit``) is naturally **shared across
sessions**: tenant B warm-hits tenant A's compiles because the cache is
process-global and keyed only by plan/schema/bucket/donation.

Commands (frame header ``cmd``):

* ``hello``      open (or re-attach to) a session; returns id + budget
* ``stream``     run a plan over N inline batches; returns N results
* ``upload``     wire batch -> session-resident table id
* ``plan``       plan over resident ids -> new resident id
* ``download``   resident id -> wire batch
* ``free``       reclaim one resident table's HBM now
* ``stats``      server + per-session statistics
* ``bye``        detach this connection (last detach tears the session
                 down with full table reclamation — as does a crash)

Errors are typed responses ``{"ok": false, "error": {"type", value
"message"}}``; notably ``busy`` (queue shed) and ``over_budget``
(admission) — a saturated daemon answers, it never hangs.

Every served stream opens a ``profiler.profile_session`` labeled
``serve:<session-name>``, so profile/flight dumps are session-stamped
and ``tools/explain.py --merge`` renders a multi-tenant timeline.
"""

from __future__ import annotations

import contextlib
import json
import select
import socket
import threading
import uuid
from collections import deque
from typing import Optional

from .. import pipeline, plan as plan_mod, plancheck, runtime_bridge as rb
from ..utils import config, faults, flight, hbm, lockcheck, metrics, profiler, spill
from . import frames
from .scheduler import Busy, FairScheduler
from .session import (
    OverBudget,
    Session,
    SessionClosed,
    estimate_request_bytes,
)


class SessionLimit(Exception):
    """Typed HELLO rejection: the daemon is at SERVE_MAX_SESSIONS."""


# ordered most-specific first: the fault taxonomy entries must win
# over any generic base class they might share
_ERROR_TYPES = {
    faults.Degraded: "degraded",
    faults.Cancelled: "cancelled",
    faults.DeadlineExceeded: "deadline_exceeded",
    faults.ResourceExhausted: "resource_exhausted",
    faults.TransientDeviceError: "transient_device",
    Busy: "busy",
    OverBudget: "over_budget",
    SessionLimit: "session_limit",
    SessionClosed: "session_closed",
    KeyError: "unknown_table",
    frames.ProtocolError: "bad_request",
    TypeError: "bad_request",
    ValueError: "bad_request",
}


def _error_type(exc: BaseException) -> str:
    for cls, name in _ERROR_TYPES.items():
        if isinstance(exc, cls):
            return name
    return "internal"


def _error_header(exc: BaseException) -> dict:
    msg = str(exc)
    if isinstance(exc, KeyError) and exc.args:
        msg = str(exc.args[0])  # un-repr the KeyError message
    err = {
        "type": _error_type(exc),
        "exception": type(exc).__name__,
        "message": msg,
    }
    # a plancheck rejection carries the full tagged report (per-op tier +
    # reason, GpuOverrides-style) — ship it so the client learns *why*
    # before paying upload or queue wait
    report = getattr(exc, "plan_report", None)
    if report is not None:
        err["plan_report"] = report
    return {"ok": False, "error": err}


class Server:
    """The resident daemon. ``with Server().start() as srv:`` or call
    :meth:`start` / :meth:`stop` explicitly; ``srv.port`` is the bound
    port (OS-assigned when SERVE_PORT / ``port`` is 0)."""

    def __init__(self, port: Optional[int] = None,
                 max_sessions: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 session_hbm_fraction: Optional[float] = None,
                 workers: int = 2):
        self._port_req = (
            int(config.get_flag("SERVE_PORT")) if port is None else port
        )
        self.max_sessions = (
            int(config.get_flag("SERVE_MAX_SESSIONS"))
            if max_sessions is None else int(max_sessions)
        )
        self.queue_depth = (
            int(config.get_flag("SERVE_QUEUE_DEPTH"))
            if queue_depth is None else int(queue_depth)
        )
        self.session_hbm_fraction = (
            float(config.get_flag("SERVE_SESSION_HBM_FRACTION"))
            if session_hbm_fraction is None
            else float(session_hbm_fraction)
        )
        self.scheduler = FairScheduler(
            workers=workers, queue_depth=self.queue_depth
        )
        # N consecutive transient failures flip the daemon to typed
        # Degraded sheds; a background probe closes it again without
        # waiting for client traffic (faults.CircuitBreaker)
        self.breaker = faults.CircuitBreaker(name="serving")
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = lockcheck.make_lock("session.server")
        self._sessions: dict = {}
        self._conns: set = set()
        self._conn_threads: list = []
        self._stopping = False
        self._sessions_served = 0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Server":
        self.scheduler.start()
        s = socket.create_server(("127.0.0.1", self._port_req))
        self.port = s.getsockname()[1]
        self._listener = s
        t = threading.Thread(
            target=self._accept_loop, name="srt-serve-accept", daemon=True
        )
        t.start()
        self._accept_thread = t
        p = threading.Thread(
            target=self._probe_loop, name="srt-serve-probe", daemon=True
        )
        p.start()
        self._probe_thread = p
        if flight.enabled():
            flight.record("I", "serving.start", self.port)
        return self

    def stop(self) -> None:
        """Shut down: stop accepting, close connections (tearing their
        sessions down with full reclamation), stop executors, drain the
        pipelined plane."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            conns = list(self._conns)
            threads = list(self._conn_threads)
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
        if self._listener is not None:
            # closing a listening socket does NOT wake a thread blocked
            # in accept() on Linux — poke it with a throwaway connection
            # (the accept loop sees _stopping and exits) so shutdown is
            # immediate instead of eating the join timeout
            with contextlib.suppress(OSError):
                socket.create_connection(
                    ("127.0.0.1", self.port), timeout=1
                ).close()
            with contextlib.suppress(OSError):
                self._listener.close()
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        for t in threads:
            t.join(timeout=10)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10)
        # belt-and-braces: a session left attached by a hung handler
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
        for sess in leftovers:
            self.scheduler.unregister(sess)
            sess.teardown()
        self.scheduler.stop()
        pipeline.drain()
        if flight.enabled():
            flight.record("I", "serving.stop", self.port)

    def __enter__(self) -> "Server":
        if self.port is None:
            self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    # -- accept / connection plumbing ------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            with self._lock:
                if self._stopping:
                    with contextlib.suppress(OSError):
                        sock.close()
                    return
                self._conns.add(sock)
                t = threading.Thread(
                    target=self._handle_conn, args=(sock,),
                    name="srt-serve-conn", daemon=True,
                )
                self._conn_threads.append(t)
            t.start()

    def _probe_loop(self) -> None:
        """Background half-open probing: while the breaker is OPEN,
        periodically run one trivial device op so the daemon recovers
        (closes the breaker) even with zero client traffic. Client
        requests race for the same half-open slot; whoever wins is the
        trial — the loser sheds typed Degraded as usual."""
        interval = max(self.breaker.probe_interval_s / 4, 0.05)
        while not self._probe_stop.wait(interval):
            if self.breaker.state == faults.CLOSED:
                continue
            try:
                if not self.breaker.allow():
                    continue  # closed between the check and the call
            except faults.Degraded:
                continue  # probe interval not yet elapsed
            try:
                faults.default_probe()
            except BaseException as e:
                self.breaker.note_failure(e)
            else:
                self.breaker.note_success()

    def _handle_conn(self, sock: socket.socket) -> None:
        sess: Optional[Session] = None
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                header, payload = frames.recv_frame(sock)
                cmd = header.get("cmd")
                if cmd == "hello":
                    sess = self._cmd_hello(sock, header, sess)
                    continue
                if cmd == "bye":
                    frames.send_frame(sock, {"ok": True})
                    break
                if sess is None:
                    frames.send_frame(sock, _error_header(
                        frames.ProtocolError(
                            f"first frame must be hello, got {cmd!r}"
                        )
                    ))
                    continue
                try:
                    self._dispatch(sock, sess, cmd, header, payload)
                except (BrokenPipeError, ConnectionError, OSError):
                    raise
                # srt: allow-broad-except(every failure becomes a typed error frame via _error_header; the client always gets an answer, never a hang)
                except BaseException as e:
                    frames.send_frame(sock, _error_header(e))
        except (ConnectionError, OSError, frames.ProtocolError):
            # disconnect / crash mid-stream: the finally below detaches
            # and (on last detach) tears the session down with full
            # table reclamation — the "crash leaks zero tables" path
            pass
        finally:
            with contextlib.suppress(OSError):
                sock.close()
            with self._lock:
                self._conns.discard(sock)
            if sess is not None:
                self._detach(sess)

    # -- session lifecycle ------------------------------------------------
    def _cmd_hello(self, sock, header, prev: Optional[Session]):
        try:
            sess = self._attach(header)
        except (SessionLimit, SessionClosed, ValueError, TypeError) as e:
            frames.send_frame(sock, _error_header(e))
            return prev
        if prev is not None and prev is not sess:
            self._detach(prev)
        frames.send_frame(sock, {
            "ok": True,
            "session": sess.id,
            "name": sess.name,
            "weight": sess.weight,
            "budget_bytes": sess.budget_bytes,
            "queue_depth": self.queue_depth,
        })
        return sess

    def _attach(self, header) -> Session:
        sid = header.get("session")
        weight = float(header.get("weight", 1.0) or 1.0)
        deadline_s = float(header.get("deadline_s") or 0.0)
        if deadline_s < 0:
            raise ValueError(
                f"hello: deadline_s must be >= 0, got {deadline_s}"
            )
        with self._lock:
            if sid is not None:
                sess = self._sessions.get(sid)
                if sess is None:
                    raise SessionClosed(
                        f"unknown or already-closed session {sid!r}"
                    )
                sess.connections += 1
                if deadline_s:
                    sess.deadline_s = deadline_s
                return sess
            if len(self._sessions) >= self.max_sessions:
                raise SessionLimit(
                    f"daemon at max sessions ({self.max_sessions}); "
                    "retry after a session closes"
                )
            new_id = uuid.uuid4().hex[:8]
            name = str(header.get("name") or f"sess-{new_id}")
            budget = max(
                int(self.session_hbm_fraction * hbm.budget_bytes()), 1
            )
            sess = Session(new_id, name, weight, budget)
            sess.deadline_s = deadline_s
            sess.connections = 1
            self._sessions[new_id] = sess
            self._sessions_served += 1
            live = len(self._sessions)
        self.scheduler.register(sess)
        metrics.counter_add("serving.sessions_opened")
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_open", sess.name)
        return sess

    def _detach(self, sess: Session) -> None:
        with self._lock:
            sess.connections -= 1
            last = sess.connections <= 0
            if last:
                self._sessions.pop(sess.id, None)
            live = len(self._sessions)
        if not last:
            return
        # order matters: unregister drains the session's queued AND
        # in-flight work first, so teardown reclaims tables no executor
        # still touches (and table_reclaim's barrier covers any
        # pipelined reader beyond that)
        self.scheduler.unregister(sess)
        reclaimed = sess.teardown()
        metrics.counter_add("serving.sessions_closed")
        metrics.bytes_add("serving.reclaimed_bytes", reclaimed)
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_close", sess.name)

    # -- request dispatch -------------------------------------------------
    _DEVICE_CMDS = frozenset({"stream", "upload", "plan", "download"})

    def _dispatch(self, sock, sess, cmd, header, payload) -> None:
        if cmd in self._DEVICE_CMDS:
            # breaker gate: an OPEN breaker sheds with typed Degraded
            # before any device work; a True return marks this request
            # as the half-open trial (the accounting below is the same
            # either way)
            self.breaker.allow()
            try:
                faults.inject("serve_accept")
                err = self._cmd_device(sock, sess, cmd, header, payload)
            except BaseException as e:
                # socket errors are peer failures, not device health:
                # a crashing client must never trip the breaker
                if not isinstance(e, (ConnectionError, OSError)):
                    self.breaker.note_failure(e)
                raise
            if err is not None:
                # _cmd_stream answered the client itself; the breaker
                # still needs to see the failure
                self.breaker.note_failure(err)
            else:
                self.breaker.note_success()
        elif cmd == "free":
            nbytes = sess.free_table(header.get("table"))
            frames.send_frame(sock, {"ok": True, "bytes": nbytes})
        elif cmd == "stats":
            frames.send_frame(sock, {"ok": True, "stats": self.stats()})
        else:
            frames.send_frame(sock, _error_header(
                frames.ProtocolError(f"unknown command {cmd!r}")
            ))

    def _cmd_device(self, sock, sess, cmd, header, payload):
        """Route one device command. Returns the exception a handler
        answered itself (stream sends its own error frame) or None —
        the breaker accounting in :meth:`_dispatch` needs it."""
        if cmd == "stream":
            return self._cmd_stream(sock, sess, header, payload)
        if cmd == "upload":
            self._cmd_upload(sock, sess, header, payload)
        elif cmd == "plan":
            self._cmd_plan(sock, sess, header)
        else:
            self._cmd_download(sock, sess, header)
        return None

    @staticmethod
    def _plan_ops(header) -> list:
        ops = header.get("plan")
        if not isinstance(ops, list):
            raise TypeError("serving: plan must be a JSON list of ops")
        return ops

    def _request_token(self, header, sess) -> faults.CancelToken:
        """Per-request cancellation token. Deadline precedence:
        command header ``deadline_s`` > session hello ``deadline_s`` >
        SPARK_RAPIDS_TPU_DEADLINE_DEFAULT_S; 0 anywhere means none."""
        d = header.get("deadline_s")
        if d is None:
            d = sess.deadline_s or float(
                config.get_flag("DEADLINE_DEFAULT_S")
            )
        d = float(d)
        if d < 0:
            raise ValueError(
                f"serving: deadline_s must be >= 0, got {d}"
            )
        return faults.CancelToken(deadline_s=d if d > 0 else None)

    @staticmethod
    def _client_gone(sock) -> bool:
        """Liveness poll while this conn thread is busy serving: a
        readable socket whose peek returns no bytes is a closed or
        reset peer (a pipelined next command peeks non-empty and is
        NOT a disconnect)."""
        try:
            r, _, _ = select.select([sock], [], [], 0)
            if not r:
                return False
            return sock.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _cmd_stream(self, sock, sess, header, payload):
        """The main entry: one plan over N inline batches, scheduled
        per batch (so a heavy stream interleaves with other tenants),
        answered in one frame, byte-identical to ``table_plan_wire``
        / ``table_stream_wire`` run serially.

        Returns the exception it answered with, or None on success
        (breaker accounting). Every batch runs under the request's
        :class:`faults.CancelToken`; between batches the conn thread
        polls the socket, so a client that crashed mid-stream cancels
        the remaining work at its next checkpoint instead of leaving
        it running against a dead peer while holding HBM charge."""
        ops = self._plan_ops(header)
        tok = self._request_token(header, sess)
        batches = frames.batches_from_parts(
            header.get("batches") or [], payload
        )
        # pre-admission static analysis against the first batch's wire
        # schema: a plan that statically cannot run answers a typed
        # bad_request (tagged report attached) BEFORE any scheduler
        # admission, HBM charge, or upload
        if batches:
            plancheck.check_plan(
                ops,
                schema=plancheck.schema_from_wire(
                    batches[0][0], batches[0][1]
                ),
                rows=int(batches[0][4]),
            )
        else:
            plancheck.check_plan(ops)
        n = len(batches)
        sess.stats["bytes_in"] += len(payload)
        scope = profiler.profile_session(
            ops, label=f"serve:{sess.name}", batches=n
        )
        prof = scope.__enter__()
        results = [None] * n
        window: deque = deque()

        def checkpoint():
            if self._client_gone(sock):
                tok.cancel("client disconnected mid-stream")
                metrics.counter_add("serving.cancelled")
                if flight.enabled():
                    flight.record(
                        "I", "serving.client_gone", sess.name
                    )
                raise ConnectionResetError(
                    f"session {sess.name}: client gone mid-stream"
                )
            tok.check()

        try:
            if flight.enabled():
                flight.record("I", "serving.stream", f"{sess.name}:{n}")

            def make_work(b):
                def work():
                    type_ids, scales, datas, valids, rows = b
                    tbl = rb._table_from_wire(
                        type_ids, scales, datas, valids, rows,
                        rb._plan_pad_to(ops, rows),
                    )
                    out = plan_mod.run_plan(ops, tbl, donate_input=True)
                    return rb._table_to_wire(out)

                return work

            for i, b in enumerate(batches):
                checkpoint()
                est = estimate_request_bytes(b)
                sess.admit(est)  # typed OverBudget / queues on inflight
                try:
                    t = self.scheduler.submit(
                        sess, make_work(b), cost=b[4],
                        label="stream", charge=est, prof=prof,
                        shed=(i == 0), token=tok,
                    )
                except BaseException:
                    sess.release(est)
                    raise
                window.append((i, t))
                # keep at most queue_depth batches of THIS stream in
                # flight; draining here (in order) bounds the window
                # without ever blocking the scheduler itself
                while len(window) >= self.queue_depth:
                    j, tj = window.popleft()
                    results[j] = tj.result()
                    checkpoint()
            while window:
                j, tj = window.popleft()
                results[j] = tj.result()
                if window:
                    # more results pending: a dead peer cancels them
                    # instead of computing for nobody
                    checkpoint()
        except BaseException as e:
            # drain stragglers before answering: their results are
            # discarded but their budget charges must settle. The
            # token is cancelled first so queued batches settle
            # without running and in-flight ones abort at their next
            # between-segment checkpoint
            if not tok.cancelled:
                tok.cancel(f"stream aborted: {type(e).__name__}")
            while window:
                _, tj = window.popleft()
                with contextlib.suppress(BaseException):
                    tj.result()
            if isinstance(e, (ConnectionError, OSError)):
                raise  # peer is gone: nobody to answer
            frames.send_frame(sock, _error_header(e))
            return e
        finally:
            scope.__exit__(None, None, None)
        metas, buffers = frames.batches_to_parts(results)
        sess.stats["bytes_out"] += sum(len(b) for b in buffers)
        frames.send_frame(sock, {"ok": True, "results": metas}, buffers)
        return None

    def _cmd_upload(self, sock, sess, header, payload) -> None:
        batch = frames.batches_from_parts(
            [header.get("batch") or {}], payload
        )[0]
        sess.stats["bytes_in"] += len(payload)
        est = estimate_request_bytes(batch)
        sess.admit(est)
        try:
            t = self.scheduler.submit(
                sess, lambda: rb.table_upload_wire(*batch),
                cost=batch[4], label="upload", charge=est,
            )
        except BaseException:
            sess.release(est)
            raise
        rb_id = t.result()
        actual = int(hbm.table_bytes(rb._resident_peek(rb_id)))
        local = sess.put_table(rb_id, actual)
        frames.send_frame(
            sock, {"ok": True, "table": local, "bytes": actual}
        )

    def _cmd_plan(self, sock, sess, header) -> None:
        ops = self._plan_ops(header)
        tok = self._request_token(header, sess)
        locals_ = [int(x) for x in (header.get("tables") or [])]
        if not locals_:
            raise ValueError("serving: plan needs at least one table id")
        donate = bool(header.get("donate"))
        rb_ids = [sess.rb_id(x) for x in locals_]
        # output estimate: the chain input's resident size (already
        # charged) approximates the result; charge it as in-flight
        # until the result's actual size lands as resident
        try:
            head = rb._resident_get(rb_ids[0])
        except KeyError:
            raise sess._unknown_local_error(locals_[0])
        # pre-admission static analysis against the resident schemas: a
        # statically-invalid plan answers bad_request before admit() or
        # the scheduler queue. Rest inputs degrade to structural checks
        # when pending or missing (the runtime surfaces those exactly as
        # before).
        rest_sigs = []
        for rid in rb_ids[1:]:
            try:
                t = rb._resident_peek(rid)
            except KeyError:
                t = None
            rest_sigs.append(
                (plancheck.schema_of_table(t), int(t.logical_row_count))
                if t is not None and not isinstance(t, pipeline.Pending)
                else (None, None)
            )
        plancheck.check_plan(
            ops,
            schema=plancheck.schema_of_table(head),
            rows=int(head.logical_row_count),
            rest=rest_sigs,
            names=head.names,
        )
        est = int(hbm.table_bytes(head))
        sess.admit(est)
        plan_json = json.dumps(ops)
        try:
            t = self.scheduler.submit(
                sess,
                lambda: rb.table_plan_resident(plan_json, rb_ids, donate),
                cost=max(est // 64, 1), label="plan", charge=est,
                token=tok,
            )
        except BaseException:
            sess.release(est)
            raise
        out_id = t.result()
        if donate:
            sess.drop_local(locals_[0])
        out = rb._resident_peek(out_id)
        actual = (
            est if isinstance(out, pipeline.Pending)
            else int(hbm.table_bytes(out))
        )
        local = sess.put_table(out_id, actual)
        frames.send_frame(sock, {"ok": True, "table": local})

    def _cmd_download(self, sock, sess, header) -> None:
        rb_id = sess.rb_id(header.get("table"))
        t = self.scheduler.submit(
            sess, lambda: rb.table_download_wire(rb_id),
            cost=1, label="download",
        )
        result = t.result()
        meta, buffers = frames.batch_to_parts(result)
        sess.stats["bytes_out"] += sum(len(b) for b in buffers)
        frames.send_frame(sock, {"ok": True, "result": meta}, buffers)

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            sessions = [s.to_doc() for s in self._sessions.values()]
            served = self._sessions_served
        return {
            "port": self.port,
            "max_sessions": self.max_sessions,
            "queue_depth": self.queue_depth,
            "session_hbm_fraction": self.session_hbm_fraction,
            "sessions_live": len(sessions),
            "sessions_served": served,
            "resident_tables": rb.resident_table_count(),
            "spill": spill.stats_doc(),
            "breaker": self.breaker.to_doc(),
            "sessions": sessions,
        }


@contextlib.contextmanager
def serve(**kwargs):
    """``with serve(...) as srv:`` — start a daemon, always stop it."""
    srv = Server(**kwargs).start()
    try:
        yield srv
    finally:
        srv.stop()
