"""The serving daemon: a long-lived multi-tenant query-stream server.

This is the deployment shape the reference stack assumes — one resident
device process (the JVM executor that loads the shaded
``rapids-4-spark-jni`` artifact once) serving many concurrent Spark
tasks. Here the resident process is this :class:`Server`: it listens on
localhost TCP (length-prefixed JSON+binary frames, serving/frames.py),
gives each client connection a :class:`~.session.Session` (namespace +
HBM budget), runs every request through the weighted-deficit
:class:`~.scheduler.FairScheduler`, and executes through the existing
runtime bridge — so shape buckets, plan fusion, the pipelined dispatch
plane and buffer donation all apply per request, and the compiled-
executable cache (``buckets.cached_jit``) is naturally **shared across
sessions**: tenant B warm-hits tenant A's compiles because the cache is
process-global and keyed only by plan/schema/bucket/donation.

Commands (frame header ``cmd``):

* ``hello``      open (or re-attach to) a session; returns id + budget
* ``stream``     run a plan over N inline batches; returns N results
* ``upload``     wire batch -> session-resident table id
* ``plan``       plan over resident ids -> new resident id
* ``download``   resident id -> wire batch
* ``free``       reclaim one resident table's HBM now
* ``stats``      server + per-session statistics
* ``bye``        detach this connection (last detach tears the session
                 down with full table reclamation — as does a crash)

Errors are typed responses ``{"ok": false, "error": {"type", value
"message"}}``; notably ``busy`` (queue shed) and ``over_budget``
(admission) — a saturated daemon answers, it never hangs.

Every served stream opens a ``profiler.profile_session`` labeled
``serve:<session-name>``, so profile/flight dumps are session-stamped
and ``tools/explain.py --merge`` renders a multi-tenant timeline.
"""

from __future__ import annotations

import contextlib
import json
import socket
import threading
import uuid
from collections import deque
from typing import Optional

from .. import pipeline, plan as plan_mod, runtime_bridge as rb
from ..utils import config, flight, hbm, metrics, profiler
from . import frames
from .scheduler import Busy, FairScheduler
from .session import (
    OverBudget,
    Session,
    SessionClosed,
    estimate_request_bytes,
)


class SessionLimit(Exception):
    """Typed HELLO rejection: the daemon is at SERVE_MAX_SESSIONS."""


_ERROR_TYPES = {
    Busy: "busy",
    OverBudget: "over_budget",
    SessionLimit: "session_limit",
    SessionClosed: "session_closed",
    KeyError: "unknown_table",
    frames.ProtocolError: "bad_request",
    TypeError: "bad_request",
    ValueError: "bad_request",
}


def _error_type(exc: BaseException) -> str:
    for cls, name in _ERROR_TYPES.items():
        if isinstance(exc, cls):
            return name
    return "internal"


def _error_header(exc: BaseException) -> dict:
    msg = str(exc)
    if isinstance(exc, KeyError) and exc.args:
        msg = str(exc.args[0])  # un-repr the KeyError message
    return {
        "ok": False,
        "error": {
            "type": _error_type(exc),
            "exception": type(exc).__name__,
            "message": msg,
        },
    }


class Server:
    """The resident daemon. ``with Server().start() as srv:`` or call
    :meth:`start` / :meth:`stop` explicitly; ``srv.port`` is the bound
    port (OS-assigned when SERVE_PORT / ``port`` is 0)."""

    def __init__(self, port: Optional[int] = None,
                 max_sessions: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 session_hbm_fraction: Optional[float] = None,
                 workers: int = 2):
        self._port_req = (
            int(config.get_flag("SERVE_PORT")) if port is None else port
        )
        self.max_sessions = (
            int(config.get_flag("SERVE_MAX_SESSIONS"))
            if max_sessions is None else int(max_sessions)
        )
        self.queue_depth = (
            int(config.get_flag("SERVE_QUEUE_DEPTH"))
            if queue_depth is None else int(queue_depth)
        )
        self.session_hbm_fraction = (
            float(config.get_flag("SERVE_SESSION_HBM_FRACTION"))
            if session_hbm_fraction is None
            else float(session_hbm_fraction)
        )
        self.scheduler = FairScheduler(
            workers=workers, queue_depth=self.queue_depth
        )
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._sessions: dict = {}
        self._conns: set = set()
        self._conn_threads: list = []
        self._stopping = False
        self._sessions_served = 0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Server":
        self.scheduler.start()
        s = socket.create_server(("127.0.0.1", self._port_req))
        self.port = s.getsockname()[1]
        self._listener = s
        t = threading.Thread(
            target=self._accept_loop, name="srt-serve-accept", daemon=True
        )
        t.start()
        self._accept_thread = t
        if flight.enabled():
            flight.record("I", "serving.start", self.port)
        return self

    def stop(self) -> None:
        """Shut down: stop accepting, close connections (tearing their
        sessions down with full reclamation), stop executors, drain the
        pipelined plane."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            conns = list(self._conns)
            threads = list(self._conn_threads)
        if self._listener is not None:
            # closing a listening socket does NOT wake a thread blocked
            # in accept() on Linux — poke it with a throwaway connection
            # (the accept loop sees _stopping and exits) so shutdown is
            # immediate instead of eating the join timeout
            with contextlib.suppress(OSError):
                socket.create_connection(
                    ("127.0.0.1", self.port), timeout=1
                ).close()
            with contextlib.suppress(OSError):
                self._listener.close()
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        for t in threads:
            t.join(timeout=10)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10)
        # belt-and-braces: a session left attached by a hung handler
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
        for sess in leftovers:
            self.scheduler.unregister(sess)
            sess.teardown()
        self.scheduler.stop()
        pipeline.drain()
        if flight.enabled():
            flight.record("I", "serving.stop", self.port)

    def __enter__(self) -> "Server":
        if self.port is None:
            self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    # -- accept / connection plumbing ------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            with self._lock:
                if self._stopping:
                    with contextlib.suppress(OSError):
                        sock.close()
                    return
                self._conns.add(sock)
                t = threading.Thread(
                    target=self._handle_conn, args=(sock,),
                    name="srt-serve-conn", daemon=True,
                )
                self._conn_threads.append(t)
            t.start()

    def _handle_conn(self, sock: socket.socket) -> None:
        sess: Optional[Session] = None
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                header, payload = frames.recv_frame(sock)
                cmd = header.get("cmd")
                if cmd == "hello":
                    sess = self._cmd_hello(sock, header, sess)
                    continue
                if cmd == "bye":
                    frames.send_frame(sock, {"ok": True})
                    break
                if sess is None:
                    frames.send_frame(sock, _error_header(
                        frames.ProtocolError(
                            f"first frame must be hello, got {cmd!r}"
                        )
                    ))
                    continue
                try:
                    self._dispatch(sock, sess, cmd, header, payload)
                except (BrokenPipeError, ConnectionError, OSError):
                    raise
                except BaseException as e:
                    frames.send_frame(sock, _error_header(e))
        except (ConnectionError, OSError, frames.ProtocolError):
            # disconnect / crash mid-stream: the finally below detaches
            # and (on last detach) tears the session down with full
            # table reclamation — the "crash leaks zero tables" path
            pass
        finally:
            with contextlib.suppress(OSError):
                sock.close()
            with self._lock:
                self._conns.discard(sock)
            if sess is not None:
                self._detach(sess)

    # -- session lifecycle ------------------------------------------------
    def _cmd_hello(self, sock, header, prev: Optional[Session]):
        try:
            sess = self._attach(header)
        except (SessionLimit, SessionClosed, ValueError, TypeError) as e:
            frames.send_frame(sock, _error_header(e))
            return prev
        if prev is not None and prev is not sess:
            self._detach(prev)
        frames.send_frame(sock, {
            "ok": True,
            "session": sess.id,
            "name": sess.name,
            "weight": sess.weight,
            "budget_bytes": sess.budget_bytes,
            "queue_depth": self.queue_depth,
        })
        return sess

    def _attach(self, header) -> Session:
        sid = header.get("session")
        weight = float(header.get("weight", 1.0) or 1.0)
        with self._lock:
            if sid is not None:
                sess = self._sessions.get(sid)
                if sess is None:
                    raise SessionClosed(
                        f"unknown or already-closed session {sid!r}"
                    )
                sess.connections += 1
                return sess
            if len(self._sessions) >= self.max_sessions:
                raise SessionLimit(
                    f"daemon at max sessions ({self.max_sessions}); "
                    "retry after a session closes"
                )
            new_id = uuid.uuid4().hex[:8]
            name = str(header.get("name") or f"sess-{new_id}")
            budget = max(
                int(self.session_hbm_fraction * hbm.budget_bytes()), 1
            )
            sess = Session(new_id, name, weight, budget)
            sess.connections = 1
            self._sessions[new_id] = sess
            self._sessions_served += 1
            live = len(self._sessions)
        self.scheduler.register(sess)
        metrics.counter_add("serving.sessions_opened")
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_open", sess.name)
        return sess

    def _detach(self, sess: Session) -> None:
        with self._lock:
            sess.connections -= 1
            last = sess.connections <= 0
            if last:
                self._sessions.pop(sess.id, None)
            live = len(self._sessions)
        if not last:
            return
        # order matters: unregister drains the session's queued AND
        # in-flight work first, so teardown reclaims tables no executor
        # still touches (and table_reclaim's barrier covers any
        # pipelined reader beyond that)
        self.scheduler.unregister(sess)
        reclaimed = sess.teardown()
        metrics.counter_add("serving.sessions_closed")
        metrics.bytes_add("serving.reclaimed_bytes", reclaimed)
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_close", sess.name)

    # -- request dispatch -------------------------------------------------
    def _dispatch(self, sock, sess, cmd, header, payload) -> None:
        if cmd == "stream":
            self._cmd_stream(sock, sess, header, payload)
        elif cmd == "upload":
            self._cmd_upload(sock, sess, header, payload)
        elif cmd == "plan":
            self._cmd_plan(sock, sess, header)
        elif cmd == "download":
            self._cmd_download(sock, sess, header)
        elif cmd == "free":
            nbytes = sess.free_table(header.get("table"))
            frames.send_frame(sock, {"ok": True, "bytes": nbytes})
        elif cmd == "stats":
            frames.send_frame(sock, {"ok": True, "stats": self.stats()})
        else:
            frames.send_frame(sock, _error_header(
                frames.ProtocolError(f"unknown command {cmd!r}")
            ))

    @staticmethod
    def _plan_ops(header) -> list:
        ops = header.get("plan")
        if not isinstance(ops, list):
            raise TypeError("serving: plan must be a JSON list of ops")
        return ops

    def _cmd_stream(self, sock, sess, header, payload) -> None:
        """The main entry: one plan over N inline batches, scheduled
        per batch (so a heavy stream interleaves with other tenants),
        answered in one frame, byte-identical to ``table_plan_wire``
        / ``table_stream_wire`` run serially."""
        ops = self._plan_ops(header)
        batches = frames.batches_from_parts(
            header.get("batches") or [], payload
        )
        n = len(batches)
        sess.stats["bytes_in"] += len(payload)
        scope = profiler.profile_session(
            ops, label=f"serve:{sess.name}", batches=n
        )
        prof = scope.__enter__()
        results = [None] * n
        window: deque = deque()
        try:
            if flight.enabled():
                flight.record("I", "serving.stream", f"{sess.name}:{n}")

            def make_work(b):
                def work():
                    type_ids, scales, datas, valids, rows = b
                    tbl = rb._table_from_wire(
                        type_ids, scales, datas, valids, rows,
                        rb._plan_pad_to(ops, rows),
                    )
                    out = plan_mod.run_plan(ops, tbl, donate_input=True)
                    return rb._table_to_wire(out)

                return work

            for i, b in enumerate(batches):
                est = estimate_request_bytes(b)
                sess.admit(est)  # typed OverBudget / queues on inflight
                try:
                    t = self.scheduler.submit(
                        sess, make_work(b), cost=b[4],
                        label="stream", charge=est, prof=prof,
                        shed=(i == 0),
                    )
                except BaseException:
                    sess.release(est)
                    raise
                window.append((i, t))
                # keep at most queue_depth batches of THIS stream in
                # flight; draining here (in order) bounds the window
                # without ever blocking the scheduler itself
                while len(window) >= self.queue_depth:
                    j, tj = window.popleft()
                    results[j] = tj.result()
            while window:
                j, tj = window.popleft()
                results[j] = tj.result()
        except BaseException as e:
            # drain stragglers before answering: their results are
            # discarded but their budget charges must settle
            while window:
                _, tj = window.popleft()
                with contextlib.suppress(BaseException):
                    tj.result()
            frames.send_frame(sock, _error_header(e))
            return
        finally:
            scope.__exit__(None, None, None)
        metas, buffers = frames.batches_to_parts(results)
        sess.stats["bytes_out"] += sum(len(b) for b in buffers)
        frames.send_frame(sock, {"ok": True, "results": metas}, buffers)

    def _cmd_upload(self, sock, sess, header, payload) -> None:
        batch = frames.batches_from_parts(
            [header.get("batch") or {}], payload
        )[0]
        sess.stats["bytes_in"] += len(payload)
        est = estimate_request_bytes(batch)
        sess.admit(est)
        try:
            t = self.scheduler.submit(
                sess, lambda: rb.table_upload_wire(*batch),
                cost=batch[4], label="upload", charge=est,
            )
        except BaseException:
            sess.release(est)
            raise
        rb_id = t.result()
        actual = int(hbm.table_bytes(rb._resident_peek(rb_id)))
        local = sess.put_table(rb_id, actual)
        frames.send_frame(
            sock, {"ok": True, "table": local, "bytes": actual}
        )

    def _cmd_plan(self, sock, sess, header) -> None:
        ops = self._plan_ops(header)
        locals_ = [int(x) for x in (header.get("tables") or [])]
        if not locals_:
            raise ValueError("serving: plan needs at least one table id")
        donate = bool(header.get("donate"))
        rb_ids = [sess.rb_id(x) for x in locals_]
        # output estimate: the chain input's resident size (already
        # charged) approximates the result; charge it as in-flight
        # until the result's actual size lands as resident
        try:
            est = int(hbm.table_bytes(rb._resident_get(rb_ids[0])))
        except KeyError:
            raise sess._unknown_local_error(locals_[0])
        sess.admit(est)
        plan_json = json.dumps(ops)
        try:
            t = self.scheduler.submit(
                sess,
                lambda: rb.table_plan_resident(plan_json, rb_ids, donate),
                cost=max(est // 64, 1), label="plan", charge=est,
            )
        except BaseException:
            sess.release(est)
            raise
        out_id = t.result()
        if donate:
            sess.drop_local(locals_[0])
        out = rb._resident_peek(out_id)
        actual = (
            est if isinstance(out, pipeline.Pending)
            else int(hbm.table_bytes(out))
        )
        local = sess.put_table(out_id, actual)
        frames.send_frame(sock, {"ok": True, "table": local})

    def _cmd_download(self, sock, sess, header) -> None:
        rb_id = sess.rb_id(header.get("table"))
        t = self.scheduler.submit(
            sess, lambda: rb.table_download_wire(rb_id),
            cost=1, label="download",
        )
        result = t.result()
        meta, buffers = frames.batch_to_parts(result)
        sess.stats["bytes_out"] += sum(len(b) for b in buffers)
        frames.send_frame(sock, {"ok": True, "result": meta}, buffers)

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            sessions = [s.to_doc() for s in self._sessions.values()]
            served = self._sessions_served
        return {
            "port": self.port,
            "max_sessions": self.max_sessions,
            "queue_depth": self.queue_depth,
            "session_hbm_fraction": self.session_hbm_fraction,
            "sessions_live": len(sessions),
            "sessions_served": served,
            "resident_tables": rb.resident_table_count(),
            "sessions": sessions,
        }


@contextlib.contextmanager
def serve(**kwargs):
    """``with serve(...) as srv:`` — start a daemon, always stop it."""
    srv = Server(**kwargs).start()
    try:
        yield srv
    finally:
        srv.stop()
