"""The serving daemon: a long-lived multi-tenant query-stream server.

This is the deployment shape the reference stack assumes — one resident
device process (the JVM executor that loads the shaded
``rapids-4-spark-jni`` artifact once) serving many concurrent Spark
tasks. Here the resident process is this :class:`Server`: it listens on
localhost TCP (length-prefixed JSON+binary frames, serving/frames.py),
gives each client connection a :class:`~.session.Session` (namespace +
HBM budget), runs every request through the weighted-deficit
:class:`~.scheduler.FairScheduler`, and executes through the existing
runtime bridge — so shape buckets, plan fusion, the pipelined dispatch
plane and buffer donation all apply per request, and the compiled-
executable cache (``buckets.cached_jit``) is naturally **shared across
sessions**: tenant B warm-hits tenant A's compiles because the cache is
process-global and keyed only by plan/schema/bucket/donation.

Commands (frame header ``cmd``):

* ``hello``      open (or re-attach to) a session; returns id + budget
* ``stream``     run a plan over N inline batches; returns N results
* ``upload``     wire batch -> session-resident table id
* ``plan``       plan over resident ids -> new resident id
* ``download``   resident id -> wire batch
* ``free``       reclaim one resident table's HBM now
* ``stats``      server + per-session statistics
* ``trace``      live introspection: tail-sampled slow-request log +
                 Prometheus-style text exposition of the metrics
* ``bye``        detach this connection (last detach tears the session
                 down with full table reclamation — as does a crash)

Errors are typed responses ``{"ok": false, "error": {"type", value
"message"}}``; notably ``busy`` (queue shed) and ``over_budget``
(admission) — a saturated daemon answers, it never hangs.

Every served stream opens a ``profiler.profile_session`` labeled
``serve:<session-name>``, so profile/flight dumps are session-stamped
and ``tools/explain.py --merge`` renders a multi-tenant timeline.
"""

from __future__ import annotations

import contextlib
import json
import os
import select
import socket
import threading
import time
import uuid
from collections import deque
from typing import Optional

from .. import pipeline, plan as plan_mod, plancheck, runtime_bridge as rb
from ..utils import (
    config,
    faults,
    flight,
    hbm,
    lockcheck,
    log,
    metrics,
    planstats,
    profiler,
    spill,
    tracing,
)
from . import durable, frames
from .scheduler import Busy, FairScheduler
from .session import (
    OverBudget,
    Session,
    SessionClosed,
    estimate_request_bytes,
)


class SessionLimit(Exception):
    """Typed HELLO rejection: the daemon is at SERVE_MAX_SESSIONS."""


# ordered most-specific first: the fault taxonomy entries must win
# over any generic base class they might share
_ERROR_TYPES = {
    durable.CheckpointCorrupt: "checkpoint_corrupt",
    durable.ResumeDenied: "resume_denied",
    durable.SessionQuarantined: "session_quarantined",
    durable.Draining: "draining",
    faults.Degraded: "degraded",
    faults.Cancelled: "cancelled",
    faults.DeadlineExceeded: "deadline_exceeded",
    faults.ResourceExhausted: "resource_exhausted",
    faults.TransientDeviceError: "transient_device",
    Busy: "busy",
    OverBudget: "over_budget",
    SessionLimit: "session_limit",
    SessionClosed: "session_closed",
    KeyError: "unknown_table",
    frames.ProtocolError: "bad_request",
    TypeError: "bad_request",
    ValueError: "bad_request",
}


def _error_type(exc: BaseException) -> str:
    for cls, name in _ERROR_TYPES.items():
        if isinstance(exc, cls):
            return name
    return "internal"


def _error_header(exc: BaseException) -> dict:
    msg = str(exc)
    if isinstance(exc, KeyError) and exc.args:
        msg = str(exc.args[0])  # un-repr the KeyError message
    err = {
        "type": _error_type(exc),
        "exception": type(exc).__name__,
        "message": msg,
    }
    # a plancheck rejection carries the full tagged report (per-op tier +
    # reason, GpuOverrides-style) — ship it so the client learns *why*
    # before paying upload or queue wait
    report = getattr(exc, "plan_report", None)
    if report is not None:
        err["plan_report"] = report
    return {"ok": False, "error": err}


class Server:
    """The resident daemon. ``with Server().start() as srv:`` or call
    :meth:`start` / :meth:`stop` explicitly; ``srv.port`` is the bound
    port (OS-assigned when SERVE_PORT / ``port`` is 0)."""

    def __init__(self, port: Optional[int] = None,
                 max_sessions: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 session_hbm_fraction: Optional[float] = None,
                 workers: int = 2):
        self._port_req = (
            int(config.get_flag("SERVE_PORT")) if port is None else port
        )
        self.max_sessions = (
            int(config.get_flag("SERVE_MAX_SESSIONS"))
            if max_sessions is None else int(max_sessions)
        )
        self.queue_depth = (
            int(config.get_flag("SERVE_QUEUE_DEPTH"))
            if queue_depth is None else int(queue_depth)
        )
        self.session_hbm_fraction = (
            float(config.get_flag("SERVE_SESSION_HBM_FRACTION"))
            if session_hbm_fraction is None
            else float(session_hbm_fraction)
        )
        self.scheduler = FairScheduler(
            workers=workers, queue_depth=self.queue_depth
        )
        # N consecutive transient failures flip the daemon to typed
        # Degraded sheds; a background probe closes it again without
        # waiting for client traffic (faults.CircuitBreaker)
        self.breaker = faults.CircuitBreaker(name="serving")
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = lockcheck.make_lock("session.server")
        self._sessions: dict = {}
        self._conns: set = set()
        self._conn_threads: list = []
        self._stopping = False
        self._stopped = threading.Event()
        self._sessions_served = 0
        # durable serving plane (serving/durable.py)
        self._draining = False
        self._durable_logs: dict = {}   # sid -> durable.SessionLog
        self._quarantined: dict = {}    # sid -> quarantine reason
        self._manifest: Optional[durable.Manifest] = None
        self._restore_doc: Optional[dict] = None
        # mesh-backed sessions (parallel/tolerant.py): one shared
        # MeshRunner per requested device count — the degradation
        # ladder's state (surviving mesh, counters) is daemon-wide, so
        # a mesh that shrank for one tenant stays shrunk for the next
        self._mesh_runners: dict = {}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Server":
        self.scheduler.start()
        if durable.enabled():
            # recover BEFORE the listener opens: the first client to
            # connect sees restored sessions and a warm compile cache
            self._restore()
        s = socket.create_server(("127.0.0.1", self._port_req))
        self.port = s.getsockname()[1]
        self._listener = s
        t = threading.Thread(
            target=self._accept_loop, name="srt-serve-accept", daemon=True
        )
        t.start()
        self._accept_thread = t
        p = threading.Thread(
            target=self._probe_loop, name="srt-serve-probe", daemon=True
        )
        p.start()
        self._probe_thread = p
        if flight.enabled():
            flight.record("I", "serving.start", self.port)
        return self

    def stop(self) -> None:
        """Shut down: stop accepting, close connections (tearing their
        sessions down with full reclamation), stop executors, drain the
        pipelined plane."""
        with self._lock:
            if self._stopping:
                already = True
            else:
                already = False
                self._stopping = True
                conns = list(self._conns)
                threads = list(self._conn_threads)
        if already:
            # another stopper (e.g. the drain command's background
            # shutdown thread) is mid-teardown: wait for it so callers
            # see a fully-stopped daemon, not a racing one
            self._stopped.wait(timeout=30)
            return
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
        if self._listener is not None:
            # closing a listening socket does NOT wake a thread blocked
            # in accept() on Linux — poke it with a throwaway connection
            # (the accept loop sees _stopping and exits) so shutdown is
            # immediate instead of eating the join timeout
            with contextlib.suppress(OSError):
                socket.create_connection(
                    ("127.0.0.1", self.port), timeout=1
                ).close()
            with contextlib.suppress(OSError):
                self._listener.close()
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        for t in threads:
            t.join(timeout=10)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10)
        # belt-and-braces: a session left attached by a hung handler
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
        for sess in leftovers:
            self.scheduler.unregister(sess)
            sess.teardown()
        # release journal handles; the files STAY — a stopped (or
        # drained) durable daemon restores them on its next start
        with self._lock:
            dlogs = list(self._durable_logs.values())
            self._durable_logs.clear()
        for dlog in dlogs:
            dlog.close()
        if self._manifest is not None:
            self._manifest.close()
        self.scheduler.stop()
        pipeline.drain()
        if flight.enabled():
            flight.record("I", "serving.stop", self.port)
        self._stopped.set()

    def __enter__(self) -> "Server":
        if self.port is None:
            self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    # -- durable restore --------------------------------------------------
    def _restore(self) -> None:
        """Crash recovery, before the listener opens: replay every
        session journal into a live session (tables repaged from their
        checkpoint payloads, budgets and HBM accounting re-charged),
        then warm-start the compile cache from the manifest — the
        restarted daemon's first request lands on recovered state with
        zero compiles for previously-served plans. A session whose
        journal or payloads fail integrity checks is quarantined and
        skipped; restore itself never crashes the daemon."""
        t0 = time.perf_counter()
        with metrics.span("restore"):
            sessions, quarantined = durable.restore_scan()
            self._quarantined.update(quarantined)
            restored = 0
            for rs in sessions:
                try:
                    self._restore_session(rs)
                    restored += 1
                except (durable.CheckpointCorrupt, faults.FaultError,
                        OSError) as e:
                    durable.quarantine(rs.sid, str(e))
                    self._quarantined[rs.sid] = str(e)
            self._manifest = durable.Manifest()
            compiled, failed = self._manifest.warm_start()
        self._restore_doc = {
            "sessions": restored,
            "quarantined": dict(self._quarantined),
            "warm_compiles": compiled,
            "warm_failures": failed,
            "took_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        if flight.enabled():
            flight.record("I", "restore.done", restored)
        if restored or compiled or self._quarantined:
            log.log("INFO", "serving", "restore", **self._restore_doc)

    def _restore_session(self, rs: "durable.RestoredSession") -> None:
        budget = rs.budget or max(
            int(self.session_hbm_fraction * hbm.budget_bytes()), 1
        )
        sess = Session(rs.sid, rs.name, rs.weight, budget)
        sess.resume_token = rs.token
        sess.connections = 0
        total = 0
        try:
            for local in sorted(rs.tables):
                fname, nbytes = rs.tables[local]
                path = os.path.join(durable.checkpoint_dir(), fname)
                tbl = durable.load_payload(path)
                rb_id = rb._resident_put(tbl)
                sess.restore_table(local, rb_id, nbytes)
                total += nbytes
        except BaseException:
            sess.teardown()  # unwind the partially-restored namespace
            raise
        for req, resp in rs.dedup.items():
            sess.dedup_put(req, resp, cap=durable.DEDUP_CAP)
        sess.advance_locals(rs.next_local)
        with self._lock:
            self._sessions[rs.sid] = sess
            self._sessions_served += 1
            self._durable_logs[rs.sid] = durable.SessionLog(rs.sid)
            live = len(self._sessions)
        self.scheduler.register(sess)
        durable.count("restore.sessions")
        durable.count("restore.tables", len(rs.tables))
        durable.count("restore.bytes", total, as_bytes=True)
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "restore.session", rs.name)

    def _dlog(self, sess) -> Optional["durable.SessionLog"]:
        if not durable.enabled():
            return None
        with self._lock:
            return self._durable_logs.get(sess.id)

    @staticmethod
    def _journal_safe(dlog, method: str, *args, **kwargs) -> None:
        """Apply one journal mutation, degrading durability (counted,
        logged) instead of failing the live request — the in-memory
        state is authoritative; the journal self-heals on the next
        append (Journal tail recovery)."""
        if dlog is None:
            return
        try:
            getattr(dlog, method)(*args, **kwargs)
        except (faults.FaultError, OSError) as e:
            durable.count("checkpoint.errors")
            log.log("WARN", "serving", "journal_degraded",
                    session=dlog.sid, record=method, reason=str(e))

    # -- accept / connection plumbing ------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            with self._lock:
                if self._stopping:
                    with contextlib.suppress(OSError):
                        sock.close()
                    return
                self._conns.add(sock)
                t = threading.Thread(
                    target=self._handle_conn, args=(sock,),
                    name="srt-serve-conn", daemon=True,
                )
                self._conn_threads.append(t)
            t.start()

    def _probe_loop(self) -> None:
        """Background half-open probing: while the breaker is OPEN,
        periodically run one trivial device op so the daemon recovers
        (closes the breaker) even with zero client traffic. Client
        requests race for the same half-open slot; whoever wins is the
        trial — the loser sheds typed Degraded as usual."""
        interval = max(self.breaker.probe_interval_s / 4, 0.05)
        while not self._probe_stop.wait(interval):
            if self.breaker.state == faults.CLOSED:
                continue
            try:
                if not self.breaker.allow():
                    continue  # closed between the check and the call
            except faults.Degraded:
                continue  # probe interval not yet elapsed
            try:
                faults.default_probe()
            except BaseException as e:
                self.breaker.note_failure(e)
            else:
                self.breaker.note_success()

    def _handle_conn(self, sock: socket.socket) -> None:
        sess: Optional[Session] = None
        clean = False
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                header, payload = frames.recv_frame(sock)
                cmd = header.get("cmd")
                # trace-context establishment, once per request: a
                # valid peer `traceparent` is joined (same trace id,
                # fresh hop span id), no header mints a fresh context
                # when the plane is on — every span/instant the
                # handlers record below inherits it ambiently
                ctx = tracing.ensure_context(header.get("traceparent"))
                if cmd == "hello":
                    with tracing.activate(ctx):
                        sess = self._cmd_hello(sock, header, sess)
                    continue
                if cmd == "bye":
                    # detach BEFORE the ack: the client treats the bye
                    # reply as "slot freed", and may immediately open a
                    # new session against max_sessions
                    clean = True
                    if sess is not None:
                        self._detach(sess, clean=True)
                        sess = None
                    frames.send_frame(sock, {"ok": True})
                    break
                if sess is None:
                    frames.send_frame(sock, _error_header(
                        frames.ProtocolError(
                            f"first frame must be hello, got {cmd!r}"
                        )
                    ))
                    continue
                t0 = time.perf_counter()
                err: Optional[BaseException] = None
                try:
                    with tracing.activate(ctx):
                        self._dispatch(sock, sess, cmd, header, payload)
                except (BrokenPipeError, ConnectionError, OSError):
                    raise
                # srt: allow-broad-except(every failure becomes a typed error frame via _error_header; the client always gets an answer, never a hang)
                except BaseException as e:
                    err = e
                    frames.send_frame(sock, _error_header(e))
                self._note_request(cmd, sess, ctx, t0, err)
        except (ConnectionError, OSError, frames.ProtocolError):
            # disconnect / crash mid-stream: the finally below detaches
            # and (on last detach) tears the session down with full
            # table reclamation — the "crash leaks zero tables" path
            pass
        finally:
            with contextlib.suppress(OSError):
                sock.close()
            with self._lock:
                self._conns.discard(sock)
            if sess is not None:
                self._detach(sess, clean=clean)

    @staticmethod
    def _note_request(cmd, sess, ctx, t0: float,
                      err: Optional[BaseException]) -> None:
        """Feed one finished request into the tail-sampled slow-request
        log behind the ``trace`` command. The span detail is passed as
        a callable so the flight-tail walk only runs when the record
        samples in (SLO breach or typed error — utils/tracing.py)."""
        if ctx is None:
            return
        ms = (time.perf_counter() - t0) * 1e3
        tracing.note_request(
            "serving." + str(cmd), ms,
            trace_id=ctx.trace_id,
            session=sess.name,
            error=_error_type(err) if err is not None else None,
            spans=lambda: tracing.trace_span_records(
                flight.tail_records(), ctx.trace_id
            ),
        )

    # -- session lifecycle ------------------------------------------------
    def _cmd_hello(self, sock, header, prev: Optional[Session]):
        try:
            sess = self._attach(header)
        except (SessionLimit, SessionClosed, ValueError, TypeError,
                durable.ResumeDenied, durable.SessionQuarantined,
                durable.Draining) as e:
            frames.send_frame(sock, _error_header(e))
            return prev
        if prev is not None and prev is not sess:
            self._detach(prev)
        doc = {
            "ok": True,
            "session": sess.id,
            "name": sess.name,
            "weight": sess.weight,
            "budget_bytes": sess.budget_bytes,
            "queue_depth": self.queue_depth,
        }
        if sess.resume_token is not None:
            doc["resume_token"] = sess.resume_token
            doc["tables"] = sess.table_count()
        frames.send_frame(sock, doc)
        return sess

    def _mesh_runner(self, n_devices: int):
        """The shared MeshRunner for ``n_devices`` (None when 0).

        Construction happens OUTSIDE the server lock (mesh setup can
        compile); a racing duplicate loses to ``setdefault`` and is
        dropped. ValueError from an impossible device count propagates
        to the hello/stream error path as a typed bad_request."""
        n = int(n_devices or 0)
        if not n:
            return None
        with self._lock:
            runner = self._mesh_runners.get(n)
        if runner is not None:
            return runner
        from ..parallel.tolerant import MeshRunner

        runner = MeshRunner(n)
        with self._lock:
            return self._mesh_runners.setdefault(n, runner)

    def _attach(self, header) -> Session:
        sid = header.get("session")
        weight = float(header.get("weight", 1.0) or 1.0)
        deadline_s = float(header.get("deadline_s") or 0.0)
        if deadline_s < 0:
            raise ValueError(
                f"hello: deadline_s must be >= 0, got {deadline_s}"
            )
        mesh_devices = int(header.get("mesh") or 0)
        if mesh_devices < 0:
            raise ValueError(
                f"hello: mesh must be >= 0 devices, got {mesh_devices}"
            )
        if mesh_devices:
            # eager loud-fail: a device count this host cannot mesh
            # answers a typed bad_request AT HELLO (make_mesh names the
            # remedy), not an internal error on the first stream
            self._mesh_runner(mesh_devices)
        dur = durable.enabled()
        with self._lock:
            if self._draining:
                raise durable.Draining(
                    "daemon is draining for restart; no new sessions"
                )
            if sid is not None:
                sess = self._sessions.get(sid)
                if sess is None:
                    reason = self._quarantined.get(sid)
                    if reason is not None:
                        raise durable.SessionQuarantined(
                            f"session {sid!r}: durable state quarantined"
                            f" ({reason}); open a fresh session"
                        )
                    raise SessionClosed(
                        f"unknown or already-closed session {sid!r}"
                    )
                if (dur and sess.resume_token is not None
                        and header.get("resume") != sess.resume_token):
                    raise durable.ResumeDenied(
                        f"session {sid!r}: missing or wrong resume "
                        "token"
                    )
                sess.connections += 1
                if deadline_s:
                    sess.deadline_s = deadline_s
                if mesh_devices:
                    sess.mesh_devices = mesh_devices
                return sess
            if len(self._sessions) >= self.max_sessions:
                raise SessionLimit(
                    f"daemon at max sessions ({self.max_sessions}); "
                    "retry after a session closes"
                )
            new_id = uuid.uuid4().hex[:8]
            name = str(header.get("name") or f"sess-{new_id}")
            budget = max(
                int(self.session_hbm_fraction * hbm.budget_bytes()), 1
            )
            sess = Session(new_id, name, weight, budget)
            sess.deadline_s = deadline_s
            sess.mesh_devices = mesh_devices
            sess.connections = 1
            self._sessions[new_id] = sess
            self._sessions_served += 1
            live = len(self._sessions)
        if dur:
            # the session's durable birth record: resume token handed
            # to the client, journal opened before any mutation lands
            sess.resume_token = durable.new_resume_token()
            dlog = durable.SessionLog(new_id)
            self._journal_safe(
                dlog, "log_open", name, weight, budget,
                sess.resume_token,
            )
            with self._lock:
                self._durable_logs[new_id] = dlog
        self.scheduler.register(sess)
        metrics.counter_add("serving.sessions_opened")
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_open", sess.name)
        return sess

    def _detach(self, sess: Session, clean: bool = False) -> None:
        with self._lock:
            sess.connections -= 1
            last = sess.connections <= 0
            # a durable session survives connection loss: the client
            # reconnects with its resume token (or the next daemon
            # life restores it). Only a clean bye — or server stop,
            # via the leftover sweep — ends it.
            linger = (
                last and not clean and not self._stopping
                and durable.enabled()
                and sess.resume_token is not None
            )
            if last and not linger:
                self._sessions.pop(sess.id, None)
                dlog = self._durable_logs.pop(sess.id, None)
            else:
                dlog = None
            live = len(self._sessions)
        if not last or linger:
            if linger and flight.enabled():
                flight.record("I", "serving.session_linger", sess.name)
            return
        # order matters: unregister drains the session's queued AND
        # in-flight work first, so teardown reclaims tables no executor
        # still touches (and table_reclaim's barrier covers any
        # pipelined reader beyond that)
        self.scheduler.unregister(sess)
        reclaimed = sess.teardown()
        if dlog is not None:
            if clean:
                dlog.log_bye()  # cleanly closed: erase durable state
            else:
                dlog.close()    # crash/stop: keep state for restore
        metrics.counter_add("serving.sessions_closed")
        metrics.bytes_add("serving.reclaimed_bytes", reclaimed)
        metrics.gauge_set("serving.sessions_live", live)
        if flight.enabled():
            flight.record("I", "serving.session_close", sess.name)

    # -- request dispatch -------------------------------------------------
    _DEVICE_CMDS = frozenset({"stream", "upload", "plan", "download"})
    _MUTATING_CMDS = frozenset({"upload", "plan", "free"})

    def _dispatch(self, sock, sess, cmd, header, payload) -> None:
        if cmd == "drain":
            self._cmd_drain(sock, header)
            return
        if self._draining and cmd in self._DEVICE_CMDS:
            raise durable.Draining(
                "daemon is draining for restart; no new device work"
            )
        req = header.get("req")
        if (req is not None and cmd in self._MUTATING_CMDS
                and durable.enabled()):
            # at-most-once: a request id this session already applied
            # re-sends the recorded response without re-applying — the
            # reconnect-after-crash-mid-reply path
            hit = sess.dedup_get(req)
            if hit is not None:
                metrics.counter_add("serving.idempotent_replays")
                if flight.enabled():
                    flight.record("I", "serving.replay", str(req))
                frames.send_frame(
                    sock, {"ok": True, "replayed": True, **hit}
                )
                return
        if cmd in self._DEVICE_CMDS:
            # breaker gate: an OPEN breaker sheds with typed Degraded
            # before any device work; a True return marks this request
            # as the half-open trial (the accounting below is the same
            # either way)
            self.breaker.allow()
            try:
                faults.inject("serve_accept")
                err = self._cmd_device(sock, sess, cmd, header, payload)
            except BaseException as e:
                # socket errors are peer failures, not device health:
                # a crashing client must never trip the breaker
                if not isinstance(e, (ConnectionError, OSError)):
                    self.breaker.note_failure(e)
                raise
            if err is not None:
                # _cmd_stream answered the client itself; the breaker
                # still needs to see the failure
                self.breaker.note_failure(err)
            else:
                self.breaker.note_success()
        elif cmd == "free":
            local = int(header.get("table"))
            nbytes = sess.free_table(local)
            resp = {"bytes": nbytes}
            dlog = self._dlog(sess)
            if dlog is not None:
                self._journal_safe(
                    dlog, "log_free", local, nbytes, req=req, resp=resp
                )
            if req is not None and durable.enabled():
                sess.dedup_put(req, resp, cap=durable.DEDUP_CAP)
            frames.send_frame(sock, {"ok": True, **resp})
        elif cmd == "stats":
            frames.send_frame(sock, {"ok": True, "stats": self.stats()})
        elif cmd == "trace":
            frames.send_frame(
                sock, {"ok": True, "trace": self.trace_doc()}
            )
        else:
            frames.send_frame(sock, _error_header(
                frames.ProtocolError(f"unknown command {cmd!r}")
            ))

    def _cmd_device(self, sock, sess, cmd, header, payload):
        """Route one device command. Returns the exception a handler
        answered itself (stream sends its own error frame) or None —
        the breaker accounting in :meth:`_dispatch` needs it."""
        if cmd == "stream":
            return self._cmd_stream(sock, sess, header, payload)
        if cmd == "upload":
            self._cmd_upload(sock, sess, header, payload)
        elif cmd == "plan":
            self._cmd_plan(sock, sess, header)
        else:
            self._cmd_download(sock, sess, header)
        return None

    @staticmethod
    def _plan_ops(header) -> list:
        ops = header.get("plan")
        if not isinstance(ops, list):
            raise TypeError("serving: plan must be a JSON list of ops")
        return ops

    def _request_token(self, header, sess) -> faults.CancelToken:
        """Per-request cancellation token. Deadline precedence:
        command header ``deadline_s`` > session hello ``deadline_s`` >
        SPARK_RAPIDS_TPU_DEADLINE_DEFAULT_S; 0 anywhere means none."""
        d = header.get("deadline_s")
        if d is None:
            d = sess.deadline_s or float(
                config.get_flag("DEADLINE_DEFAULT_S")
            )
        d = float(d)
        if d < 0:
            raise ValueError(
                f"serving: deadline_s must be >= 0, got {d}"
            )
        return faults.CancelToken(deadline_s=d if d > 0 else None)

    @staticmethod
    def _client_gone(sock) -> bool:
        """Liveness poll while this conn thread is busy serving: a
        readable socket whose peek returns no bytes is a closed or
        reset peer (a pipelined next command peeks non-empty and is
        NOT a disconnect)."""
        try:
            r, _, _ = select.select([sock], [], [], 0)
            if not r:
                return False
            return sock.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _cmd_stream(self, sock, sess, header, payload):
        """The main entry: one plan over N inline batches, scheduled
        per batch (so a heavy stream interleaves with other tenants),
        answered in one frame, byte-identical to ``table_plan_wire``
        / ``table_stream_wire`` run serially.

        Returns the exception it answered with, or None on success
        (breaker accounting). Every batch runs under the request's
        :class:`faults.CancelToken`; between batches the conn thread
        polls the socket, so a client that crashed mid-stream cancels
        the remaining work at its next checkpoint instead of leaving
        it running against a dead peer while holding HBM charge."""
        ops = self._plan_ops(header)
        tok = self._request_token(header, sess)
        batches = frames.batches_from_parts(
            header.get("batches") or [], payload
        )
        # pre-admission static analysis against the first batch's wire
        # schema: a plan that statically cannot run answers a typed
        # bad_request (tagged report attached) BEFORE any scheduler
        # admission, HBM charge, or upload
        if batches:
            schema = plancheck.schema_from_wire(
                batches[0][0], batches[0][1]
            )
            report = plancheck.check_plan(
                ops, schema=schema, rows=int(batches[0][4]),
            )
        else:
            schema = None
            report = plancheck.check_plan(ops)
        n = len(batches)
        sess.stats["bytes_in"] += len(payload)
        scope = profiler.profile_session(
            ops, label=f"serve:{sess.name}", batches=n,
            schema=schema, static=report,
        )
        prof = scope.__enter__()
        results = [None] * n
        window: deque = deque()

        def checkpoint():
            if self._client_gone(sock):
                tok.cancel("client disconnected mid-stream")
                metrics.counter_add("serving.cancelled")
                if flight.enabled():
                    flight.record(
                        "I", "serving.client_gone", sess.name
                    )
                raise ConnectionResetError(
                    f"session {sess.name}: client gone mid-stream"
                )
            tok.check()

        try:
            if flight.enabled():
                flight.record("I", "serving.stream", f"{sess.name}:{n}")

            man = self._manifest if durable.enabled() else None
            # mesh-backed session: offer every batch's plan to the
            # shared runner; run_plan falls back to the single-device
            # exact path on MeshUnsupported or a degraded-out mesh
            # (the keep-the-tenant guarantee — metered, typed), so
            # donation stays safe either way
            runner = self._mesh_runner(sess.mesh_devices)

            def make_work(b):
                def work():
                    type_ids, scales, datas, valids, rows = b
                    tbl = rb._table_from_wire(
                        type_ids, scales, datas, valids, rows,
                        rb._plan_pad_to(ops, rows),
                    )
                    if man is not None:
                        # warm-start manifest: the decoded (padded)
                        # table carries the exact compile signature
                        man.note(ops, [tbl], True)
                    out = plan_mod.run_plan(
                        ops, tbl, donate_input=True,
                        mesh_runner=runner,
                    )
                    return rb._table_to_wire(out)

                return work

            for i, b in enumerate(batches):
                checkpoint()
                est = estimate_request_bytes(b)
                sess.admit(est)  # typed OverBudget / queues on inflight
                try:
                    t = self.scheduler.submit(
                        sess, make_work(b), cost=b[4],
                        label="stream", charge=est, prof=prof,
                        shed=(i == 0), token=tok,
                    )
                except BaseException:
                    sess.release(est)
                    raise
                window.append((i, t))
                # keep at most queue_depth batches of THIS stream in
                # flight; draining here (in order) bounds the window
                # without ever blocking the scheduler itself
                while len(window) >= self.queue_depth:
                    j, tj = window.popleft()
                    results[j] = tj.result()
                    checkpoint()
            while window:
                j, tj = window.popleft()
                results[j] = tj.result()
                if window:
                    # more results pending: a dead peer cancels them
                    # instead of computing for nobody
                    checkpoint()
        except BaseException as e:
            # drain stragglers before answering: their results are
            # discarded but their budget charges must settle. The
            # token is cancelled first so queued batches settle
            # without running and in-flight ones abort at their next
            # between-segment checkpoint
            if not tok.cancelled:
                tok.cancel(f"stream aborted: {type(e).__name__}")
            while window:
                _, tj = window.popleft()
                with contextlib.suppress(BaseException):
                    tj.result()
            if isinstance(e, (ConnectionError, OSError)):
                raise  # peer is gone: nobody to answer
            frames.send_frame(sock, _error_header(e))
            return e
        finally:
            scope.__exit__(None, None, None)
        with metrics.span("serving.reply_serialize", session=sess.name):
            metas, buffers = frames.batches_to_parts(results)
            sess.stats["bytes_out"] += sum(len(b) for b in buffers)
            frames.send_frame(
                sock, {"ok": True, "results": metas}, buffers
            )
        return None

    def _cmd_upload(self, sock, sess, header, payload) -> None:
        batch = frames.batches_from_parts(
            [header.get("batch") or {}], payload
        )[0]
        sess.stats["bytes_in"] += len(payload)
        est = estimate_request_bytes(batch)
        sess.admit(est)
        try:
            t = self.scheduler.submit(
                sess, lambda: rb.table_upload_wire(*batch),
                cost=batch[4], label="upload", charge=est,
            )
        except BaseException:
            sess.release(est)
            raise
        rb_id = t.result()
        tbl = rb._resident_peek(rb_id)
        actual = int(hbm.table_bytes(tbl))
        local = sess.put_table(rb_id, actual)
        resp = {"table": local, "bytes": actual}
        req = header.get("req")
        dlog = self._dlog(sess)
        if dlog is not None:
            self._journal_safe(
                dlog, "log_put", local, tbl, actual, req=req, resp=resp
            )
        if req is not None and durable.enabled():
            sess.dedup_put(req, resp, cap=durable.DEDUP_CAP)
        frames.send_frame(sock, {"ok": True, **resp})

    def _cmd_plan(self, sock, sess, header) -> None:
        ops = self._plan_ops(header)
        tok = self._request_token(header, sess)
        locals_ = [int(x) for x in (header.get("tables") or [])]
        if not locals_:
            raise ValueError("serving: plan needs at least one table id")
        donate = bool(header.get("donate"))
        rb_ids = [sess.rb_id(x) for x in locals_]
        # output estimate: the chain input's resident size (already
        # charged) approximates the result; charge it as in-flight
        # until the result's actual size lands as resident
        try:
            head = rb._resident_get(rb_ids[0])
        except KeyError:
            raise sess._unknown_local_error(locals_[0])
        # pre-admission static analysis against the resident schemas: a
        # statically-invalid plan answers bad_request before admit() or
        # the scheduler queue. Rest inputs degrade to structural checks
        # when pending or missing (the runtime surfaces those exactly as
        # before).
        rest_sigs = []
        rest_tabs = []
        for rid in rb_ids[1:]:
            try:
                t = rb._resident_peek(rid)
            except KeyError:
                t = None
            resolved = (
                t is not None and not isinstance(t, pipeline.Pending)
            )
            if resolved:
                rest_tabs.append(t)
            rest_sigs.append(
                (plancheck.schema_of_table(t), int(t.logical_row_count))
                if resolved else (None, None)
            )
        plancheck.check_plan(
            ops,
            schema=plancheck.schema_of_table(head),
            rows=int(head.logical_row_count),
            rest=rest_sigs,
            names=head.names,
        )
        if (self._manifest is not None and durable.enabled()
                and len(rest_tabs) == len(rb_ids) - 1):
            # every input resolved: record the compile signature for
            # the next life's warm start
            self._manifest.note(ops, [head] + rest_tabs, donate)
        est = int(hbm.table_bytes(head))
        sess.admit(est)
        plan_json = json.dumps(ops)
        try:
            t = self.scheduler.submit(
                sess,
                lambda: rb.table_plan_resident(plan_json, rb_ids, donate),
                cost=max(est // 64, 1), label="plan", charge=est,
                token=tok,
            )
        except BaseException:
            sess.release(est)
            raise
        out_id = t.result()
        if donate:
            sess.drop_local(locals_[0])
        out = rb._resident_peek(out_id)
        dlog = self._dlog(sess)
        if dlog is not None and isinstance(out, pipeline.Pending):
            # durability needs the real table to checkpoint: resolve
            # the pipelined result now (the documented durable-on cost)
            out = rb._resident_get(out_id)
        actual = (
            est if isinstance(out, pipeline.Pending)
            else int(hbm.table_bytes(out))
        )
        local = sess.put_table(out_id, actual)
        resp = {"table": local}
        req = header.get("req")
        if dlog is not None:
            self._journal_safe(
                dlog, "log_put", local, out, actual,
                drop=locals_[0] if donate else None,
                req=req, resp=resp,
            )
        if req is not None and durable.enabled():
            sess.dedup_put(req, resp, cap=durable.DEDUP_CAP)
        frames.send_frame(sock, {"ok": True, **resp})

    def _cmd_download(self, sock, sess, header) -> None:
        rb_id = sess.rb_id(header.get("table"))
        t = self.scheduler.submit(
            sess, lambda: rb.table_download_wire(rb_id),
            cost=1, label="download",
        )
        result = t.result()
        meta, buffers = frames.batch_to_parts(result)
        sess.stats["bytes_out"] += sum(len(b) for b in buffers)
        frames.send_frame(sock, {"ok": True, "result": meta}, buffers)

    def _cmd_drain(self, sock, header) -> None:
        """Rolling restart: stop admitting (new sessions AND device
        work shed with typed ``draining``), finish in-flight work under
        the existing deadline/cancel machinery, checkpoint (every
        mutation was journaled at apply time — the drain barrier just
        guarantees nothing is mid-flight), answer, then exit. The
        optional ``deadline_s`` bounds the wait; a daemon that cannot
        drain in time answers ``drained: false`` and still exits."""
        with self._lock:
            already = self._draining
            self._draining = True
        metrics.counter_add("serving.drains")
        if flight.enabled():
            flight.record("I", "serving.drain", self.port)
        timeout = header.get("deadline_s")
        drained = self.scheduler.wait_idle(
            None if timeout is None else float(timeout)
        )
        frames.send_frame(sock, {"ok": True, "drained": bool(drained)})
        if not already:
            threading.Thread(
                target=self.stop, name="srt-serve-drain", daemon=True
            ).start()

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            sessions = [s.to_doc() for s in self._sessions.values()]
            served = self._sessions_served
            runners = list(self._mesh_runners.values())
        return {
            "port": self.port,
            "max_sessions": self.max_sessions,
            "queue_depth": self.queue_depth,
            "session_hbm_fraction": self.session_hbm_fraction,
            "sessions_live": len(sessions),
            "sessions_served": served,
            "resident_tables": rb.resident_table_count(),
            "spill": spill.stats_doc(),
            "breaker": self.breaker.to_doc(),
            "planstats": planstats.stats_doc(),
            "mesh": [r.to_doc() for r in runners],
            "durability": {
                **durable.stats_doc(),
                "draining": self._draining,
                "quarantined_sessions": len(self._quarantined),
                "restore": self._restore_doc,
            },
            "sessions": sessions,
        }

    def trace_doc(self) -> dict:
        """The live introspection plane behind the ``trace`` command:
        the tail-sampled slow-request log (slowest first, bounded to
        TRACE_TOPK, span detail only for SLO breaches / typed errors)
        plus a Prometheus-style text exposition of the metrics
        snapshot — scrape-able without restarting the daemon."""
        return {
            "slo_ms": float(config.get_flag("TRACE_SLO_MS")),
            "topk": int(config.get_flag("TRACE_TOPK")),
            "slow_requests": tracing.slow_requests(),
            "prometheus": metrics.prometheus_text(),
        }


@contextlib.contextmanager
def serve(**kwargs):
    """``with serve(...) as srv:`` — start a daemon, always stop it."""
    srv = Server(**kwargs).start()
    try:
        yield srv
    finally:
        srv.stop()
