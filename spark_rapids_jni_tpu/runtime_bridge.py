"""Wire-level dispatch for the embedded native runtime.

This module is what ``libspark_rapids_tpu.so`` imports when a native
caller (JNI bridge, C program, Spark executor) initializes the embedded
JAX runtime (src/cpp/jax_runtime.cpp). It is the TPU answer to the
reference's JNI entry points dispatching into device kernels
(RowConversionJni.cpp:24-66): host bytes come in over the C ABI, columns
are built on the XLA backend, the op runs on device, and result columns
travel back as host bytes.

The wire format mirrors the reference's dtype marshaling: parallel
(type id, scale) int arrays (RowConversionJni.cpp:56-61), little-endian
fixed-width data buffers (FLOAT64 as IEEE-754 doubles, BOOL8 as one 0/1
byte per value), and per-column 0/1 validity byte vectors. Variable-width
columns use Arrow layouts: STRING and LIST travel as int32
offsets[n+1] + concatenated payload (for LIST the scale slot carries the
child type id). The row transpose itself stays fixed-width-only — the
same gate the reference enforces at row_conversion.cu:514-516.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

# Backend selection for embedded callers: the axon TPU plugin re-appends
# itself even when JAX_PLATFORMS is set in the environment (see
# tests/conftest.py), so tests that must keep a native embedder off the
# tunneled chip set SRT_JAX_PLATFORMS and we apply it through the config
# API before the first backend touch.
if os.environ.get("SRT_JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["SRT_JAX_PLATFORMS"])

from . import dtype as dt
from . import pipeline
from .column import Column, Table
from .utils import buckets, faults, flight, lockcheck, log, metrics, profiler, spill


def _wire_np(d: dt.DType) -> np.dtype:
    """Host wire numpy dtype of a fixed-width column."""
    if not d.is_fixed_width:
        raise TypeError(f"wire format: fixed-width types only, got {d}")
    if d.id == dt.TypeId.FLOAT64:
        # device storage is the uint64 bit pattern; the wire carries
        # doubles (same bytes, different view)
        return np.dtype(np.float64)
    return np.dtype(d.storage_dtype)


def _padded_from_offsets(
    data: bytes, num_rows: int, child_np: np.dtype, label: str,
    pad_rows: Optional[int] = None,
):
    """Arrow offsets+payload wire buffer -> ((n, pad) matrix, lengths).

    Shared by the STRING and LIST branches: int32 offsets[num_rows+1]
    followed by the concatenated payload values, decoded into the
    padded-matrix device layout. Offsets are untrusted wire input and
    validated up front: a corrupt buffer with negative or non-monotonic
    offsets would otherwise yield negative lengths and a silently wrong
    row mask (``arange < lens`` is all-False for a negative length, so
    payload bytes would land in the WRONG rows without any error).

    ``pad_rows`` sizes the matrix's ROW dimension directly at the shape
    bucket: the old decode built an (n, pad) matrix and then re-padded
    it to the bucket — a second multi-MB alloc + copy per column on the
    wire hot path. Constant-width payloads (every length == pad, the
    dictionary-code/fixed-id shape) take a bulk-reshape fast path that
    skips the row mask entirely."""
    if len(data) < 4 * (num_rows + 1):
        raise ValueError(
            f"{label} wire buffer holds {len(data)} bytes, "
            f"{4 * (num_rows + 1)} needed for {num_rows + 1} offsets"
        )
    offs = np.frombuffer(data, np.int32, num_rows + 1)
    lens = np.diff(offs).astype(np.int32)
    if int(offs[0]) != 0 or (num_rows and bool((lens < 0).any())):
        raise ValueError(
            f"{label} wire offsets corrupt: must start at 0 and be "
            f"non-decreasing (first={int(offs[0])}, "
            f"min diff={int(lens.min()) if num_rows else 0})"
        )
    need = 4 * (num_rows + 1) + child_np.itemsize * int(offs[-1])
    if len(data) < need:
        raise ValueError(
            f"{label} wire buffer holds {len(data)} bytes, offsets "
            f"require {need}"
        )
    flat = np.frombuffer(
        data, child_np, count=int(offs[-1]), offset=4 * (num_rows + 1)
    )
    pad = max(int(lens.max()) if num_rows else 1, 1)
    rows = max(num_rows, pad_rows or 0)
    mat = np.zeros((rows, pad), child_np)
    if num_rows and int(offs[-1]) == num_rows * pad:
        # constant-width payload: the flat buffer IS the row-major
        # matrix — one bulk copy instead of mask build + fancy index
        mat[:num_rows] = flat.reshape(num_rows, pad)
    else:
        mask = np.arange(pad)[None, :] < lens[:, None]
        mat[:num_rows][mask] = flat
    return mat, lens


class _SerializePass:
    """Scratch state for ONE wire-serialize pass over a table.

    The STRING/LIST branch needs an ``(n, pad)`` boolean row mask per
    column; a multi-column table re-derives byte-identical ``arange``
    rows and re-allocates the mask buffer for every column of the same
    shape. One pass object caches the ``arange`` per pad width and
    reuses ONE mask buffer per ``(n, pad)`` shape (refilled in place —
    each column's mask is consumed before the next is built). Saved
    allocations are counted in ``wire.serialize.saved_bytes``."""

    __slots__ = ("_aranges", "_masks")

    def __init__(self):
        self._aranges = {}
        self._masks = {}

    def arange(self, pad: int) -> np.ndarray:
        a = self._aranges.get(pad)
        if a is None:
            a = self._aranges[pad] = np.arange(pad)
        return a

    def row_mask(self, lens: np.ndarray, pad: int) -> np.ndarray:
        buf = self._masks.get((lens.shape[0], pad))
        if buf is None:
            buf = self._masks[(lens.shape[0], pad)] = np.empty(
                (lens.shape[0], pad), np.bool_
            )
        else:
            metrics.bytes_add("wire.serialize.saved_bytes", buf.nbytes)
        np.less(self.arange(pad)[None, :], lens[:, None], out=buf)
        return buf


def _padded_to_offsets(
    mat: np.ndarray, lens: np.ndarray, ctx: Optional[_SerializePass] = None
) -> bytes:
    """(n, pad) matrix + lengths -> offsets+payload wire bytes."""
    offs = np.zeros((lens.shape[0] + 1,), np.int32)
    np.cumsum(lens, out=offs[1:])
    if lens.shape[0] and int(offs[-1]) == lens.shape[0] * mat.shape[1]:
        # constant-width rows (every length == pad): the matrix IS the
        # payload — skip the row mask + fancy gather outright. Counted
        # as saved serialize bytes: the mask buffer was never built.
        if ctx is not None:
            metrics.bytes_add(
                "wire.serialize.saved_bytes",
                lens.shape[0] * mat.shape[1],
            )
        return offs.tobytes() + mat.tobytes()
    if ctx is not None:
        mask = ctx.row_mask(lens, mat.shape[1])
    else:
        mask = np.arange(mat.shape[1])[None, :] < lens[:, None]
    # fancy indexing already yields a fresh contiguous array — no
    # ascontiguousarray copy on top
    flat = mat[mask]
    return offs.tobytes() + flat.tobytes()


def _wire_validity(valid: Optional[bytes], num_rows: int):
    if valid is None:
        return None
    return np.frombuffer(valid, np.uint8, num_rows).astype(np.bool_)


def _pad_host(arr: np.ndarray, total: Optional[int]) -> np.ndarray:
    """Zero-pad a host buffer's row dimension to ``total`` rows BEFORE
    upload — padding to the shape bucket on the host side costs no XLA
    compile and makes every upload within a bucket the same shape."""
    if total is None or arr.shape[0] == total:
        return arr
    out = np.zeros((total,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class _HostCol:
    """One wire column decoded to HOST storage buffers, not yet
    uploaded — the staging unit of the per-table batched transfer
    (``_upload_host_columns``). ``data`` is already in the DEVICE
    storage dtype (FLOAT64 carried as its uint64 bit pattern, the
    encode_storage rule) so the upload is a pure copy."""

    __slots__ = ("dtype", "data", "validity", "lengths")

    def __init__(self, dtype, data, validity=None, lengths=None):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.lengths = lengths


def _host_column_from_wire(
    type_id: int, scale: int, data: Optional[bytes],
    valid: Optional[bytes], num_rows: int,
    pad_to: Optional[int] = None,
) -> _HostCol:
    """Decode one wire column to host numpy buffers (no device touch)."""
    if metrics.enabled():
        metrics.bytes_add(
            "wire.bytes_in",
            (len(data) if data is not None else 0)
            + (len(valid) if valid is not None else 0),
        )
        metrics.counter_add("wire.columns_in")
    if dt.TypeId(type_id) == dt.TypeId.LIST:
        # LIST wire convention: the scale slot carries the CHILD type id
        # (scale is meaningless for LIST); payload per _padded_from_offsets.
        child = dt.DType(dt.TypeId(scale))
        mat, lens = _padded_from_offsets(
            data, num_rows, np.dtype(child.storage_dtype), "LIST",
            pad_rows=pad_to,
        )
        v = _wire_validity(valid, num_rows)
        return _HostCol(
            dt.DType(dt.TypeId.LIST),
            mat,
            None if v is None else _pad_host(v, pad_to),
            _pad_host(lens, pad_to),
        )
    if dt.TypeId(type_id) == dt.TypeId.STRING:
        # STRING wire convention (the Arrow string layout cudf's JNI
        # marshals): offsets + concatenated UTF-8 bytes.
        mat, lens = _padded_from_offsets(
            data, num_rows, np.dtype(np.uint8), "STRING", pad_rows=pad_to,
        )
        v = _wire_validity(valid, num_rows)
        return _HostCol(
            dt.STRING,
            mat,
            None if v is None else _pad_host(v, pad_to),
            _pad_host(lens, pad_to),
        )
    d = dt.DType(dt.TypeId(type_id), scale)
    if d.id == dt.TypeId.DECIMAL128:
        # 16 little-endian bytes/value on the wire -> (n, 2) u64 limbs
        arr = np.frombuffer(
            data, dtype=np.uint64, count=2 * num_rows
        ).reshape(num_rows, 2)
    else:
        arr = np.frombuffer(data, dtype=_wire_np(d), count=num_rows)
    v = (
        None
        if valid is None
        else np.frombuffer(valid, dtype=np.uint8, count=num_rows).astype(
            np.bool_
        )
    )
    arr = _pad_host(arr, pad_to)
    # the one FLOAT64 bit-view rule, shared with encode_storage
    from .column import storage_host_view

    arr = storage_host_view(arr, d)
    return _HostCol(d, arr, None if v is None else _pad_host(v, pad_to))


def _upload_host_columns(hcols: Sequence[_HostCol]) -> list:
    """Upload a whole table's host buffers in ONE batched transfer.

    ``jax.device_put`` on the flat leaf list dispatches every buffer
    together (the reference uploads a ColumnarBatch as one contiguous
    HtoD copy, not one cudaMemcpy per column); the per-column path cost
    one transfer per data/validity/lengths buffer. Transfers saved by
    batching are counted in ``wire.upload.batched``."""
    import jax

    leaves = []
    for h in hcols:
        leaves.append(h.data)
        if h.validity is not None:
            leaves.append(h.validity)
        if h.lengths is not None:
            leaves.append(h.lengths)
    dev = jax.device_put(leaves) if leaves else []
    if metrics.enabled() and len(leaves) > 1:
        metrics.counter_add("wire.upload.batched", len(leaves) - 1)
    it = iter(dev)
    cols = []
    for h in hcols:
        d = next(it)
        if d.dtype != h.data.dtype:
            # x64 disabled: a silent int64->int32 downgrade would
            # corrupt values AND misreport the type id on download
            # (the shared encode_storage guard, batched-upload flavor)
            from .column import x64_downgrade_error

            raise x64_downgrade_error(
                d.dtype, h.data.dtype,
                "LIST children" if h.dtype.id == dt.TypeId.LIST
                else "types",
            )
        v = next(it) if h.validity is not None else None
        lens = next(it) if h.lengths is not None else None
        cols.append(Column(d, h.dtype, v, lens))
    return cols


def _column_from_wire(
    type_id: int, scale: int, data: Optional[bytes],
    valid: Optional[bytes], num_rows: int,
    pad_to: Optional[int] = None,
) -> Column:
    return _upload_host_columns(
        [_host_column_from_wire(type_id, scale, data, valid, num_rows,
                                pad_to)]
    )[0]


def _column_to_wire(
    c: Column, rows: Optional[int] = None,
    ctx: Optional[_SerializePass] = None,
):
    """(type_id, scale, data bytes, valid bytes | None).

    LIST columns use the convention documented in _column_from_wire:
    scale = child type id, data = int32 offsets then child values.

    ``rows`` slices a shape-bucket-padded column back to its logical
    row count on the HOST side (after the device fetch) — the padding
    never reaches the wire and the slice costs no XLA compile.
    ``ctx`` is the per-serialize-pass scratch (mask-buffer reuse).
    """
    out = _column_to_wire_impl(c, rows, ctx)
    if metrics.enabled():
        metrics.bytes_add(
            "wire.bytes_out",
            len(out[2]) + (len(out[3]) if out[3] is not None else 0),
        )
        metrics.counter_add("wire.columns_out")
    return out


def _host_rows(arr: np.ndarray, rows: Optional[int]) -> np.ndarray:
    return arr if rows is None else arr[:rows]


def _column_to_wire_impl(
    c: Column, rows: Optional[int] = None,
    ctx: Optional[_SerializePass] = None,
):
    if c.dtype.id == dt.TypeId.STRING:
        valid = (
            None
            if c.validity is None
            else _host_rows(np.asarray(c.validity), rows)
            .astype(np.uint8).tobytes()
        )
        return (
            int(dt.TypeId.STRING),
            0,
            _padded_to_offsets(
                _host_rows(np.asarray(c.data), rows),
                _host_rows(np.asarray(c.lengths), rows).astype(np.int32),
                ctx,
            ),
            valid,
        )
    if c.dtype.id == dt.TypeId.LIST:
        child = c.list_child_dtype
        valid = (
            None
            if c.validity is None
            else _host_rows(np.asarray(c.validity), rows)
            .astype(np.uint8).tobytes()
        )
        return (
            int(dt.TypeId.LIST),
            int(child.id),
            _padded_to_offsets(
                _host_rows(np.asarray(c.data), rows),
                _host_rows(np.asarray(c.lengths), rows).astype(np.int32),
                ctx,
            ),
            valid,
        )
    # tobytes() emits C-order bytes from any layout in one copy — an
    # ascontiguousarray on top would only add a second copy for
    # non-contiguous slices
    host = _host_rows(np.asarray(c.data), rows)
    valid = (
        None
        if c.validity is None
        else _host_rows(np.asarray(c.validity), rows)
        .astype(np.uint8).tobytes()
    )
    return (
        int(c.dtype.id.value),
        int(c.dtype.scale),
        host.tobytes(),
        valid,
    )


def _dispatch(op: dict, table: Table, rest: Sequence[Table] = ()) -> Table:
    """Run one op on device; returns the result Table.

    ``rest`` carries additional input tables for multi-table ops
    (``join`` takes the probe side as ``table`` and the build side as
    ``rest[0]``; ``concat`` appends every table in ``rest``).

    With shape bucketing on (the default; ``SPARK_RAPIDS_TPU_BUCKETS``),
    bucketable ops run through ``bucketed.dispatch_bucketed``: inputs
    padded to row-count buckets, one compiled executable per
    ``(op, schema, bucket)`` from the central cache, results padded with
    ``Table.logical_rows`` carrying the real count. Non-bucketable ops
    (and the ``=off`` debug mode) take the exact-shape path — padded
    inputs are unpadded first so exact ops never see garbage tails.

    Every op runs inside a ``metrics.span`` and feeds the per-op
    call/row counters — the ``GpuMetric`` plane of the dispatch layer.
    The disabled path costs one string concat and the span's cheap
    gate checks. Row counters count LOGICAL rows (padding is an
    implementation detail; its cost shows up in ``bucket.*`` instead).

    This is also a fault boundary (utils/faults.py): the ``dispatch``
    injection site is armed here, transient-classified failures retry
    with backoff (safe: nothing on this path donates its inputs — the
    consumed single-op flavor is ``dispatch_bucketed_donated``, gated
    by its caller), and permanent-classified errors surface unchanged.
    """
    name = op["op"]

    def attempt():
        faults.inject("dispatch")
        return _dispatch_once(op, table, rest, name)

    return faults.run_with_retry(attempt, "dispatch." + name)


def _dispatch_once(
    op: dict, table: Table, rest: Sequence[Table], name: str
) -> Table:
    # a tracked lock held across a device launch serializes every other
    # dispatcher behind the chip — the lockcheck shim reports it
    lockcheck.note_blocking("device_dispatch")
    with metrics.span("dispatch." + name):
        # the kernel tier (kernels/registry.py) is consulted FIRST:
        # hand-written Pallas runners under SPARK_RAPIDS_TPU_KERNELS,
        # byte-identical over the logical rows, declining/falling back
        # to the bucketed/exact chain below. The flag-off path is one
        # generation check (<5 µs contract, test_kernel_tier.py).
        from .kernels import registry as kernel_registry

        out = kernel_registry.dispatch_kernel(op, table, rest, name)
        if out is None and buckets.enabled():
            from . import bucketed

            out = bucketed.dispatch_bucketed(op, table, rest, name)
        if out is None:
            out = _dispatch_impl(
                op,
                buckets.unpad_table(table),
                [buckets.unpad_table(t) for t in rest],
                name,
            )
    if metrics.enabled():
        rows_in = int(table.logical_row_count) + sum(
            int(t.logical_row_count) for t in rest
        )
        metrics.counter_add("op." + name + ".calls")
        metrics.counter_add("op." + name + ".rows_in", rows_in)
        metrics.counter_add(
            "op." + name + ".rows_out", int(out.logical_row_count)
        )
        metrics.hist_observe("dispatch.rows_in", rows_in)
    return out


def _dispatch_impl(
    op: dict, table: Table, rest: Sequence[Table], name: str
) -> Table:
    import jax.numpy as jnp

    from . import ops
    from . import rows as rows_mod

    if name == "join":
        how = op.get("how", "inner")
        fn = {
            "inner": ops.inner_join,
            "left": ops.left_join,
            "right": ops.right_join,
            "full": ops.full_join,
            "semi": ops.semi_join,
            "anti": ops.anti_join,
        }.get(how)
        if fn is None:
            raise ValueError(f"unknown join how={how!r}")
        if not rest:
            raise ValueError("join needs two input tables")
        return fn(table, rest[0], op["on"])
    if name == "concat":
        return ops.concatenate([table, *rest])
    if name == "groupby":
        from .ops.groupby import GroupbyAgg

        aggs = [GroupbyAgg(a["column"], a["agg"]) for a in op["aggs"]]
        return ops.groupby_aggregate(table, op["by"], aggs)
    if name == "sort_by":
        keys = [
            ops.SortKey(k["column"], ascending=k.get("ascending", True))
            for k in op["keys"]
        ]
        return ops.sort_table(table, keys)
    if name == "filter":
        mask_idx = op["mask"]
        mask = table.columns[mask_idx]
        keep = [
            c for i, c in enumerate(table.columns) if i != mask_idx
        ]
        return ops.filter_table(Table(keep), mask)
    if name == "distinct":
        return ops.distinct(table, op.get("keys"))
    if name == "cast":
        target = dt.DType(dt.TypeId(op["type_id"]), op.get("scale", 0))
        out = list(table.columns)
        src = table.columns[op["column"]]
        if src.dtype.is_string or target.is_string:
            from .ops import strings as strings_mod

            out[op["column"]] = strings_mod.cast(src, target)
        else:
            out[op["column"]] = ops.cast(src, target)
        return Table(out, table.names)
    if name == "explode":
        return ops.explode(table, op["column"])
    if name == "rlike":
        # filter rows whose string column matches the pattern (the
        # Spark `WHERE col RLIKE pat` scan shape)
        from .ops import regex as regex_mod

        mask = regex_mod.contains_re(
            table.columns[op["column"]], op["pattern"]
        )
        return ops.filter_table(table, mask)
    if name == "cross_join":
        if not rest:
            raise ValueError("cross_join needs two input tables")
        return ops.cross_join(table, rest[0])
    if name == "slice":
        n = table.row_count
        start = int(op.get("start", 0))
        stop = int(op.get("stop", n))
        if start < 0 or stop < 0:
            raise ValueError(
                f"slice: negative bounds not supported (start={start}, "
                f"stop={stop})"
            )
        start = min(start, n)
        stop = max(start, min(stop, n))
        return ops.slice_rows(table, start, stop)
    if name == "repeat":
        return ops.repeat(table, int(op["count"]))
    if name == "sample":
        return ops.sample(
            table, int(op["n"]), seed=int(op.get("seed", 0)),
            replacement=bool(op.get("replacement", False)),
        )
    if name == "partition":
        # Spark's ShuffleExchangeExec partitioning step as a table op:
        # rows reordered partition-contiguously by Pmod(Murmur3, num)
        # (hash) or sampled key-range splitters (range). The exchange
        # itself is the mesh path's job (planmesh); on the exact path
        # the stable reorder IS the observable result, which is what
        # the mesh path must match byte-for-byte after its all-to-all.
        from .ops import partition as partition_mod

        kind = op.get("kind", "hash")
        num = int(op["num"])
        if num < 1:
            raise ValueError(f"partition: num must be >= 1, got {num}")
        keys = list(op.get("keys", []))
        if kind == "hash":
            out, _ = partition_mod.hash_partition(table, keys or None, num)
        elif kind == "range":
            if not keys:
                raise ValueError("partition: range kind needs keys")
            out, _ = partition_mod.range_partition(table, keys, num)
        else:
            raise ValueError(f"unknown partition kind {kind!r}")
        if metrics.enabled():
            metrics.counter_add("partition.exact")
        return out
    if name == "to_rows":
        # device row transpose; result = a true LIST<UINT8> column (the
        # reference's output type, row_conversion.cu:389-406)
        return Table([rows_mod.to_rows_list(table)])
    if name == "from_rows":
        schema = [
            dt.DType(dt.TypeId(t), s)
            for t, s in zip(op["type_ids"], op["scales"])
        ]
        src = table.columns[0]
        if src.dtype.id == dt.TypeId.LIST:
            return rows_mod.from_rows_list(src, schema)
        # legacy flat-UINT8 input: one column of num_rows*row_size bytes
        layout = rows_mod.compute_fixed_width_layout(schema)
        n = int(op["num_rows"])
        raw = np.asarray(src.data).reshape(n, layout.row_size)
        pr = rows_mod.PackedRows(jnp.asarray(raw), layout)
        return rows_mod.from_rows(pr, schema)
    raise ValueError(f"unknown table op {name!r}")


# Every op key the dispatch chain above accepts. This literal is the
# dispatch-plane side of the SRT008 registry-parity pair: srt_check
# verifies (statically) that it matches both the ``name == "..."`` arms
# of _dispatch_impl and plancheck's inference-rule table, so an op added
# to one registry without the others fails CI before it can ship.
DISPATCH_OPS = frozenset(
    {
        "join",
        "concat",
        "groupby",
        "sort_by",
        "filter",
        "distinct",
        "cast",
        "explode",
        "rlike",
        "cross_join",
        "slice",
        "repeat",
        "sample",
        "partition",
        "to_rows",
        "from_rows",
    }
)


def _table_from_wire(
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
    pad_to: Optional[int],
) -> Table:
    """One wire-deserialize pass -> a (possibly host-padded) Table.
    Host decode per column, then the whole table's buffers cross to the
    device as ONE batched ``jax.device_put`` pytree transfer. A wire
    decode is pure (the caller's bytes are never consumed), so the
    ``serde`` fault site retries transient failures here freely."""

    def attempt():
        faults.inject("serde")
        return _table_from_wire_impl(
            type_ids, scales, datas, valids, num_rows, pad_to
        )

    return faults.run_with_retry(attempt, "wire.in")


def _table_from_wire_impl(
    type_ids, scales, datas, valids, num_rows, pad_to
) -> Table:
    prof = profiler.session_active()
    nbytes = (
        sum(len(d) for d in datas if d is not None)
        if (prof or flight.enabled()) else 0
    )
    if flight.enabled():
        flight.record("I", "wire.in", nbytes)
    t0 = _time.perf_counter() if prof else 0.0
    with metrics.span("wire.deserialize"):
        cols = _upload_host_columns([
            _host_column_from_wire(t, s, d, v, num_rows, pad_to=pad_to)
            for t, s, d, v in zip(type_ids, scales, datas, valids)
        ])
    if prof:
        profiler.note_serde("in", _time.perf_counter() - t0, nbytes)
    tbl = Table(cols, logical_rows=num_rows if pad_to is not None else None)
    if pad_to is not None:
        buckets.note_padded(tbl)
    return tbl


def _table_to_wire(t: Table):
    """One wire-serialize pass -> the 5-tuple every wire entry returns
    (shape-bucket padding sliced away host-side; one shared
    ``_SerializePass`` scratch across the table's columns). Pure reads
    of device buffers, so the ``serde`` fault site retries here too."""

    def attempt():
        faults.inject("serde")
        return _table_to_wire_impl(t)

    return faults.run_with_retry(attempt, "wire.out")


def _table_to_wire_impl(t: Table):
    out_t, out_s, out_d, out_v = [], [], [], []
    ctx = _SerializePass()
    prof = profiler.session_active()
    t0 = _time.perf_counter() if prof else 0.0
    with metrics.span("wire.serialize"):
        for c in t.columns:
            ti, s, d, v = _column_to_wire(c, t.logical_rows, ctx)
            out_t.append(ti)
            out_s.append(s)
            out_d.append(d)
            out_v.append(v)
    if prof or flight.enabled():
        nbytes = sum(len(d) for d in out_d if d is not None)
        if flight.enabled():
            flight.record("I", "wire.out", nbytes)
        if prof:
            profiler.note_serde(
                "out", _time.perf_counter() - t0, nbytes
            )
    return out_t, out_s, out_d, out_v, int(t.logical_row_count)


def table_op_wire(
    op_json: str,
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
):
    """C-ABI entry: bytes in, bytes out.

    Returns (out_type_ids, out_scales, out_datas, out_valids, out_rows).
    """
    op = json.loads(op_json)
    pad_to = None
    if buckets.enabled():
        from . import bucketed

        # pad only when the op can actually take the bucketed path —
        # a non-bucketable op would pay the padded upload AND a device
        # unpad slice for nothing
        if bucketed.is_bucketable(op):
            pad_to = buckets.bucket_for(num_rows)
    tbl = _table_from_wire(
        type_ids, scales, datas, valids, num_rows, pad_to
    )
    result = _dispatch(op, tbl)
    return _table_to_wire(result)


def _plan_pad_to(ops, num_rows: int) -> Optional[int]:
    """Host-side pad target for a plan's wire upload: pad only when the
    FIRST segment can consume the padding (a fused segment, or a 1-op
    segment with a bucketed runner) — the table_op_wire gate applied at
    segment granularity, so a plan opening with e.g. a lone slice
    doesn't pay a padded upload just to unpad on the exact path;
    malformed entries fall through to run_plan's loud validation."""
    from . import bucketed, plan as plan_mod

    if not (buckets.enabled() and ops and isinstance(ops[0], dict)):
        return None
    segs = plan_mod.segment_plan(ops)
    if segs and (
        segs[0][0] == "fused" or bucketed.is_bucketable(segs[0][1][0])
    ):
        return buckets.bucket_for(num_rows)
    return None


def table_plan_wire(
    plan_json: str,
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
):
    """C-ABI plan entry: ``plan_json`` is a JSON LIST of ops executed
    as a fused plan (plan.py) over ONE wire table — upload once, every
    fusable run costs one executable launch, download once. Returns the
    same 5-tuple as ``table_op_wire``. The uploaded table is consumed
    by construction (nothing else holds a wire table), so the first
    fused segment donates its buffers — the chain updates HBM in place
    instead of doubling peak (``hbm.donated_bytes``)."""
    from . import plan as plan_mod

    ops = json.loads(plan_json)
    if not isinstance(ops, list):
        raise TypeError("table_plan_wire: plan must be a JSON list of ops")
    # static analysis BEFORE the upload: a plan that cannot run costs
    # zero wire bytes, zero compiles (plancheck.PlanCheckError names the
    # op index + reason and subclasses ValueError)
    from . import plancheck

    schema = plancheck.schema_from_wire(type_ids, scales)
    report = plancheck.check_plan(ops, schema=schema, rows=int(num_rows))
    pad_to = _plan_pad_to(ops, num_rows)
    with profiler.maybe_session(
        ops, label="plan_wire", schema=schema, bucket=pad_to,
        static=report,
    ):
        tbl = _table_from_wire(
            type_ids, scales, datas, valids, num_rows, pad_to,
        )
        result = plan_mod.run_plan(ops, tbl, donate_input=True)
        return _table_to_wire(result)


def table_stream_wire(plan_json: str, batches: Sequence) -> list:
    """Streaming C-ABI entry: drive a whole plan-per-batch stream
    through the pipelined dispatch plane from ONE call.

    ``batches`` is a sequence of ``(type_ids, scales, datas, valids,
    num_rows)`` wire tuples; each runs the same ``plan_json`` op list
    and the returned list carries one ``table_op_wire``-shaped 5-tuple
    per batch, in input order. With ``SPARK_RAPIDS_TPU_PIPELINE`` on,
    batch N+1's wire decode and batch N-1's wire encode run on
    background workers while batch N's fused-plan executable runs on
    the calling thread (pipeline.run_stream); with the pipeline off
    this is exactly a loop of ``table_plan_wire`` — byte-identical
    results and error surfacing either way. Each batch's decoded table
    is consumed by its plan run, so fused chains donate
    (``hbm.donated_bytes``)."""
    from . import plan as plan_mod

    ops = json.loads(plan_json)
    if not isinstance(ops, list):
        raise TypeError(
            "table_stream_wire: plan must be a JSON list of ops"
        )
    # static analysis against the first batch's wire schema before any
    # batch decodes or the pipeline spins up; an empty stream still gets
    # the structural walk
    from . import plancheck

    batches = list(batches)
    schema = None
    bucket = None
    if batches:
        first = batches[0]
        schema = plancheck.schema_from_wire(first[0], first[1])
        report = plancheck.check_plan(
            ops, schema=schema, rows=int(first[4]),
        )
        bucket = _plan_pad_to(ops, int(first[4]))
    else:
        report = plancheck.check_plan(ops)

    def decode(batch):
        type_ids, scales, datas, valids, num_rows = batch
        return _table_from_wire(
            type_ids, scales, datas, valids, num_rows,
            _plan_pad_to(ops, num_rows),
        )

    def compute(tbl):
        return plan_mod.run_plan(ops, tbl, donate_input=True)

    with profiler.maybe_session(
        ops, label="stream", batches=len(batches), schema=schema,
        bucket=bucket, static=report,
    ):
        with metrics.span(
            "stream", batches=len(batches), depth=pipeline.depth()
        ):
            return pipeline.run_stream(
                batches, decode, compute, _table_to_wire
            )


def platform() -> str:
    """Active XLA backend platform name."""
    import jax

    return jax.devices()[0].platform


# ---------------------------------------------------------------------------
# Device-resident table handles (round-3 VERDICT item 4)
#
# The reference passes jlong pointers to DEVICE-resident cudf tables
# between JNI calls with no host copy in between
# (RowConversionJni.cpp:31,54). The wire path above copies host->device
# per op; these functions give native callers the same chaining
# capability: a table id maps to a Table whose buffers stay on the XLA
# backend, ops consume and produce ids, and bytes only cross the
# boundary at upload/download.
# ---------------------------------------------------------------------------

import atexit
import itertools
import threading
import time as _time

_RESIDENT: dict = {}
# table id -> allocation provenance (span stack, rows, timestamp): what
# the exit-time leak report prints for every handle still live — the
# RMM leak report's "where was this allocated" role. Populated only
# when a telemetry plane is on (metrics/flight/REFCOUNT_DEBUG), so the
# shipped-disabled path stays two dict ops.
_RESIDENT_META: dict = {}
# Lock + atomic counter: Spark executors call through the JNI bridge
# from many threads (the GilGuard path), and the GIL can switch between
# a read-increment pair — an unsynchronized counter could hand two
# threads the same table id. RLock because the SIGTERM-handler flush
# path reaches leak_report() (a flight-dump exit section) on the main
# thread and must not self-deadlock mid-_resident_put. Tracked: rank 0
# of the sanctioned registry->session->scheduler->spill order.
_RESIDENT_LOCK = lockcheck.make_rlock("registry.resident")
_NEXT_TABLE_ID = itertools.count(1)


def _provenance_on() -> bool:
    from .utils import config

    return (
        metrics.enabled()
        or flight.enabled()
        or bool(config.get_flag("REFCOUNT_DEBUG"))
    )


def _unknown_id_error(table_id, live: int) -> KeyError:
    """The labeled miss every resident entry raises: names the id AND
    the live count so a use-after-free reads as one (a bare dict miss
    cost a round-6 debugging session distinguishing "never uploaded"
    from "double freed")."""
    return KeyError(
        f"unknown or already-freed device table id {int(table_id)} "
        f"({live} table(s) live)"
    )


def _resident_peek(table_id: int):
    """Registry entry for ``table_id`` WITHOUT resolving a pending: a
    Table, or a ``pipeline.Pending`` still computing. A SPILLED entry
    (utils/spill.py) transparently repages back to the device here —
    access is what promotes a cold table. Raises the labeled KeyError
    on a miss."""
    with _RESIDENT_LOCK:
        t = _RESIDENT.get(int(table_id))
        live = len(_RESIDENT)
        if isinstance(t, spill.SpilledTable):
            t = spill.repage_locked(int(table_id))
    if t is None:
        raise _unknown_id_error(table_id, live)
    spill.flush_events()
    spill.touch(int(table_id))
    return t


def _resident_get(table_id: int) -> Table:
    """Resolved Table for ``table_id`` — THE blocking point of the
    pipelined plane: a pending entry is waited for here, with any
    worker error replayed synchronously so the originating op's own
    exception surfaces (pipeline.Pending.resolve)."""
    t = _resident_peek(table_id)
    if isinstance(t, pipeline.Pending):
        t = t.resolve()
        with _RESIDENT_LOCK:
            # swap the settled Table in so later gets skip the handle
            # (unless the id was freed while we waited)
            if int(table_id) in _RESIDENT:
                _RESIDENT[int(table_id)] = t
        spill.note_put(int(table_id), t)
    metrics.counter_add("resident.get")
    return t


def _resident_put(t) -> int:
    """Register a Table (or a ``pipeline.Pending`` still computing it)
    and return its id. Pending entries count as live — backpressure and
    the leak report both see in-flight results."""
    tid = next(_NEXT_TABLE_ID)
    is_pending = isinstance(t, pipeline.Pending)
    rows = None if is_pending else int(t.logical_row_count)
    meta = None
    if _provenance_on():
        meta = {
            "rows": rows,
            "columns": None if is_pending else len(t.columns),
            "allocated_under": list(metrics.span_stack()),
            "age_anchor_ns": _time.perf_counter_ns(),
        }
        if is_pending:
            meta["pending"] = t.label
        sid = profiler.current_session_id()
        if sid is not None:
            # which profiled plan run allocated this table: the leak
            # report names the session, the session report the leak
            meta["session"] = sid
    with _RESIDENT_LOCK:
        _RESIDENT[tid] = t
        if meta is not None:
            _RESIDENT_META[tid] = meta
        live = len(_RESIDENT)
    log.log("DEBUG", "handles", "resident_put", table_id=tid,
            rows=rows, live=live)
    # resident.live's high-water mark is the leak-report analog: a chain
    # that frees what it allocates returns to the pre-chain value while
    # high_water records the peak resident set
    metrics.counter_add("resident.put")
    metrics.gauge_set("resident.live", live)
    if flight.enabled():
        flight.record("C", "resident.live", live)
    if not is_pending:
        # spill tracking + proactive pressure: a put that carries the
        # device tier past the HBM budget evicts the coldest entries
        spill.note_put(tid, t)
    return tid


def table_upload_wire(
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
) -> int:
    """Host bytes -> device-resident table; returns its id. With shape
    bucketing on, the resident buffers are padded to the row-count
    bucket (host-side, before upload) and the table carries its logical
    row count — a chain of bucketed ops then reuses one compiled
    executable per bucket with no repadding."""
    pad_to = buckets.bucket_for(num_rows) if buckets.enabled() else None
    return _resident_put(
        _table_from_wire(type_ids, scales, datas, valids, num_rows, pad_to)
    )


# table id -> in-flight pipelined ops READING that id (pruned as they
# settle). A donate-consume of an id must terminally settle these
# before its executable deletes the buffers: without the barrier,
# op1=[A] then op2=[A, donate] on two workers could delete A's device
# arrays out from under op1's running dispatch (or its later replay) —
# an error the synchronous ordering (op1 completes before op2 starts)
# can never produce.
_RESIDENT_READERS: dict = {}


def _capture_inputs(
    table_ids: Sequence[int], donate: bool, reader=None,
    pin: bool = False,
) -> tuple:
    """Atomically snapshot the input entries at CALL time (Tables or
    Pendings) -> ``(inputs, donate_barrier)``.

    The capture is what makes the async chain pattern safe: a caller
    may ``table_free`` an input right after enqueueing the op that
    consumes it — the op holds its own reference, exactly as if it had
    completed before the free (the synchronous ordering). Unknown ids
    raise the labeled KeyError synchronously (all ids validated BEFORE
    the donated input is consumed, so a bad rest id leaves it intact).

    One lock acquisition covers validation, the donate-consume, the
    barrier snapshot AND registering ``reader`` (the op's own not-yet-
    enqueued Pending) against the ids it captured: a concurrent
    donate-consume of the same id therefore either sees this reader in
    its barrier or ordered itself first (in which case THIS capture
    fails with the labeled KeyError) — there is no window where a
    reader runs unprotected.

    Spilled inputs repage inside the same lock hold, so the captured
    objects are always device Tables (or Pendings). ``pin=True``
    additionally pins the non-donated ids against eviction atomically
    with the capture — the SYNCHRONOUS dispatch paths use it (no
    reader Pending exists there to make the eviction check see them);
    the caller must ``spill.unpin_ids`` the same ids when done."""
    ids = [int(t) for t in table_ids]
    took = False
    with _RESIDENT_LOCK:
        live = len(_RESIDENT)
        for t in ids:
            if t not in _RESIDENT:
                raise _unknown_id_error(t, live)
        objs = []
        for t in ids:
            o = _RESIDENT[t]
            if isinstance(o, spill.SpilledTable):
                o = spill.repage_locked(t)
            objs.append(o)
        barrier = []
        if donate:
            _RESIDENT.pop(ids[0])
            _RESIDENT_META.pop(ids[0], None)
            spill.note_free(ids[0])
            barrier = [
                p for p in _RESIDENT_READERS.pop(ids[0], ())
                if not p.done()
            ]
            live = len(_RESIDENT)
            took = True
        if reader is not None:
            for t in (ids[1:] if donate else ids):
                lst = _RESIDENT_READERS.setdefault(t, [])
                lst[:] = [p for p in lst if not p.done()]
                lst.append(reader)
        if pin:
            spill.pin_ids(ids[1:] if donate else ids)
        for t in (ids[1:] if donate else ids):
            spill.touch(t)
    spill.flush_events()
    metrics.counter_add("resident.get", len(ids))
    if took:
        log.log("DEBUG", "handles", "resident_take", table_id=ids[0],
                live=live)
        metrics.counter_add("resident.free")
        metrics.gauge_set("resident.live", live)
        if flight.enabled():
            flight.record("C", "resident.live", live)
    return objs, barrier


def _run_resident_op(
    op: dict, inputs: list, donate: bool, name: str, barrier=(),
):
    """The shared (sync or worker-side) body of ``table_op_resident``:
    resolve pending inputs, dispatch — through the donated single-op
    executable when the input was consumed — and return the result.
    ``barrier`` holds still-running readers of the donated input; they
    must be terminally settled (later replays included) before the
    donated executable may delete its buffers."""
    tables = pipeline.materialize_inputs(inputs)
    out = None
    if donate:
        from . import bucketed

        for p in barrier:
            p.settle_terminally()
        out = bucketed.dispatch_bucketed_donated(op, tables[0], name)
    if out is None:
        out = _dispatch(op, tables[0], tables[1:])
    return out


def table_op_resident(
    op_json: str, table_ids: Sequence[int], donate: bool = False
) -> int:
    """Run one op over resident tables; the result STAYS resident.

    No host transfer happens here — chaining filter -> join -> groupby
    costs upload + download once, not per op.

    ``donate=True`` declares ``table_ids[0]`` CONSUMED: the id is freed
    now (equivalent to op + table_free, but the op may then donate the
    input's HBM buffers to its executable and update them in place —
    ``hbm.donated_bytes``). The caller must not use the id again.

    With ``SPARK_RAPIDS_TPU_PIPELINE`` on this enqueues and returns the
    result id immediately; ``table_download_wire``/``table_num_rows``
    are the blocking points, and any worker error is replayed
    synchronously there so the op's own exception surfaces unchanged.
    """
    if not table_ids:
        raise ValueError("table_op_resident needs at least one input")
    op = json.loads(op_json)
    name = str(op.get("op", "?")) if isinstance(op, dict) else "?"
    if pipeline.enabled():
        # donated work is at-most-once once its own dispatch starts
        # (the input may be consumed by a partial run): the worker's
        # post-consumption error is authoritative; input-materialize
        # failures stay replayable (pipeline.DependencyFailed). The
        # Pending is built FIRST so _capture_inputs can register it as
        # a reader atomically with the capture; the captured state
        # lands in `cell` before the enqueue makes the work runnable.
        cell: dict = {}

        def work():
            return _run_resident_op(
                op, cell["inputs"], donate, name, cell["barrier"]
            )

        pending = pipeline.Pending(
            work, "op." + name, replayable=not donate
        )
        cell["inputs"], cell["barrier"] = _capture_inputs(
            table_ids, donate, reader=pending
        )
        return _resident_put(pipeline.enqueue(pending))
    # synchronous path: pin the surviving inputs for the dispatch (no
    # reader Pending exists here for the eviction check to see)
    inputs, barrier = _capture_inputs(table_ids, donate, pin=True)
    try:
        return _resident_put(_run_resident_op(op, inputs, donate, name,
                                              barrier))
    finally:
        spill.unpin_ids(table_ids[1:] if donate else table_ids)


def _static_check_resident_plan(ops, table_ids: Sequence[int]):
    """Plan-time analysis for the resident entry: schemas come from the
    registry (a peek — no Pending resolution, so an in-flight input
    degrades the walk to structural validation instead of blocking the
    enqueue). Raises plancheck.PlanCheckError before any input capture,
    pin, or pipeline enqueue. Returns ``(report, head_schema)`` so the
    caller can key the profile session's plan-stats record."""
    from . import plancheck

    def settled(tid):
        t = _resident_peek(int(tid))
        return None if isinstance(t, pipeline.Pending) else t

    head = settled(table_ids[0])
    rest = []
    for tid in table_ids[1:]:
        t = settled(tid)
        rest.append(
            (plancheck.schema_of_table(t), int(t.logical_row_count))
            if t is not None
            else (None, None)
        )
    head_schema = (
        plancheck.schema_of_table(head) if head is not None else None
    )
    report = plancheck.check_plan(
        ops,
        schema=head_schema,
        rows=int(head.logical_row_count) if head is not None else None,
        rest=rest,
        names=head.names if head is not None else None,
    )
    return report, head_schema


def table_plan_resident(
    plan_json: str, table_ids: Sequence[int], donate: bool = False
) -> int:
    """Run a whole PLAN (a JSON list of ops) over resident tables; the
    result stays resident. ``table_ids[0]`` is the chain input; the
    remaining ids feed multi-table segment-boundary ops (join/concat —
    explicit ``"rest"`` indices into this list, or sequential
    consumption; see plan._take_rest). Fusable runs execute as ONE
    cached executable each (plan.py), so an N-op chain costs one
    launch per segment instead of N dispatches.

    ``donate=True`` consumes ``table_ids[0]`` (freed now) and lets the
    plan's first fused segment donate its buffers; later segments
    always donate their plan-owned intermediates. Enqueues and returns
    immediately when the pipeline is on (see ``table_op_resident``)."""
    if not table_ids:
        raise ValueError("table_plan_resident needs at least one input")
    from . import plan as plan_mod

    ops = json.loads(plan_json)
    if not isinstance(ops, list):
        raise TypeError(
            "table_plan_resident: plan must be a JSON list of ops"
        )
    report, head_schema = _static_check_resident_plan(ops, table_ids)
    cell: dict = {}

    def work():
        # the session opens INSIDE the work closure so it scopes the
        # actual execution — on a pipeline worker when enqueued, on the
        # caller when synchronous — not the enqueue-and-return
        with profiler.maybe_session(
            ops, label="plan_resident", schema=head_schema,
            static=report,
        ):
            tables = pipeline.materialize_inputs(cell["inputs"])
            for p in cell["barrier"]:
                p.settle_terminally()
            return plan_mod.run_plan(
                ops, tables[0], tables[1:], donate_input=donate
            )

    if pipeline.enabled():
        # capture + reader registration are atomic (see
        # table_op_resident); the enqueue comes after the cell is set
        pending = pipeline.Pending(work, "plan", replayable=not donate)
        cell["inputs"], cell["barrier"] = _capture_inputs(
            table_ids, donate, reader=pending
        )
        return _resident_put(pipeline.enqueue(pending))
    cell["inputs"], cell["barrier"] = _capture_inputs(
        table_ids, donate, pin=True
    )
    try:
        return _resident_put(work())
    finally:
        spill.unpin_ids(table_ids[1:] if donate else table_ids)


# table id -> count of table_download_wire serializers currently
# reading that id's buffers. table_free never touches buffers, so a
# plain free under an active download is safe (the download holds its
# own Table reference) — but table_reclaim DELETES device buffers and
# must drain these readers first, exactly like the pipelined-reader
# barrier. Registered atomically with the registry lookup so a reclaim
# that popped the id either sees this read or ordered itself first.
_RESIDENT_ACTIVE_READS: dict = {}
_RESIDENT_READS_CV = lockcheck.make_condition(_RESIDENT_LOCK)


def table_download_wire(table_id: int):
    """Resident table -> the wire 5-tuple of table_op_wire (shape-bucket
    padding sliced away host-side; the wire never sees it). One of the
    two BLOCKING points of the pipelined plane: a pending chain is
    waited for here and any worker failure is replayed synchronously so
    the originating op's labeled error raises from this call. Raises
    the labeled KeyError on an unknown or already-freed id."""
    tid = int(table_id)
    with _RESIDENT_LOCK:
        t = _RESIDENT.get(tid)
        if isinstance(t, spill.SpilledTable):
            t = spill.repage_locked(tid)
        live = len(_RESIDENT)
        if t is not None:
            _RESIDENT_ACTIVE_READS[tid] = (
                _RESIDENT_ACTIVE_READS.get(tid, 0) + 1
            )
            spill.touch(tid)
    spill.flush_events()
    if t is None:
        raise _unknown_id_error(tid, live)
    try:
        if isinstance(t, pipeline.Pending):
            t = t.resolve()
            with _RESIDENT_LOCK:
                # swap the settled Table in so later gets skip the
                # handle (unless the id was freed while we waited)
                if tid in _RESIDENT:
                    _RESIDENT[tid] = t
        metrics.counter_add("resident.get")
        return _table_to_wire(t)
    finally:
        with _RESIDENT_READS_CV:
            n = _RESIDENT_ACTIVE_READS.get(tid, 1) - 1
            if n > 0:
                _RESIDENT_ACTIVE_READS[tid] = n
            else:
                _RESIDENT_ACTIVE_READS.pop(tid, None)
            _RESIDENT_READS_CV.notify_all()


def table_num_rows(table_id: int) -> int:
    """Logical row count — the other blocking point (see
    ``table_download_wire``)."""
    return int(_resident_get(table_id).logical_row_count)


def table_free(table_id: int) -> None:
    """Release a resident id. A still-pending entry is dropped without
    waiting (the enqueued op keeps its own input references and simply
    completes unobserved); a pending that already FAILED logs the
    dropped error — the caller chose to never hit a blocking point, so
    this WARN is the only trace the op ever broke. Raises the labeled
    KeyError naming the id and live count on an unknown or
    already-freed id."""
    with _RESIDENT_LOCK:
        t = _RESIDENT.pop(int(table_id), None)
        gone = t is None
        _RESIDENT_META.pop(int(table_id), None)
        readers = _RESIDENT_READERS.pop(int(table_id), ())
        live = len(_RESIDENT)
    if gone:
        raise _unknown_id_error(table_id, live)
    # drops spill tracking; for a spilled entry this also releases the
    # host/disk backing (no orphaned spill files)
    spill.note_free(int(table_id), t)
    if isinstance(t, pipeline.Pending):
        if not any(not p.done() for p in readers):
            # fire-and-forget: nothing downstream captured this handle
            # and no blocking point remains — a failure (already
            # landed or still to come) must log itself; when an
            # in-flight consumer DID capture it, error surfacing is
            # delegated to that consumer's blocking point (the normal
            # enqueue -> free(input) chain idiom)
            t.orphan()
            if t.failed_nowait():
                log.log(
                    "WARN", "handles", "freed_failed_pending",
                    table_id=int(table_id), stage=t.label,
                )
                if flight.enabled():
                    flight.record("I", "pipeline.freed_failed", t.label)
    log.log("DEBUG", "handles", "table_free", table_id=int(table_id),
            live=live)
    metrics.counter_add("resident.free")
    metrics.gauge_set("resident.live", live)
    if flight.enabled():
        flight.record("C", "resident.live", live)


def _column_device_arrays(col) -> list:
    """The column's device buffers (data + validity + LIST lengths)."""
    out = []
    for name in ("data", "validity", "lengths"):
        a = getattr(col, name, None)
        if a is not None and hasattr(a, "delete"):
            out.append(a)
    return out


def table_reclaim(table_id: int) -> int:
    """Serving-teardown free: release a resident id AND return its HBM
    to the device now. Returns the approximate bytes reclaimed.

    ``table_free`` only drops the registry reference — safe under
    concurrent readers because each holds its own Table reference — but
    a multi-tenant daemon tearing a session down needs the bytes back
    while OTHER tenants keep running, which means deleting device
    buffers that an in-flight pipelined reader may still dereference.
    That is exactly the donate-consume hazard, so this settles through
    the same barrier before touching anything: (1) every registered
    pipelined reader of the id is terminally settled (later replays
    included, ``Pending.settle_terminally``), (2) in-flight
    ``table_download_wire`` serializers of the id drain, and only then
    (3) the buffers are deleted — skipping any buffer shared with a
    still-live resident table (an aliasing op output), and tolerating
    buffers an executable already consumed by donation. Like donation,
    the caller owns the id: no OTHER thread may still be synchronously
    dispatching ops over it (the serving scheduler guarantees this by
    draining a session's in-flight work before teardown reclaims).
    Raises the labeled KeyError on an unknown or already-freed id."""
    tid = int(table_id)
    with _RESIDENT_LOCK:
        t = _RESIDENT.pop(tid, None)
        gone = t is None
        _RESIDENT_META.pop(tid, None)
        readers = _RESIDENT_READERS.pop(tid, ())
        live = len(_RESIDENT)
    if gone:
        raise _unknown_id_error(table_id, live)
    for p in readers:
        # the donate barrier: a still-running (or failed-but-
        # replayable) reader would dereference the buffers we are about
        # to delete — run it to terminal settlement NOW
        p.settle_terminally()
    if isinstance(t, spill.SpilledTable):
        # already off the device: release the host/disk backing and
        # credit the device bytes the table would have re-occupied
        nbytes = spill.note_free(tid, t)
        metrics.counter_add("resident.free")
        metrics.bytes_add("resident.reclaimed_bytes", nbytes)
        metrics.gauge_set("resident.live", live)
        if flight.enabled():
            flight.record("C", "resident.live", live)
        log.log("DEBUG", "handles", "table_reclaim", table_id=tid,
                live=live, nbytes=nbytes)
        return nbytes
    spill.note_free(tid)
    if isinstance(t, pipeline.Pending):
        t.orphan()  # no blocking point remains for this handle
        t.wait_settled()
        settled = t.value_nowait()
        if settled is None:
            # the producing op failed: there are no buffers to reclaim,
            # and table_free's fire-and-forget WARN is the only trace
            if t.failed_nowait():
                log.log(
                    "WARN", "handles", "reclaimed_failed_pending",
                    table_id=tid, stage=t.label,
                )
            metrics.counter_add("resident.free")
            metrics.gauge_set("resident.live", live)
            if flight.enabled():
                flight.record("C", "resident.live", live)
            return 0
        t = settled
    # drain in-flight wire serializers of this id (they registered
    # atomically with their registry lookup; the pop above makes new
    # ones impossible, so this wait terminates)
    with _RESIDENT_READS_CV:
        while _RESIDENT_ACTIVE_READS.get(tid):
            _RESIDENT_READS_CV.wait()
    from .utils import hbm

    try:
        nbytes = int(hbm.table_bytes(t))
    # srt: allow-broad-except(diagnostic sizing only; reclaim proceeds with nbytes=0)
    except Exception:
        nbytes = 0
    # never delete a buffer another live table can still see: an op
    # output may alias its input outright (e.g. single-table concat
    # returns the input Table), and settled pending entries count
    shared = set()
    with _RESIDENT_LOCK:
        others = list(_RESIDENT.values())
    for o in others:
        if isinstance(o, pipeline.Pending):
            o = o.value_nowait()
            if o is None:
                continue
        if isinstance(o, spill.SpilledTable):
            continue  # holds no device buffers
        for c in o.columns:
            for a in _column_device_arrays(c):
                shared.add(id(a))
    for c in t.columns:
        for a in _column_device_arrays(c):
            if id(a) in shared:
                continue
            try:
                a.delete()
            # srt: allow-broad-except(already consumed by a donated executable or no explicit delete; the reference drop reclaims it)
            except Exception:
                pass
    log.log("DEBUG", "handles", "table_reclaim", table_id=tid,
            live=live, nbytes=nbytes)
    metrics.counter_add("resident.free")
    metrics.bytes_add("resident.reclaimed_bytes", nbytes)
    metrics.gauge_set("resident.live", live)
    if flight.enabled():
        flight.record("C", "resident.live", live)
    return nbytes


def resident_table_count() -> int:
    """Live resident tables (leak-report analog for device tables)."""
    with _RESIDENT_LOCK:
        return len(_RESIDENT)


def leak_report() -> list:
    """Tables still resident, each with the span stack that allocated
    it — the RMM leak report's role for device table handles. JSON-able;
    embedded in the flight dump as the ``resident_leaks`` section and
    printed at exit when non-empty and a telemetry plane is on."""
    with _RESIDENT_LOCK:
        items = [
            (tid, _RESIDENT[tid], dict(_RESIDENT_META.get(tid) or {}))
            for tid in sorted(_RESIDENT)
        ]
    now = _time.perf_counter_ns()
    out = []
    for tid, t, meta in items:
        # never resolve a pending here: the leak report runs at exit
        # and must not replay abandoned work just to size it
        pending = isinstance(t, pipeline.Pending)
        if pending:
            settled = t.value_nowait()
            if settled is not None:
                t, pending = settled, False
        spilled = isinstance(t, spill.SpilledTable)
        if spilled:
            logical = int(t.rows)
        else:
            logical = None if pending else int(t.logical_row_count)
        rec = {
            "table_id": tid,
            "rows": logical,
            "logical_rows": logical,
            "columns": t.num_columns if spilled
            else (None if pending else len(t.columns)),
            "allocated_under": meta.get("allocated_under", []),
        }
        if pending:
            rec["pending"] = t.label
        if spilled:
            # a spilled leak holds host RAM or a disk file, not HBM —
            # say which tier so the postmortem reads correctly
            rec["residency"] = t.state
            rec["approx_bytes"] = int(t.nbytes)
        if meta.get("session"):
            rec["session"] = meta["session"]
        anchor = meta.get("age_anchor_ns")
        if anchor is not None:
            rec["age_s"] = round((now - anchor) / 1e9, 3)
        if not pending and not spilled:
            try:
                from .utils import hbm

                rec["approx_bytes"] = int(hbm.table_bytes(t))
            # srt: allow-broad-except(best-effort sizing for the leak report; listing tables must never fail)
            except Exception:
                pass
        out.append(rec)
    return out


def _leak_report_at_exit() -> None:  # pragma: no cover - atexit path
    """The RMM-leak-report-at-shutdown analog: WARN (ungated when a
    telemetry plane is on — a leak with no trace wasted a round-5
    debugging session) for every table a dead process left resident."""
    if not _RESIDENT or not _provenance_on():
        return
    import sys as _sys

    leaks = leak_report()
    print(
        f"[srt][leak][WARN] {len(leaks)} device table(s) still resident "
        "at exit:",
        file=_sys.stderr,
        flush=True,
    )
    for rec in leaks:
        under = "/".join(rec["allocated_under"]) or "<no span>"
        print(
            f"[srt][leak][WARN]   table_id={rec['table_id']} "
            f"logical_rows={rec['logical_rows']} cols={rec['columns']} "
            f"bytes~{rec.get('approx_bytes', '?')} "
            f"allocated_under={under}",
            file=_sys.stderr,
            flush=True,
        )


atexit.register(_leak_report_at_exit)
# the flight dump carries the same record, so a postmortem reads one file
flight.register_exit_section("resident_leaks", leak_report)
# the spill tier operates UNDER this registry's lock: one lock decides
# eviction vs capture vs reclaim ordering (utils/spill.py)
spill.bind_registry(
    _RESIDENT_LOCK, _RESIDENT, _RESIDENT_READERS, _RESIDENT_ACTIVE_READS
)
