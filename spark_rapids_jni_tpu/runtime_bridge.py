"""Wire-level dispatch for the embedded native runtime.

This module is what ``libspark_rapids_tpu.so`` imports when a native
caller (JNI bridge, C program, Spark executor) initializes the embedded
JAX runtime (src/cpp/jax_runtime.cpp). It is the TPU answer to the
reference's JNI entry points dispatching into device kernels
(RowConversionJni.cpp:24-66): host bytes come in over the C ABI, columns
are built on the XLA backend, the op runs on device, and result columns
travel back as host bytes.

The wire format mirrors the reference's dtype marshaling: parallel
(type id, scale) int arrays (RowConversionJni.cpp:56-61), little-endian
fixed-width data buffers (FLOAT64 as IEEE-754 doubles, BOOL8 as one 0/1
byte per value), and per-column 0/1 validity byte vectors. Variable-width
columns use Arrow layouts: STRING and LIST travel as int32
offsets[n+1] + concatenated payload (for LIST the scale slot carries the
child type id). The row transpose itself stays fixed-width-only — the
same gate the reference enforces at row_conversion.cu:514-516.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

# Backend selection for embedded callers: the axon TPU plugin re-appends
# itself even when JAX_PLATFORMS is set in the environment (see
# tests/conftest.py), so tests that must keep a native embedder off the
# tunneled chip set SRT_JAX_PLATFORMS and we apply it through the config
# API before the first backend touch.
if os.environ.get("SRT_JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["SRT_JAX_PLATFORMS"])

from . import dtype as dt
from .column import Column, Table
from .utils import buckets, flight, log, metrics


def _wire_np(d: dt.DType) -> np.dtype:
    """Host wire numpy dtype of a fixed-width column."""
    if not d.is_fixed_width:
        raise TypeError(f"wire format: fixed-width types only, got {d}")
    if d.id == dt.TypeId.FLOAT64:
        # device storage is the uint64 bit pattern; the wire carries
        # doubles (same bytes, different view)
        return np.dtype(np.float64)
    return np.dtype(d.storage_dtype)


def _padded_from_offsets(
    data: bytes, num_rows: int, child_np: np.dtype, label: str
):
    """Arrow offsets+payload wire buffer -> ((n, pad) matrix, lengths).

    Shared by the STRING and LIST branches: int32 offsets[num_rows+1]
    followed by the concatenated payload values, decoded into the
    padded-matrix device layout. Offsets are untrusted wire input and
    validated up front: a corrupt buffer with negative or non-monotonic
    offsets would otherwise yield negative lengths and a silently wrong
    row mask (``arange < lens`` is all-False for a negative length, so
    payload bytes would land in the WRONG rows without any error)."""
    if len(data) < 4 * (num_rows + 1):
        raise ValueError(
            f"{label} wire buffer holds {len(data)} bytes, "
            f"{4 * (num_rows + 1)} needed for {num_rows + 1} offsets"
        )
    offs = np.frombuffer(data, np.int32, num_rows + 1)
    lens = np.diff(offs).astype(np.int32)
    if int(offs[0]) != 0 or (num_rows and bool((lens < 0).any())):
        raise ValueError(
            f"{label} wire offsets corrupt: must start at 0 and be "
            f"non-decreasing (first={int(offs[0])}, "
            f"min diff={int(lens.min()) if num_rows else 0})"
        )
    need = 4 * (num_rows + 1) + child_np.itemsize * int(offs[-1])
    if len(data) < need:
        raise ValueError(
            f"{label} wire buffer holds {len(data)} bytes, offsets "
            f"require {need}"
        )
    flat = np.frombuffer(
        data, child_np, count=int(offs[-1]), offset=4 * (num_rows + 1)
    )
    pad = max(int(lens.max()) if num_rows else 1, 1)
    mat = np.zeros((num_rows, pad), child_np)
    mask = np.arange(pad)[None, :] < lens[:, None]
    mat[mask] = flat
    return mat, lens


class _SerializePass:
    """Scratch state for ONE wire-serialize pass over a table.

    The STRING/LIST branch needs an ``(n, pad)`` boolean row mask per
    column; a multi-column table re-derives byte-identical ``arange``
    rows and re-allocates the mask buffer for every column of the same
    shape. One pass object caches the ``arange`` per pad width and
    reuses ONE mask buffer per ``(n, pad)`` shape (refilled in place —
    each column's mask is consumed before the next is built). Saved
    allocations are counted in ``wire.serialize.saved_bytes``."""

    __slots__ = ("_aranges", "_masks")

    def __init__(self):
        self._aranges = {}
        self._masks = {}

    def arange(self, pad: int) -> np.ndarray:
        a = self._aranges.get(pad)
        if a is None:
            a = self._aranges[pad] = np.arange(pad)
        return a

    def row_mask(self, lens: np.ndarray, pad: int) -> np.ndarray:
        buf = self._masks.get((lens.shape[0], pad))
        if buf is None:
            buf = self._masks[(lens.shape[0], pad)] = np.empty(
                (lens.shape[0], pad), np.bool_
            )
        else:
            metrics.bytes_add("wire.serialize.saved_bytes", buf.nbytes)
        np.less(self.arange(pad)[None, :], lens[:, None], out=buf)
        return buf


def _padded_to_offsets(
    mat: np.ndarray, lens: np.ndarray, ctx: Optional[_SerializePass] = None
) -> bytes:
    """(n, pad) matrix + lengths -> offsets+payload wire bytes."""
    offs = np.zeros((lens.shape[0] + 1,), np.int32)
    np.cumsum(lens, out=offs[1:])
    if ctx is not None:
        mask = ctx.row_mask(lens, mat.shape[1])
    else:
        mask = np.arange(mat.shape[1])[None, :] < lens[:, None]
    # fancy indexing already yields a fresh contiguous array — no
    # ascontiguousarray copy on top
    flat = mat[mask]
    return offs.tobytes() + flat.tobytes()


def _wire_validity(valid: Optional[bytes], num_rows: int):
    if valid is None:
        return None
    return np.frombuffer(valid, np.uint8, num_rows).astype(np.bool_)


def _pad_host(arr: np.ndarray, total: Optional[int]) -> np.ndarray:
    """Zero-pad a host buffer's row dimension to ``total`` rows BEFORE
    upload — padding to the shape bucket on the host side costs no XLA
    compile and makes every upload within a bucket the same shape."""
    if total is None or arr.shape[0] == total:
        return arr
    out = np.zeros((total,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _column_from_wire(
    type_id: int, scale: int, data: Optional[bytes],
    valid: Optional[bytes], num_rows: int,
    pad_to: Optional[int] = None,
) -> Column:
    if metrics.enabled():
        metrics.bytes_add(
            "wire.bytes_in",
            (len(data) if data is not None else 0)
            + (len(valid) if valid is not None else 0),
        )
        metrics.counter_add("wire.columns_in")
    if dt.TypeId(type_id) == dt.TypeId.LIST:
        # LIST wire convention: the scale slot carries the CHILD type id
        # (scale is meaningless for LIST); payload per _padded_from_offsets.
        import jax.numpy as jnp

        child = dt.DType(dt.TypeId(scale))
        mat, lens = _padded_from_offsets(
            data, num_rows, np.dtype(child.storage_dtype), "LIST"
        )
        v = _wire_validity(valid, num_rows)
        mat = _pad_host(mat, pad_to)
        lens = _pad_host(lens, pad_to)
        v = None if v is None else _pad_host(v, pad_to)
        dev = jnp.asarray(mat)
        if dev.dtype != mat.dtype:
            # x64 disabled: a silent int64->int32 downgrade would corrupt
            # values AND misreport the child type id on download
            raise TypeError(
                f"device buffer dtype {dev.dtype} != {mat.dtype}; 64-bit "
                "LIST children require jax_enable_x64"
            )
        return Column(
            dev, dt.DType(dt.TypeId.LIST),
            None if v is None else jnp.asarray(v), jnp.asarray(lens),
        )
    if dt.TypeId(type_id) == dt.TypeId.STRING:
        # STRING wire convention (the Arrow string layout cudf's JNI
        # marshals): offsets + concatenated UTF-8 bytes.
        import jax.numpy as jnp

        mat, lens = _padded_from_offsets(
            data, num_rows, np.dtype(np.uint8), "STRING"
        )
        v = _wire_validity(valid, num_rows)
        mat = _pad_host(mat, pad_to)
        lens = _pad_host(lens, pad_to)
        v = None if v is None else _pad_host(v, pad_to)
        return Column(
            jnp.asarray(mat), dt.STRING,
            None if v is None else jnp.asarray(v), jnp.asarray(lens),
        )
    d = dt.DType(dt.TypeId(type_id), scale)
    if d.id == dt.TypeId.DECIMAL128:
        # 16 little-endian bytes/value on the wire -> (n, 2) u64 limbs
        arr = np.frombuffer(
            data, dtype=np.uint64, count=2 * num_rows
        ).reshape(num_rows, 2)
    else:
        arr = np.frombuffer(data, dtype=_wire_np(d), count=num_rows)
    v = (
        None
        if valid is None
        else np.frombuffer(valid, dtype=np.uint8, count=num_rows).astype(
            np.bool_
        )
    )
    arr = _pad_host(arr, pad_to)
    v = None if v is None else _pad_host(v, pad_to)
    return Column.from_numpy(arr, validity=v, dtype=d)


def _column_to_wire(
    c: Column, rows: Optional[int] = None,
    ctx: Optional[_SerializePass] = None,
):
    """(type_id, scale, data bytes, valid bytes | None).

    LIST columns use the convention documented in _column_from_wire:
    scale = child type id, data = int32 offsets then child values.

    ``rows`` slices a shape-bucket-padded column back to its logical
    row count on the HOST side (after the device fetch) — the padding
    never reaches the wire and the slice costs no XLA compile.
    ``ctx`` is the per-serialize-pass scratch (mask-buffer reuse).
    """
    out = _column_to_wire_impl(c, rows, ctx)
    if metrics.enabled():
        metrics.bytes_add(
            "wire.bytes_out",
            len(out[2]) + (len(out[3]) if out[3] is not None else 0),
        )
        metrics.counter_add("wire.columns_out")
    return out


def _host_rows(arr: np.ndarray, rows: Optional[int]) -> np.ndarray:
    return arr if rows is None else arr[:rows]


def _column_to_wire_impl(
    c: Column, rows: Optional[int] = None,
    ctx: Optional[_SerializePass] = None,
):
    if c.dtype.id == dt.TypeId.STRING:
        valid = (
            None
            if c.validity is None
            else _host_rows(np.asarray(c.validity), rows)
            .astype(np.uint8).tobytes()
        )
        return (
            int(dt.TypeId.STRING),
            0,
            _padded_to_offsets(
                _host_rows(np.asarray(c.data), rows),
                _host_rows(np.asarray(c.lengths), rows).astype(np.int32),
                ctx,
            ),
            valid,
        )
    if c.dtype.id == dt.TypeId.LIST:
        child = c.list_child_dtype
        valid = (
            None
            if c.validity is None
            else _host_rows(np.asarray(c.validity), rows)
            .astype(np.uint8).tobytes()
        )
        return (
            int(dt.TypeId.LIST),
            int(child.id),
            _padded_to_offsets(
                _host_rows(np.asarray(c.data), rows),
                _host_rows(np.asarray(c.lengths), rows).astype(np.int32),
                ctx,
            ),
            valid,
        )
    # tobytes() emits C-order bytes from any layout in one copy — an
    # ascontiguousarray on top would only add a second copy for
    # non-contiguous slices
    host = _host_rows(np.asarray(c.data), rows)
    valid = (
        None
        if c.validity is None
        else _host_rows(np.asarray(c.validity), rows)
        .astype(np.uint8).tobytes()
    )
    return (
        int(c.dtype.id.value),
        int(c.dtype.scale),
        host.tobytes(),
        valid,
    )


def _dispatch(op: dict, table: Table, rest: Sequence[Table] = ()) -> Table:
    """Run one op on device; returns the result Table.

    ``rest`` carries additional input tables for multi-table ops
    (``join`` takes the probe side as ``table`` and the build side as
    ``rest[0]``; ``concat`` appends every table in ``rest``).

    With shape bucketing on (the default; ``SPARK_RAPIDS_TPU_BUCKETS``),
    bucketable ops run through ``bucketed.dispatch_bucketed``: inputs
    padded to row-count buckets, one compiled executable per
    ``(op, schema, bucket)`` from the central cache, results padded with
    ``Table.logical_rows`` carrying the real count. Non-bucketable ops
    (and the ``=off`` debug mode) take the exact-shape path — padded
    inputs are unpadded first so exact ops never see garbage tails.

    Every op runs inside a ``metrics.span`` and feeds the per-op
    call/row counters — the ``GpuMetric`` plane of the dispatch layer.
    The disabled path costs one string concat and the span's cheap
    gate checks. Row counters count LOGICAL rows (padding is an
    implementation detail; its cost shows up in ``bucket.*`` instead).
    """
    name = op["op"]
    with metrics.span("dispatch." + name):
        out = None
        if buckets.enabled():
            from . import bucketed

            out = bucketed.dispatch_bucketed(op, table, rest, name)
        if out is None:
            out = _dispatch_impl(
                op,
                buckets.unpad_table(table),
                [buckets.unpad_table(t) for t in rest],
                name,
            )
    if metrics.enabled():
        rows_in = int(table.logical_row_count) + sum(
            int(t.logical_row_count) for t in rest
        )
        metrics.counter_add("op." + name + ".calls")
        metrics.counter_add("op." + name + ".rows_in", rows_in)
        metrics.counter_add(
            "op." + name + ".rows_out", int(out.logical_row_count)
        )
        metrics.hist_observe("dispatch.rows_in", rows_in)
    return out


def _dispatch_impl(
    op: dict, table: Table, rest: Sequence[Table], name: str
) -> Table:
    import jax.numpy as jnp

    from . import ops
    from . import rows as rows_mod

    if name == "join":
        how = op.get("how", "inner")
        fn = {
            "inner": ops.inner_join,
            "left": ops.left_join,
            "right": ops.right_join,
            "full": ops.full_join,
            "semi": ops.semi_join,
            "anti": ops.anti_join,
        }.get(how)
        if fn is None:
            raise ValueError(f"unknown join how={how!r}")
        if not rest:
            raise ValueError("join needs two input tables")
        return fn(table, rest[0], op["on"])
    if name == "concat":
        return ops.concatenate([table, *rest])
    if name == "groupby":
        from .ops.groupby import GroupbyAgg

        aggs = [GroupbyAgg(a["column"], a["agg"]) for a in op["aggs"]]
        return ops.groupby_aggregate(table, op["by"], aggs)
    if name == "sort_by":
        keys = [
            ops.SortKey(k["column"], ascending=k.get("ascending", True))
            for k in op["keys"]
        ]
        return ops.sort_table(table, keys)
    if name == "filter":
        mask_idx = op["mask"]
        mask = table.columns[mask_idx]
        keep = [
            c for i, c in enumerate(table.columns) if i != mask_idx
        ]
        return ops.filter_table(Table(keep), mask)
    if name == "distinct":
        return ops.distinct(table, op.get("keys"))
    if name == "cast":
        target = dt.DType(dt.TypeId(op["type_id"]), op.get("scale", 0))
        out = list(table.columns)
        src = table.columns[op["column"]]
        if src.dtype.is_string or target.is_string:
            from .ops import strings as strings_mod

            out[op["column"]] = strings_mod.cast(src, target)
        else:
            out[op["column"]] = ops.cast(src, target)
        return Table(out, table.names)
    if name == "explode":
        return ops.explode(table, op["column"])
    if name == "rlike":
        # filter rows whose string column matches the pattern (the
        # Spark `WHERE col RLIKE pat` scan shape)
        from .ops import regex as regex_mod

        mask = regex_mod.contains_re(
            table.columns[op["column"]], op["pattern"]
        )
        return ops.filter_table(table, mask)
    if name == "cross_join":
        if not rest:
            raise ValueError("cross_join needs two input tables")
        return ops.cross_join(table, rest[0])
    if name == "slice":
        n = table.row_count
        start = int(op.get("start", 0))
        stop = int(op.get("stop", n))
        if start < 0 or stop < 0:
            raise ValueError(
                f"slice: negative bounds not supported (start={start}, "
                f"stop={stop})"
            )
        start = min(start, n)
        stop = max(start, min(stop, n))
        return ops.slice_rows(table, start, stop)
    if name == "repeat":
        return ops.repeat(table, int(op["count"]))
    if name == "sample":
        return ops.sample(
            table, int(op["n"]), seed=int(op.get("seed", 0)),
            replacement=bool(op.get("replacement", False)),
        )
    if name == "to_rows":
        # device row transpose; result = a true LIST<UINT8> column (the
        # reference's output type, row_conversion.cu:389-406)
        return Table([rows_mod.to_rows_list(table)])
    if name == "from_rows":
        schema = [
            dt.DType(dt.TypeId(t), s)
            for t, s in zip(op["type_ids"], op["scales"])
        ]
        src = table.columns[0]
        if src.dtype.id == dt.TypeId.LIST:
            return rows_mod.from_rows_list(src, schema)
        # legacy flat-UINT8 input: one column of num_rows*row_size bytes
        layout = rows_mod.compute_fixed_width_layout(schema)
        n = int(op["num_rows"])
        raw = np.asarray(src.data).reshape(n, layout.row_size)
        pr = rows_mod.PackedRows(jnp.asarray(raw), layout)
        return rows_mod.from_rows(pr, schema)
    raise ValueError(f"unknown table op {name!r}")


def _table_from_wire(
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
    pad_to: Optional[int],
) -> Table:
    """One wire-deserialize pass -> a (possibly host-padded) Table."""
    if flight.enabled():
        flight.record(
            "I", "wire.in",
            sum(len(d) for d in datas if d is not None),
        )
    with metrics.span("wire.deserialize"):
        cols = [
            _column_from_wire(t, s, d, v, num_rows, pad_to=pad_to)
            for t, s, d, v in zip(type_ids, scales, datas, valids)
        ]
    tbl = Table(cols, logical_rows=num_rows if pad_to is not None else None)
    if pad_to is not None:
        buckets.note_padded(tbl)
    return tbl


def _table_to_wire(t: Table):
    """One wire-serialize pass -> the 5-tuple every wire entry returns
    (shape-bucket padding sliced away host-side; one shared
    ``_SerializePass`` scratch across the table's columns)."""
    out_t, out_s, out_d, out_v = [], [], [], []
    ctx = _SerializePass()
    with metrics.span("wire.serialize"):
        for c in t.columns:
            ti, s, d, v = _column_to_wire(c, t.logical_rows, ctx)
            out_t.append(ti)
            out_s.append(s)
            out_d.append(d)
            out_v.append(v)
    if flight.enabled():
        flight.record(
            "I", "wire.out", sum(len(d) for d in out_d if d is not None)
        )
    return out_t, out_s, out_d, out_v, int(t.logical_row_count)


def table_op_wire(
    op_json: str,
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
):
    """C-ABI entry: bytes in, bytes out.

    Returns (out_type_ids, out_scales, out_datas, out_valids, out_rows).
    """
    op = json.loads(op_json)
    pad_to = None
    if buckets.enabled():
        from . import bucketed

        # pad only when the op can actually take the bucketed path —
        # a non-bucketable op would pay the padded upload AND a device
        # unpad slice for nothing
        if bucketed.is_bucketable(op):
            pad_to = buckets.bucket_for(num_rows)
    tbl = _table_from_wire(
        type_ids, scales, datas, valids, num_rows, pad_to
    )
    result = _dispatch(op, tbl)
    return _table_to_wire(result)


def table_plan_wire(
    plan_json: str,
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
):
    """C-ABI plan entry: ``plan_json`` is a JSON LIST of ops executed
    as a fused plan (plan.py) over ONE wire table — upload once, every
    fusable run costs one executable launch, download once. Returns the
    same 5-tuple as ``table_op_wire``."""
    from . import bucketed, plan as plan_mod

    ops = json.loads(plan_json)
    if not isinstance(ops, list):
        raise TypeError("table_plan_wire: plan must be a JSON list of ops")
    pad_to = None
    if buckets.enabled() and ops and isinstance(ops[0], dict):
        # pad only when the FIRST segment can consume the padding (a
        # fused segment, or a 1-op segment with a bucketed runner) —
        # the table_op_wire gate applied at segment granularity, so a
        # plan opening with e.g. a lone slice doesn't pay a padded
        # upload just to unpad on the exact path; malformed entries
        # fall through to run_plan's loud validation
        segs = plan_mod.segment_plan(ops)
        if segs and (
            segs[0][0] == "fused" or bucketed.is_bucketable(segs[0][1][0])
        ):
            pad_to = buckets.bucket_for(num_rows)
    tbl = _table_from_wire(
        type_ids, scales, datas, valids, num_rows, pad_to
    )
    result = plan_mod.run_plan(ops, tbl)
    return _table_to_wire(result)


def platform() -> str:
    """Active XLA backend platform name."""
    import jax

    return jax.devices()[0].platform


# ---------------------------------------------------------------------------
# Device-resident table handles (round-3 VERDICT item 4)
#
# The reference passes jlong pointers to DEVICE-resident cudf tables
# between JNI calls with no host copy in between
# (RowConversionJni.cpp:31,54). The wire path above copies host->device
# per op; these functions give native callers the same chaining
# capability: a table id maps to a Table whose buffers stay on the XLA
# backend, ops consume and produce ids, and bytes only cross the
# boundary at upload/download.
# ---------------------------------------------------------------------------

import atexit
import itertools
import threading
import time as _time

_RESIDENT: dict = {}
# table id -> allocation provenance (span stack, rows, timestamp): what
# the exit-time leak report prints for every handle still live — the
# RMM leak report's "where was this allocated" role. Populated only
# when a telemetry plane is on (metrics/flight/REFCOUNT_DEBUG), so the
# shipped-disabled path stays two dict ops.
_RESIDENT_META: dict = {}
# Lock + atomic counter: Spark executors call through the JNI bridge
# from many threads (the GilGuard path), and the GIL can switch between
# a read-increment pair — an unsynchronized counter could hand two
# threads the same table id. RLock because the SIGTERM-handler flush
# path reaches leak_report() (a flight-dump exit section) on the main
# thread and must not self-deadlock mid-_resident_put.
_RESIDENT_LOCK = threading.RLock()
_NEXT_TABLE_ID = itertools.count(1)


def _provenance_on() -> bool:
    from .utils import config

    return (
        metrics.enabled()
        or flight.enabled()
        or bool(config.get_flag("REFCOUNT_DEBUG"))
    )


def _resident_get(table_id: int) -> Table:
    with _RESIDENT_LOCK:
        t = _RESIDENT.get(int(table_id))
    if t is None:
        raise KeyError(f"unknown device table id {table_id}")
    metrics.counter_add("resident.get")
    return t


def _resident_put(t: Table) -> int:
    tid = next(_NEXT_TABLE_ID)
    meta = None
    if _provenance_on():
        meta = {
            "rows": int(t.logical_row_count),
            "columns": len(t.columns),
            "allocated_under": list(metrics.span_stack()),
            "age_anchor_ns": _time.perf_counter_ns(),
        }
    with _RESIDENT_LOCK:
        _RESIDENT[tid] = t
        if meta is not None:
            _RESIDENT_META[tid] = meta
        live = len(_RESIDENT)
    log.log("DEBUG", "handles", "resident_put", table_id=tid,
            rows=int(t.logical_row_count), live=live)
    # resident.live's high-water mark is the leak-report analog: a chain
    # that frees what it allocates returns to the pre-chain value while
    # high_water records the peak resident set
    metrics.counter_add("resident.put")
    metrics.gauge_set("resident.live", live)
    if flight.enabled():
        flight.record("C", "resident.live", live)
    return tid


def table_upload_wire(
    type_ids: Sequence[int],
    scales: Sequence[int],
    datas: Sequence[Optional[bytes]],
    valids: Sequence[Optional[bytes]],
    num_rows: int,
) -> int:
    """Host bytes -> device-resident table; returns its id. With shape
    bucketing on, the resident buffers are padded to the row-count
    bucket (host-side, before upload) and the table carries its logical
    row count — a chain of bucketed ops then reuses one compiled
    executable per bucket with no repadding."""
    pad_to = buckets.bucket_for(num_rows) if buckets.enabled() else None
    return _resident_put(
        _table_from_wire(type_ids, scales, datas, valids, num_rows, pad_to)
    )


def table_op_resident(op_json: str, table_ids: Sequence[int]) -> int:
    """Run one op over resident tables; the result STAYS resident.

    No host transfer happens here — chaining filter -> join -> groupby
    costs upload + download once, not per op.
    """
    if not table_ids:
        raise ValueError("table_op_resident needs at least one input")
    op = json.loads(op_json)
    tables = [_resident_get(t) for t in table_ids]
    out = _dispatch(op, tables[0], tables[1:])
    return _resident_put(out)


def table_plan_resident(
    plan_json: str, table_ids: Sequence[int]
) -> int:
    """Run a whole PLAN (a JSON list of ops) over resident tables; the
    result stays resident. ``table_ids[0]`` is the chain input; the
    remaining ids feed multi-table segment-boundary ops (join/concat —
    explicit ``"rest"`` indices into this list, or sequential
    consumption; see plan._take_rest). Fusable runs execute as ONE
    cached executable each (plan.py), so an N-op chain costs one
    launch per segment instead of N dispatches."""
    if not table_ids:
        raise ValueError("table_plan_resident needs at least one input")
    from . import plan as plan_mod

    ops = json.loads(plan_json)
    tables = [_resident_get(t) for t in table_ids]
    out = plan_mod.run_plan(ops, tables[0], tables[1:])
    return _resident_put(out)


def table_download_wire(table_id: int):
    """Resident table -> the wire 5-tuple of table_op_wire (shape-bucket
    padding sliced away host-side; the wire never sees it)."""
    return _table_to_wire(_resident_get(table_id))


def table_num_rows(table_id: int) -> int:
    return int(_resident_get(table_id).logical_row_count)


def table_free(table_id: int) -> None:
    with _RESIDENT_LOCK:
        gone = _RESIDENT.pop(int(table_id), None) is None
        _RESIDENT_META.pop(int(table_id), None)
        live = len(_RESIDENT)
    if gone:
        raise KeyError(f"unknown device table id {table_id}")
    log.log("DEBUG", "handles", "table_free", table_id=int(table_id),
            live=live)
    metrics.counter_add("resident.free")
    metrics.gauge_set("resident.live", live)
    if flight.enabled():
        flight.record("C", "resident.live", live)


def resident_table_count() -> int:
    """Live resident tables (leak-report analog for device tables)."""
    with _RESIDENT_LOCK:
        return len(_RESIDENT)


def leak_report() -> list:
    """Tables still resident, each with the span stack that allocated
    it — the RMM leak report's role for device table handles. JSON-able;
    embedded in the flight dump as the ``resident_leaks`` section and
    printed at exit when non-empty and a telemetry plane is on."""
    with _RESIDENT_LOCK:
        items = [
            (tid, _RESIDENT[tid], dict(_RESIDENT_META.get(tid) or {}))
            for tid in sorted(_RESIDENT)
        ]
    now = _time.perf_counter_ns()
    out = []
    for tid, t, meta in items:
        rec = {
            "table_id": tid,
            "rows": int(t.logical_row_count),
            "columns": len(t.columns),
            "allocated_under": meta.get("allocated_under", []),
        }
        anchor = meta.get("age_anchor_ns")
        if anchor is not None:
            rec["age_s"] = round((now - anchor) / 1e9, 3)
        try:
            from .utils import hbm

            rec["approx_bytes"] = int(hbm.table_bytes(t))
        except Exception:
            pass
        out.append(rec)
    return out


def _leak_report_at_exit() -> None:  # pragma: no cover - atexit path
    """The RMM-leak-report-at-shutdown analog: WARN (ungated when a
    telemetry plane is on — a leak with no trace wasted a round-5
    debugging session) for every table a dead process left resident."""
    if not _RESIDENT or not _provenance_on():
        return
    import sys as _sys

    leaks = leak_report()
    print(
        f"[srt][leak][WARN] {len(leaks)} device table(s) still resident "
        "at exit:",
        file=_sys.stderr,
        flush=True,
    )
    for rec in leaks:
        under = "/".join(rec["allocated_under"]) or "<no span>"
        print(
            f"[srt][leak][WARN]   table_id={rec['table_id']} "
            f"rows={rec['rows']} cols={rec['columns']} "
            f"bytes~{rec.get('approx_bytes', '?')} "
            f"allocated_under={under}",
            file=_sys.stderr,
            flush=True,
        )


atexit.register(_leak_report_at_exit)
# the flight dump carries the same record, so a postmortem reads one file
flight.register_exit_section("resident_leaks", leak_report)
