"""Host ⇄ device interop: Arrow is the wire/interop format.

Plays the role the static Arrow build plays in the reference
(CMakeLists.txt:90 includes arrow; CUDF_USE_ARROW_STATIC=ON at
build-libcudf.xml:41): host data arrives as Arrow arrays/tables and becomes
HBM-resident columns, and vice versa.

Validity is 1 bit/value LSB-first in Arrow; on device we keep a bool vector
(see column.py). Packing/unpacking happens here, vectorized on host with
numpy (np.packbits/unpackbits with bitorder="little").
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from . import dtype as dt
from .column import Column, Table, encode_storage

try:  # pyarrow is optional at runtime; gate cleanly (environment contract).
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None


def _require_pyarrow():
    if pa is None:  # pragma: no cover
        raise ImportError("pyarrow is not available in this environment")


# ---------------------------------------------------------------------------
# Arrow validity bitmaps <-> bool vectors
# ---------------------------------------------------------------------------

def unpack_validity(bitmap: Optional[bytes], n: int, offset: int = 0) -> Optional[np.ndarray]:
    """Arrow LSB-first validity bitmap -> (n,) bool array, or None if absent."""
    if bitmap is None:
        return None
    bits = np.unpackbits(
        np.frombuffer(bitmap, dtype=np.uint8), bitorder="little"
    )
    return bits[offset : offset + n].astype(np.bool_)


def pack_validity(valid: np.ndarray) -> bytes:
    """(n,) bool array -> Arrow LSB-first validity bitmap bytes."""
    return np.packbits(valid.astype(np.uint8), bitorder="little").tobytes()


# ---------------------------------------------------------------------------
# pyarrow -> device
# ---------------------------------------------------------------------------

def _arrow_type_to_dtype(t) -> dt.DType:
    _require_pyarrow()
    if pa.types.is_int8(t):
        return dt.INT8
    if pa.types.is_int16(t):
        return dt.INT16
    if pa.types.is_int32(t):
        return dt.INT32
    if pa.types.is_int64(t):
        return dt.INT64
    if pa.types.is_uint8(t):
        return dt.UINT8
    if pa.types.is_uint16(t):
        return dt.UINT16
    if pa.types.is_uint32(t):
        return dt.UINT32
    if pa.types.is_uint64(t):
        return dt.UINT64
    if pa.types.is_float32(t):
        return dt.FLOAT32
    if pa.types.is_float64(t):
        return dt.FLOAT64
    if pa.types.is_boolean(t):
        return dt.BOOL8
    if pa.types.is_date32(t):
        return dt.TIMESTAMP_DAYS
    if pa.types.is_timestamp(t):
        return {
            "s": dt.TIMESTAMP_SECONDS,
            "ms": dt.TIMESTAMP_MILLISECONDS,
            "us": dt.TIMESTAMP_MICROSECONDS,
            "ns": dt.TIMESTAMP_NANOSECONDS,
        }[t.unit]
    if pa.types.is_duration(t):
        return {
            "s": dt.DURATION_SECONDS,
            "ms": dt.DURATION_MILLISECONDS,
            "us": dt.DURATION_MICROSECONDS,
            "ns": dt.DURATION_NANOSECONDS,
        }[t.unit]
    if pa.types.is_decimal(t):
        # cudf maps precision<=9 -> DECIMAL32, <=18 -> DECIMAL64, else
        # DECIMAL128. Arrow scale is positive-right-of-point; cudf wire
        # scale is its negation (RowConversionTest.java:37-38 uses
        # negative scales). decimal256 (32-byte values) must be rejected
        # here: every buffer reader below assumes the 16-byte stride.
        if not pa.types.is_decimal128(t):
            raise TypeError(f"unsupported arrow decimal width: {t}")
        if t.precision <= 9:
            return dt.decimal32(-t.scale)
        if t.precision <= 18:
            return dt.decimal64(-t.scale)
        return dt.decimal128(-t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_binary(t):
        return dt.STRING
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return dt.DType(dt.TypeId.LIST)
    raise TypeError(f"unsupported arrow type {t}")


def column_from_arrow(arr, pad_width: Optional[int] = None) -> Column:
    """pyarrow Array/ChunkedArray -> device Column."""
    _require_pyarrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dtype = _arrow_type_to_dtype(arr.type)

    if dtype.is_string:
        # from_strings accepts str/bytes/None directly (binary arrays arrive
        # as bytes and stay lossless via surrogateescape).
        return Column.from_strings(arr.to_pylist(), pad_width=pad_width)

    if dtype.id == dt.TypeId.LIST:
        # offsets+child -> padded matrix (fixed-width child only; the
        # reference's own nested output is LIST<INT8>,
        # row_conversion.cu:389-406)
        child = _arrow_type_to_dtype(arr.type.value_type)
        # from_list_of_lists enforces the supported-child set (and
        # raises clearly for float64/temporal/decimal children)
        return Column.from_list_of_lists(
            arr.to_pylist(), child, pad_width=pad_width
        )

    n = len(arr)
    valid_np = None
    if arr.null_count:
        valid_np = np.asarray(arr.is_valid())

    if dtype.id == dt.TypeId.DECIMAL128:
        # Arrow decimal128's buffer IS the device limb layout: 16-byte
        # little-endian two's-complement values = (n, 2) u64 [lo, hi]
        buf = arr.buffers()[1]
        words = np.frombuffer(buf, dtype=np.uint64)
        limbs = words[arr.offset * 2 : (arr.offset + n) * 2].reshape(n, 2)
        return Column.from_numpy(
            np.ascontiguousarray(limbs),
            validity=valid_np,
            dtype=dtype,
        )
    if dtype.is_decimal:
        # Arrow decimal128 stores 16-byte little-endian two's-complement
        # unscaled ints. The precision<=18 gate guarantees values fit in the
        # low signed 64 bits, so a vectorized view of the data buffer
        # suffices (no per-row Python Decimal objects).
        buf = arr.buffers()[1]
        words = np.frombuffer(buf, dtype=np.int64)
        lo = words[arr.offset * 2 : (arr.offset + n) * 2 : 2]
        host = lo.astype(np.dtype(dtype.device_dtype))
    elif dtype.is_boolean:
        host = np.asarray(arr.fill_null(False))
    else:
        filler = 0
        host = np.asarray(arr.fill_null(filler))
        if host.dtype.kind in "Mm":
            host = host.view(np.dtype(f"i{host.dtype.itemsize}"))

    return Column(
        data=encode_storage(host, dtype),
        dtype=dtype,
        validity=None if valid_np is None else jnp.asarray(valid_np),
    )


def table_from_arrow(tbl, pad_widths: Optional[dict] = None) -> Table:
    """pyarrow Table -> device Table (names preserved)."""
    _require_pyarrow()
    cols = []
    for name in tbl.column_names:
        pw = (pad_widths or {}).get(name)
        cols.append(column_from_arrow(tbl.column(name), pad_width=pw))
    return Table(cols, tbl.column_names)


# ---------------------------------------------------------------------------
# device -> pyarrow
# ---------------------------------------------------------------------------

def column_to_arrow(col: Column):
    """Device Column -> pyarrow Array (null payloads masked out)."""
    _require_pyarrow()
    valid = col.validity_to_numpy()
    mask = ~valid  # pyarrow wants a null mask
    if col.dtype.is_string:
        vals = col.to_pylist()
        try:
            return pa.array(vals, type=pa.string())
        except (UnicodeEncodeError, pa.ArrowInvalid):
            # Non-UTF8 payload (ingested from an Arrow binary array):
            # export as binary, losslessly undoing surrogateescape.
            return pa.array(
                [
                    None if v is None else v.encode("utf-8", "surrogateescape")
                    for v in vals
                ],
                type=pa.binary(),
            )
    if col.dtype.id == dt.TypeId.LIST:
        # every supported child's storage dtype is a plain numpy dtype
        # (the from_list_of_lists restriction), so arrow derives the
        # child type from it directly — no second hand-maintained map
        child = col.list_child_dtype
        pa_child = pa.from_numpy_dtype(np.dtype(child.storage_dtype))
        return pa.array(col.to_pylist(), type=pa.list_(pa_child))

    if col.dtype.is_decimal:
        # one export path for all three widths: python ints (None for
        # null) -> Decimal at the cudf precision for the width. The
        # localcontext matters for 128-bit values (default precision is
        # 28 significant digits; scaleb would silently round).
        import decimal as _dec

        scale = -col.dtype.scale
        precision = {4: 9, 8: 18, 16: 38}[col.dtype.itemsize]
        vals = col.to_pylist()
        limit = 10 ** precision
        for v in vals:
            if v is not None and abs(v) >= limit:
                raise ValueError(
                    f"unscaled value {v} exceeds Arrow "
                    f"decimal128({precision}) precision"
                )
        # localcontext(prec=...) kwargs need Python 3.11+; set the
        # precision on the entered context so 3.10 works too
        with _dec.localcontext() as ctx:
            ctx.prec = 50
            py = [
                None if v is None else _dec.Decimal(v).scaleb(-scale)
                for v in vals
            ]
        return pa.array(py, type=pa.decimal128(precision, scale))

    arr = col.to_numpy()
    if col.dtype.id == dt.TypeId.DURATION_DAYS:
        # Arrow has no duration[D] unit; export as duration[s].
        arr = arr.astype("timedelta64[s]")
    return pa.array(arr, mask=mask if mask.any() else None)


def table_to_arrow(tbl: Table):
    _require_pyarrow()
    names = (
        list(tbl.names)
        if tbl.names is not None
        else [f"c{i}" for i in range(tbl.num_columns)]
    )
    return pa.table(
        {n: column_to_arrow(c) for n, c in zip(names, tbl.columns)}
    )
