"""Plan-statistics store + prediction-drift telemetry (ISSUE 16).

The runtime *predicts* (plancheck's static segmentation, row bounds and
HBM footprint) and *measures* (profiler per-segment compile/execute
splits, spill/retry/shed/shuffle counters) — this module is the
substrate that persists the measurements and compares them to the
predictions, the Spark-AQE observe half the re-planner will act on:

* a crash-tolerant, append-only, CRC-framed **stats store**: one
  record per finished profile session (i.e. per ``run_plan``
  execution — exact, pipelined, and mesh paths all open sessions at
  the dispatch entries), keyed by plan fingerprint x schema x bucket,
  carrying per-segment observed wall/compile/execute time, rows
  in/out, bytes moved, an HBM working-set proxy, and the
  spill/retry/shed/exchange counter deltas that accrued during the
  session;
* a **drift layer** that compares each record against plancheck's
  static prediction (embedded in the session doc as ``pred`` by the
  dispatch entries) and against the plan's own history, emitting
  structured ``drift.*`` metrics plus typed findings when observed
  segmentation, cardinality, or HBM peak diverge past the
  ``SPARK_RAPIDS_TPU_DRIFT_*_FACTOR`` thresholds;
* a **report plane**: :func:`drift_report` aggregates the store into
  per-(plan, schema, bucket) groups with per-segment
  predicted-vs-observed percentiles, rendered by
  ``tools/explain.py --drift`` and surfaced through the serving
  ``stats`` command (:func:`stats_doc`).

Store format (``planstats-<host>-<pid>.wal`` in ``PLANSTATS_DIR``,
default ``<tempdir>/srt-planstats``): the ``serving/durable.py`` WAL
framing — the 6-byte magic ``SRTS1\\n``, then records of
``u32 LE payload length | u32 LE crc32(payload) | UTF-8 JSON``.
Appends are written + flushed (the kernel owns the bytes, so a
``kill -9`` loses at most the in-flight record); unlike durable.py
there is no per-append ``fsync`` — stats are telemetry, not
acknowledged client state, and an fsync per dispatch would tax the
query it observes. One file per process means appends never interleave
across writers; :func:`load` reads every ``planstats-*.wal*`` file in
the directory. A torn tail (crash mid-append) is dropped silently;
mid-file corruption stops that file's scan with a
``planstats.corrupt_files`` tick — a stats reader must never take down
the process that asks. Retention: past ``PLANSTATS_ROTATE_MB`` the
live file rotates to ``<name>.wal.1`` (one old generation kept).

Every append goes through :class:`StatsWriter` — the single sanctioned
``open(..., "ab")`` site lives in ``_open_append`` and
``tools/srt_check.py`` (the stats-append pass) rejects any other
append-mode open on the stats path.

Import discipline: this module imports config/flight/lockcheck/metrics.
The profiler lazy-imports *it* at session close (never at module load),
so planstats may import metrics while metrics imports profiler.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import socket
import struct
import tempfile
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import config
from . import flight
from . import lockcheck
from . import metrics

_MAGIC = b"SRTS1\n"
_FRAME = struct.Struct("<II")
_HOST = socket.gethostname()

# ---------------------------------------------------------------------------
# flag gate (the metrics._GATE_GEN discipline)
# ---------------------------------------------------------------------------

_GATE = (None, False)


def enabled() -> bool:
    """True when sessions should append stats records (cached gate);
    a configured PLANSTATS_DIR implies PLANSTATS, the dump-path
    convention."""
    global _GATE
    gen = config.generation()
    if _GATE[0] != gen:
        _GATE = (
            gen,
            bool(config.get_flag("PLANSTATS"))
            or bool(str(config.get_flag("PLANSTATS_DIR") or "")),
        )
    return _GATE[1]


def stats_dir() -> str:
    """Directory for store files; created lazily. Like CHECKPOINT_DIR
    (and unlike SPILL_DIR) the default is STABLE across processes and
    never swept — cross-process history is what drift compares
    against."""
    d = str(config.get_flag("PLANSTATS_DIR") or "").strip()
    if not d:
        d = os.path.join(tempfile.gettempdir(), "srt-planstats")
    os.makedirs(d, exist_ok=True)
    return d


# ---------------------------------------------------------------------------
# always-on counter mirror (the durable.count pattern): server.stats()
# gets a planstats block even when the metrics plane is off
# ---------------------------------------------------------------------------

_STATS_LOCK = lockcheck.make_lock("planstats.stats")
_STATS: Dict[str, int] = {}

# recent typed drift findings, newest last — the serving stats /
# flight-dump surfacing for "what diverged lately"
_FINDINGS: "deque" = deque(maxlen=64)

# skew events observed mid-plan (shuffle exchange planning) waiting to
# ride the next session record as typed "skew" findings; bounded so an
# always-disabled planstats can't leak
_PENDING_SKEW: "deque" = deque(maxlen=64)


def _count(name: str, n: int = 1, as_bytes: bool = False) -> None:
    with _STATS_LOCK:
        _STATS[name] = _STATS.get(name, 0) + int(n)
    if as_bytes:
        metrics.bytes_add(name, n)
    else:
        metrics.counter_add(name, n)


def _skew_detail(ev: dict) -> str:
    """Human line for one skew event (the --drift rendering)."""
    try:
        site = ev.get("site", "?")
        ratio = float(ev.get("ratio") or 0.0)
        factor = float(ev.get("factor") or 0.0)
        if ev.get("action") == "split":
            hot = ev.get("hot_destinations") or 0
            nhot = len(hot) if isinstance(hot, (list, tuple)) else int(hot)
            return (
                f"{site}: split {nhot} hot "
                f"destination(s) across k={int(ev.get('k') or 0)} salts — "
                f"planned max recv {int(ev.get('max_recv') or 0)} rows "
                f"(x{ratio:.1f} mean) -> {int(ev.get('post_max_recv') or 0)} "
                f"(x{float(ev.get('post_ratio') or 0.0):.1f}) "
                f"at factor {factor:g}"
            )
        return (
            f"{site}: planned max recv {int(ev.get('max_recv') or 0)} rows "
            f"is x{ratio:.1f} the mean at factor {factor:g} — "
            "no split applied"
        )
    # srt: allow-broad-except(telemetry formatting must never raise into the shuffle path)
    except Exception:
        return repr(ev)


def note_skew(detail: dict) -> None:
    """Record one adaptive-skew decision from the shuffle plane. Surfaces
    immediately in the always-on findings ring (serving stats, flight
    dumps) and rides the next ``record_session`` record as a typed
    ``skew`` drift finding so ``explain --drift`` shows it next to the
    cardinality/HBM divergences. Never raises into the exchange path."""
    try:
        ev = dict(detail)
        entry = {
            "type": "skew",
            "segment": None,
            "detail": _skew_detail(ev),
            "event": ev,
            "fp": None,
            "schema": None,
            "bucket": None,
            "ts": None,
        }
        with _STATS_LOCK:
            _FINDINGS.append(dict(entry))
            _PENDING_SKEW.append(entry)
        _count("drift.skew")
    # srt: allow-broad-except(telemetry hook on the hot shuffle path)
    except Exception:
        pass


def _drain_skew(rec: dict) -> List[dict]:
    """Pop pending skew events into findings stamped with the session
    record's identity (fp/schema/bucket/ts)."""
    with _STATS_LOCK:
        pending = list(_PENDING_SKEW)
        _PENDING_SKEW.clear()
    out = []
    for entry in pending:
        e = dict(entry)
        e["fp"] = rec.get("fp")
        e["schema"] = rec.get("schema")
        e["bucket"] = rec.get("bucket")
        e["ts"] = rec.get("ts")
        out.append(e)
    return out


def stats_doc() -> dict:
    """Always-available summary block (serving stats, flight dumps)."""
    with _STATS_LOCK:
        doc: Dict[str, Any] = dict(sorted(_STATS.items()))
    doc["enabled"] = enabled()
    doc["findings"] = list(_FINDINGS)
    return doc


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------


def plan_fingerprint(ops) -> str:
    """Stable 16-hex fingerprint of a plan's canonical JSON — the store
    key that makes 'same plan, different day' one history."""
    try:
        blob = json.dumps(ops, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError):
        blob = repr(ops)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# the CRC-framed writer — every append in the process funnels here
# ---------------------------------------------------------------------------


def _open_append(path: str):
    """THE sanctioned raw append-mode open for the stats path; the
    srt_check stats-append pass rejects any other. Keeping it one
    function keeps the CRC framing un-bypassable by construction."""
    return open(path, "ab")


class StatsWriter:
    """One process's append-only store file. Thread-safe; each append
    is framed (len | crc32 | JSON), written and flushed — the kernel
    owns acknowledged bytes, so SIGKILL loses at most the record being
    framed. A torn write (partial frame on disk after a crash landed
    mid-``write``) self-heals on the next append by truncating back to
    the last good offset, the durable.Journal discipline."""

    def __init__(self, path: str):
        self.path = path
        self._lock = lockcheck.make_lock("planstats.writer")
        self._f = _open_append(path)
        size = os.fstat(self._f.fileno()).st_size
        if size == 0:
            self._f.write(_MAGIC)
            self._f.flush()
            size = len(_MAGIC)
        self._good = size

    def append(self, record: dict) -> int:
        """Append one record; returns the framed size in bytes."""
        payload = json.dumps(record, sort_keys=True).encode()
        frame = _FRAME.pack(
            len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        ) + payload
        with self._lock:
            self._maybe_rotate()
            size = os.fstat(self._f.fileno()).st_size
            if size != self._good:
                self._f.truncate(self._good)
            self._f.write(frame)
            self._f.flush()
            self._good = os.fstat(self._f.fileno()).st_size
        return len(frame)

    def _maybe_rotate(self) -> None:
        limit = float(config.get_flag("PLANSTATS_ROTATE_MB")) * (1 << 20)
        if self._good <= limit:
            return
        self._f.close()
        os.replace(self.path, self.path + ".1")  # old generation
        self._f = _open_append(self.path)
        self._f.write(_MAGIC)
        self._f.flush()
        self._good = len(_MAGIC)
        _count("planstats.rotations")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()


_WRITER_LOCK = lockcheck.make_lock("planstats.writer_singleton")
_WRITER: Optional[StatsWriter] = None


def _writer() -> StatsWriter:
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is None or _WRITER._f.closed:
            path = os.path.join(
                stats_dir(), f"planstats-{_HOST}-{os.getpid()}.wal"
            )
            _WRITER = StatsWriter(path)
        return _WRITER


# ---------------------------------------------------------------------------
# readers — torn tails recover silently; corruption never raises
# ---------------------------------------------------------------------------


def read_stats_file(path: str) -> Tuple[List[dict], int]:
    """Parse one store file. Returns ``(records, torn)`` where torn
    counts the incomplete trailing record (0 or 1). A bad magic or
    mid-file CRC/decode failure stops THIS file's scan with a
    ``planstats.corrupt_files`` tick instead of raising — unlike
    durable journals, stats carry no client-acknowledged state, so the
    reader degrades to 'what survived' rather than quarantining."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return [], 0
    if not blob.startswith(_MAGIC):
        _count("planstats.corrupt_files")
        return [], 0
    off = len(_MAGIC)
    n = len(blob)
    records: List[dict] = []
    torn = 0
    while off < n:
        if off + _FRAME.size > n:
            torn = 1  # header truncated mid-append
            break
        length, crc = _FRAME.unpack_from(blob, off)
        end = off + _FRAME.size + length
        if end > n:
            torn = 1  # payload truncated mid-append
            break
        payload = blob[off + _FRAME.size:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            if end == n:
                torn = 1  # full-length tail frame with torn payload
            else:
                _count("planstats.corrupt_files")
            break
        try:
            records.append(json.loads(payload.decode()))
        except ValueError:
            if end == n:
                torn = 1
            else:
                _count("planstats.corrupt_files")
            break
        off = end
    if torn:
        _count("planstats.torn_records")
    return records, torn


def load(path: Optional[str] = None) -> List[dict]:
    """Every record across the store, oldest first (by ``ts``).
    ``path`` may be a directory (default: :func:`stats_dir`), one store
    file, or absent."""
    if path is None:
        path = stats_dir()
    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, "planstats-*.wal"))) \
            + sorted(glob.glob(os.path.join(path, "planstats-*.wal.1")))
    else:
        paths = [path]
    records: List[dict] = []
    for p in paths:
        recs, _torn = read_stats_file(p)
        records.extend(recs)
    records.sort(key=lambda r: (r.get("ts") or 0))
    return records


# ---------------------------------------------------------------------------
# the session hook (called by profiler._SessionScope, lazily)
# ---------------------------------------------------------------------------

# counter names whose session-scoped deltas ride every record: the
# spill/retry/shed/exchange story of one plan execution
_DELTA_KEYS = (
    "spill.evictions", "spill.demotions", "spill.repages",
    "spill.bytes_out", "spill.bytes_in",
    "retry.attempts", "retry.giveups",
    "serving.shed",
    "shuffle.exchanges", "shuffle.rows_exchanged",
    "shuffle.skew_splits",
    "plan.oom_spill_retries", "plan.mesh_fallbacks", "mesh.degraded",
)

# plan-key -> deque of {seg index -> rows_out} from past runs; the
# history the cardinality check medians over. Seeded once per process
# from the on-disk store so cross-process runs share one history.
_HISTORY_LOCK = lockcheck.make_lock("planstats.history")
_HISTORY: Dict[tuple, "deque"] = {}
_HISTORY_SEEDED = False
_HISTORY_KEEP = 64


def counter_snapshot() -> Dict[str, int]:
    """Base values captured at session open; diffed at close."""
    return metrics.counter_values(_DELTA_KEYS)


def _plan_key(rec: dict) -> tuple:
    return (rec.get("fp"), rec.get("schema"), rec.get("bucket"))


def _seg_rows(rec: dict) -> Dict[int, int]:
    return {
        int(s["index"]): int(s.get("rows_out") or 0)
        for s in rec.get("segments") or []
        if s.get("index") is not None
    }


def _seed_history_locked() -> None:
    global _HISTORY_SEEDED
    if _HISTORY_SEEDED:
        return
    _HISTORY_SEEDED = True
    for rec in load():
        _HISTORY.setdefault(
            _plan_key(rec), deque(maxlen=_HISTORY_KEEP)
        ).append(_seg_rows(rec))


def _history_medians(key: tuple) -> Dict[int, float]:
    """Per-segment-index median rows_out over the plan's history."""
    with _HISTORY_LOCK:
        _seed_history_locked()
        runs = list(_HISTORY.get(key) or ())
    by_seg: Dict[int, List[int]] = {}
    for run in runs:
        for idx, rows in run.items():
            by_seg.setdefault(idx, []).append(rows)
    out: Dict[int, float] = {}
    for idx, vals in by_seg.items():
        vals.sort()
        m = len(vals) // 2
        out[idx] = (
            float(vals[m]) if len(vals) % 2
            else (vals[m - 1] + vals[m]) / 2.0
        )
    return out


def _push_history(rec: dict) -> None:
    with _HISTORY_LOCK:
        _seed_history_locked()
        _HISTORY.setdefault(
            _plan_key(rec), deque(maxlen=_HISTORY_KEEP)
        ).append(_seg_rows(rec))


def _seg_hbm_proxy(seg: dict) -> Optional[int]:
    """Observed working-set proxy for one segment: rows_in at the
    observed output row width plus the output itself — the same
    rows x width shape plancheck's static ``est_hbm_bytes`` bounds, so
    the two are comparable. None when the segment moved no bytes
    (resident-only chains report out_bytes 0)."""
    out_bytes = int(seg.get("out_bytes") or 0)
    rows_out = int(seg.get("rows_out") or 0)
    rows_in = int(seg.get("rows_in") or 0)
    calls = max(int(seg.get("calls") or 1), 1)
    if out_bytes <= 0 or rows_out <= 0:
        return None
    width = out_bytes / rows_out
    return int((rows_in * width + out_bytes) / calls)


def _drift_check(rec: dict, pred: Optional[dict]) -> List[dict]:
    """Typed findings for one fresh record: segmentation / cardinality
    / HBM divergence vs the static prediction and the plan's history.
    Emits the structured ``drift.*`` metrics as it goes."""
    findings: List[dict] = []
    _count("drift.checks")
    segs = rec.get("segments") or []

    def finding(kind: str, segment, detail: str) -> None:
        findings.append({
            "type": kind,
            "segment": segment,
            "detail": detail,
            "fp": rec.get("fp"),
            "schema": rec.get("schema"),
            "bucket": rec.get("bucket"),
            "ts": rec.get("ts"),
        })
        _count("drift." + kind)

    if pred:
        psegs = pred.get("segments") or []
        okinds = [s.get("kind") for s in segs]
        pkinds = [s.get("kind") for s in psegs]
        # mesh runs execute whole-plan as ONE sharded "mesh" segment
        # plancheck never predicts — a different execution strategy,
        # not a mis-segmentation; same for an empty observed list
        # (not measured)
        if (
            okinds and pkinds and okinds != pkinds
            and "mesh" not in okinds
        ):
            finding(
                "segmentation", None,
                f"predicted {len(pkinds)} segment(s) "
                f"[{','.join(map(str, pkinds))}] but observed "
                f"{len(okinds)} [{','.join(map(str, okinds))}]",
            )
        hbm_factor = float(config.get_flag("DRIFT_HBM_FACTOR"))
        for seg, pseg in zip(segs, psegs):
            if seg.get("kind") == "mesh":
                continue  # whole-plan stage; pseg is one segment of it
            idx = seg.get("index")
            bound = pseg.get("rows_bound")
            rows_out = int(seg.get("rows_out") or 0)
            calls = max(int(seg.get("calls") or 1), 1)
            if bound is not None and rows_out > int(bound) * calls:
                finding(
                    "cardinality", idx,
                    f"observed rows_out {rows_out} exceeds the static "
                    f"bound {int(bound) * calls} — the row-count "
                    "inference is wrong for this plan",
                )
            est = pseg.get("est_hbm_bytes")
            obs = seg.get("hbm_bytes")
            if est and obs:
                est_eff = float(est)
                bucket = rec.get("bucket")
                # bucket padding inflates the physical working set by
                # design (plancheck estimates logical rows); drift
                # means exceeding even the bucket-scaled estimate
                if bucket and bound and int(bucket) > int(bound):
                    est_eff *= int(bucket) / float(bound)
                if obs > est_eff * hbm_factor:
                    finding(
                        "hbm", idx,
                        f"observed working set ~{obs}B exceeds the "
                        f"static estimate {int(est_eff)}B by more "
                        f"than x{hbm_factor:g}",
                    )

    rows_factor = float(config.get_flag("DRIFT_ROWS_FACTOR"))
    medians = _history_medians(_plan_key(rec))
    for seg in segs:
        idx = seg.get("index")
        med = medians.get(int(idx)) if idx is not None else None
        if med is None or med < 1.0:
            continue
        rows_out = int(seg.get("rows_out") or 0)
        if rows_out > med * rows_factor or rows_out * rows_factor < med:
            finding(
                "cardinality", idx,
                f"observed rows_out {rows_out} vs history median "
                f"{med:g} (x{max(rows_out / med, med / max(rows_out, 1)):.1f}"
                f" > factor {rows_factor:g}) — skewed input or stale "
                "history",
            )
    if findings:
        _count("drift.findings", len(findings))
        with _STATS_LOCK:
            _FINDINGS.extend(findings)
    return findings


def record_session(doc: dict, base: Optional[Dict[str, int]] = None):
    """Append one stats record for a finished profile-session doc —
    the hook profiler._SessionScope.__exit__ calls (lazily) for every
    run_plan execution. Never raises into the query path: the caller
    wraps it, and everything here degrades to 'record less'. Returns
    the record (tests) or None when disabled."""
    if not enabled():
        return None
    plan = doc.get("plan")
    counters: Dict[str, int] = {}
    if base is not None:
        now = counter_snapshot()
        counters = {
            k: now.get(k, 0) - base.get(k, 0)
            for k in now
            if now.get(k, 0) - base.get(k, 0)
        }
    segs: List[dict] = []
    bytes_moved = 0
    hbm_peak: Optional[int] = None
    for s in doc.get("segments") or []:
        proxy = _seg_hbm_proxy(s)
        segs.append({
            "index": s.get("index"),
            "kind": s.get("kind"),
            "ops": list(s.get("ops") or []),
            "calls": int(s.get("calls") or 0),
            "wall_s": round(float(s.get("wall_s") or 0.0), 6),
            "compile_s": round(float(s.get("compile_s") or 0.0), 6),
            "execute_s": round(float(s.get("execute_s") or 0.0), 6),
            "rows_in": int(s.get("rows_in") or 0),
            "rows_out": int(s.get("rows_out") or 0),
            "out_bytes": int(s.get("out_bytes") or 0),
            "hbm_bytes": proxy,
        })
        bytes_moved += int(s.get("out_bytes") or 0)
        if proxy is not None:
            hbm_peak = proxy if hbm_peak is None else max(hbm_peak, proxy)
    boundary = doc.get("boundary") or {}
    bytes_moved += int(boundary.get("serde_bytes_in") or 0)
    bytes_moved += int(boundary.get("serde_bytes_out") or 0)
    rec = {
        "v": 1,
        "fp": plan_fingerprint(plan) if plan else "-",
        "schema": doc.get("schema"),
        "bucket": doc.get("bucket"),
        "label": doc.get("label"),
        "session_id": doc.get("session_id"),
        "pid": doc.get("pid"),
        "host": doc.get("host"),
        "ts": doc.get("epoch_ns"),
        "wall_s": round(float(doc.get("wall_s") or 0.0), 6),
        "batches": doc.get("batches"),
        "segments": segs,
        "counters": counters,
        "bytes_moved": bytes_moved,
        "hbm_peak_bytes": hbm_peak,
    }
    pred = doc.get("pred")
    if pred is not None:
        rec["pred"] = pred
    drift = _drift_check(rec, pred)
    drift = list(drift) + _drain_skew(rec)
    if drift:
        rec["drift"] = drift
    nbytes = _writer().append(rec)
    _push_history(rec)
    _count("planstats.records")
    _count("planstats.bytes", nbytes, as_bytes=True)
    if flight.enabled():
        flight.record("I", "planstats.record", rec["fp"])
    return rec


# ---------------------------------------------------------------------------
# report plane (tools/explain.py --drift, serving stats, bench)
# ---------------------------------------------------------------------------


def _dist(vals: List[float]) -> dict:
    vals = sorted(vals)

    def pct(q: float) -> float:
        i = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
        return vals[i]

    return {
        "n": len(vals),
        "p50": pct(0.50),
        "p95": pct(0.95),
        "max": vals[-1],
    }


def drift_report(
    records: Optional[Sequence[dict]] = None,
    path: Optional[str] = None,
) -> dict:
    """Aggregate the store into per-(fp, schema, bucket) groups: runs,
    per-segment observed percentiles (wall time, rows out, bytes, HBM
    proxy) next to the static prediction, and every typed finding the
    append-time drift checks raised — the machine form behind
    ``explain --drift``."""
    if records is None:
        records = load(path)
    groups: Dict[tuple, dict] = {}
    for rec in records:
        key = _plan_key(rec)
        g = groups.get(key)
        if g is None:
            g = groups[key] = {
                "fp": rec.get("fp"),
                "schema": rec.get("schema"),
                "bucket": rec.get("bucket"),
                "labels": [],
                "runs": 0,
                "_segs": {},
                "pred": None,
                "findings": [],
                "counters": {},
            }
        g["runs"] += 1
        for ck, cv in (rec.get("counters") or {}).items():
            g["counters"][ck] = g["counters"].get(ck, 0) + int(cv)
        label = rec.get("label")
        if label and label not in g["labels"]:
            g["labels"].append(label)
        if rec.get("pred") is not None:
            g["pred"] = rec["pred"]  # latest wins
        g["findings"].extend(rec.get("drift") or [])
        for s in rec.get("segments") or []:
            idx = s.get("index")
            agg = g["_segs"].get(idx)
            if agg is None:
                agg = g["_segs"][idx] = {
                    "index": idx,
                    "kind": s.get("kind"),
                    "ops": list(s.get("ops") or []),
                    "calls": 0,
                    "wall_s": [],
                    "rows_out": [],
                    "out_bytes": [],
                    "hbm_bytes": [],
                }
            agg["kind"] = s.get("kind")
            agg["calls"] += int(s.get("calls") or 0)
            agg["wall_s"].append(float(s.get("wall_s") or 0.0))
            agg["rows_out"].append(float(s.get("rows_out") or 0))
            agg["out_bytes"].append(float(s.get("out_bytes") or 0))
            if s.get("hbm_bytes") is not None:
                agg["hbm_bytes"].append(float(s["hbm_bytes"]))
    out_groups = []
    for key in sorted(groups, key=lambda k: tuple(str(x) for x in k)):
        g = groups[key]
        psegs = (g["pred"] or {}).get("segments") or []
        segments = []
        for idx in sorted(g["_segs"], key=lambda i: (i is None, i)):
            agg = g["_segs"][idx]
            pseg = psegs[idx] if isinstance(idx, int) and idx < len(psegs) \
                else None
            segments.append({
                "index": agg["index"],
                "kind": agg["kind"],
                "ops": agg["ops"],
                "calls": agg["calls"],
                "wall_s": _dist(agg["wall_s"]) if agg["wall_s"] else None,
                "rows_out": _dist(agg["rows_out"]) if agg["rows_out"]
                else None,
                "out_bytes": _dist(agg["out_bytes"]) if agg["out_bytes"]
                else None,
                "hbm_bytes": _dist(agg["hbm_bytes"]) if agg["hbm_bytes"]
                else None,
                "pred": pseg,
            })
        out_groups.append({
            "fp": g["fp"],
            "schema": g["schema"],
            "bucket": g["bucket"],
            "labels": g["labels"],
            "runs": g["runs"],
            "segments": segments,
            "rows_out_bound": (g["pred"] or {}).get("rows_out_bound"),
            "est_hbm_peak_bytes": (g["pred"] or {}).get(
                "est_hbm_peak_bytes"
            ),
            "findings": g["findings"],
            "counters": dict(sorted(g["counters"].items())),
        })
    return {
        "version": 1,
        "records": len(list(records)),
        "groups": out_groups,
    }


def _fmt_dist(d: Optional[dict], unit: str = "", scale: float = 1.0,
              nd: int = 2) -> str:
    if not d:
        return "-"
    return (
        f"{d['p50'] * scale:.{nd}f}/{d['p95'] * scale:.{nd}f}"
        f"/{d['max'] * scale:.{nd}f}{unit}"
    )


def render_drift(report: dict) -> str:
    """The human form of :func:`drift_report`: per plan group, each
    segment's predicted bound next to the observed p50/p95/max, then
    the typed findings."""
    lines: List[str] = []
    lines.append(
        f"PLAN DRIFT  {len(report.get('groups') or [])} plan group(s), "
        f"{report.get('records', 0)} record(s)"
    )
    for g in report.get("groups") or []:
        head = f"\nplan {g.get('fp')}"
        if g.get("schema"):
            head += f"  schema={g['schema']}"
        if g.get("bucket") is not None:
            head += f"  bucket={g['bucket']}"
        head += (
            f"  runs={g.get('runs')}"
            f"  labels={','.join(g.get('labels') or []) or '-'}"
        )
        lines.append(head)
        for s in g.get("segments") or []:
            pred = s.get("pred") or {}
            lines.append(
                f"  seg {s.get('index')} [{s.get('kind', '?')}] "
                f"{','.join(s.get('ops') or [])}"
            )
            bound = pred.get("rows_bound")
            lines.append(
                "      rows_out p50/p95/max "
                + _fmt_dist(s.get("rows_out"), nd=0)
                + (f"  (pred bound {bound})" if bound is not None
                   else "  (pred bound -)")
            )
            est = pred.get("est_hbm_bytes")
            lines.append(
                "      hbm p50/p95/max "
                + _fmt_dist(s.get("hbm_bytes"), "B", nd=0)
                + (f"  (pred est {est}B)" if est is not None
                   else "  (pred est -)")
            )
            lines.append(
                "      wall p50/p95/max "
                + _fmt_dist(s.get("wall_s"), "ms", 1e3)
            )
        # the exchange story of this plan group: shuffle/partition
        # counter deltas (skew splits most of all) next to the findings
        exch = {
            k: v for k, v in (g.get("counters") or {}).items()
            if k.startswith("shuffle.") or k.startswith("partition.")
        }
        if exch:
            lines.append(
                "  exchange: "
                + " ".join(f"{k}={v}" for k, v in exch.items())
            )
        finds = g.get("findings") or []
        if finds:
            lines.append(f"  findings ({len(finds)}):")
            for f in finds:
                seg = f.get("segment")
                where = f"seg {seg}" if seg is not None else "plan"
                lines.append(
                    f"    DRIFT[{f.get('type')}] {where}: "
                    f"{f.get('detail')}"
                )
        else:
            lines.append("  findings: none")
    return "\n".join(lines)


def summary(path: Optional[str] = None) -> Optional[dict]:
    """Compact block for bench headline JSON: record/group counts and
    findings by type — small enough to ride every emit. None when the
    store is empty or unreadable."""
    try:
        report = drift_report(path=path)
    # srt: allow-broad-except(telemetry summary must never fail the bench emit)
    except Exception:
        return None
    if not report["records"]:
        return None
    by_type: Dict[str, int] = {}
    for g in report["groups"]:
        for f in g.get("findings") or []:
            t = str(f.get("type"))
            by_type[t] = by_type.get(t, 0) + 1
    return {
        "records": report["records"],
        "plans": len(report["groups"]),
        "findings": by_type,
    }


def reset() -> None:
    """Test hook: close the writer and drop in-process state (files on
    disk are the caller's to manage)."""
    global _WRITER, _HISTORY_SEEDED, _GATE
    with _WRITER_LOCK:
        if _WRITER is not None:
            _WRITER.close()
            _WRITER = None
    with _HISTORY_LOCK:
        _HISTORY.clear()
        _HISTORY_SEEDED = False
    with _STATS_LOCK:
        _STATS.clear()
        _FINDINGS.clear()
        _PENDING_SKEW.clear()
    _GATE = (None, False)


# the planstats block rides every flight dump, the durable/profiler
# exit-section discipline
flight.register_exit_section("planstats", stats_doc)
