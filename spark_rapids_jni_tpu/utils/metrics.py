"""Op-level metrics registry + structured spans — the ``GpuMetric`` role.

The reference is observable end to end: per-operator ``GpuMetric``
counters (op time, rows, bytes) surface in Spark's SQL UI, and NVTX
ranges (reference pom.xml:85,200) mark the hot kernels in Nsight. This
module is both planes for the TPU runtime:

* a process-wide, thread-safe registry of named **counters**, **byte
  counters**, **wall-clock timers**, bounded **histograms**, and
  high-water **gauges** (the leak-report analog for resident handles);
* a ``span(name, **attrs)`` context manager that nests (thread-local
  stack), records its wall-clock duration into the timer registry —
  including on the exception path — opens the profiler ``trace_range``
  when ``SPARK_RAPIDS_TPU_TRACE`` is on, and emits one structured
  stderr line on the ``span`` channel when ``LOG_LEVEL`` admits TRACE.

Gating follows the ``log.enabled()`` discipline: :func:`enabled` is a
cheap check (``SPARK_RAPIDS_TPU_METRICS`` truthy, or a
``SPARK_RAPIDS_TPU_METRICS_DUMP`` path configured) and every mutator
no-ops when it is false, so instrumented hot paths cost a couple of
dict lookups when shipped disabled — the reference's ship-it-disabled
default. :func:`snapshot` returns a JSON-able dict; when a dump path is
configured the snapshot is also written there at interpreter exit
(atexit), and ``bench.py`` embeds it per config so
``tools/analyze_bench.py`` can correlate throughput with op counts and
bytes moved.
"""

from __future__ import annotations

import atexit
import bisect
import functools
import json
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from . import config
from . import flight
from . import log
from . import tracing

# ---------------------------------------------------------------------------
# registry state — one lock guards every table; mutations are a few dict
# ops so contention stays negligible even under the concurrent-dispatch
# test tier (tests/test_metrics.py hammers it from many threads).
# RLock, not Lock: the bench SIGTERM handler runs on the MAIN thread and
# calls snapshot()/dump() — if the signal lands while that same thread
# is inside a mutator's critical section, a non-reentrant lock would
# self-deadlock the handler (and the process would hang to SIGKILL
# without re-printing the headline line).
# ---------------------------------------------------------------------------

_LOCK = threading.RLock()
_COUNTERS: Dict[str, int] = {}
_BYTES: Dict[str, int] = {}
# name -> [count, total_s, min_s, max_s]
_TIMERS: Dict[str, List[float]] = {}
# name -> [value, high_water]
_GAUGES: Dict[str, List[float]] = {}
# name -> {"bounds": tuple, "counts": list, "count": int, "sum": float}
_HISTS: Dict[str, dict] = {}
# name -> [count, total_s] of span SELF time (duration minus enclosed
# child spans on the same thread) — what analyze_bench's
# top-ops-by-self-time table ranks; total time alone buries the hot
# leaf under its wrappers
_SELF: Dict[str, List[float]] = {}

# bounded histogram default: powers of 4 from 1 to ~10^9 (17 buckets
# incl. overflow) — sized for row counts and byte volumes
_DEFAULT_BOUNDS = tuple(4 ** i for i in range(16))

_TLS = threading.local()

# Gate cache, invalidated by config.generation(): a disabled
# instrumentation site costs one int compare + attribute read instead
# of re-reading os.environ per call (measured ~6us/span uncached vs
# ~0.2us cached — the difference between "near-zero" and 0.5% of a
# small dispatch). Flags flipped via config.set_flag/clear_flag are
# picked up immediately; raw mid-process os.environ writes are not
# (see config.generation()).
_GATE_GEN = -1
_GATE_ENABLED = False
_GATE_SPAN = False
_GATE_FLIGHT = False


def _refresh_gate() -> None:
    global _GATE_GEN, _GATE_ENABLED, _GATE_SPAN, _GATE_FLIGHT
    _GATE_ENABLED = (
        bool(config.get_flag("METRICS"))
        or bool(config.get_flag("METRICS_DUMP"))
        # the plan-stats store diffs counters around every profile
        # session (utils/planstats.py) — stats with all-zero spill/
        # retry/shed columns would be silently wrong, so PLANSTATS
        # pulls the registry on with it
        or bool(config.get_flag("PLANSTATS"))
        or bool(str(config.get_flag("PLANSTATS_DIR") or ""))
    )
    _GATE_FLIGHT = flight.enabled()
    _GATE_SPAN = (
        _GATE_ENABLED
        or _GATE_FLIGHT
        or tracing.tracing_enabled()
        or log.enabled("TRACE", "span")
    )
    _GATE_GEN = config.generation()


def enabled() -> bool:
    """True when the metrics plane is on — instrumentation sites guard
    expensive field construction with this (the log.enabled() pattern);
    a configured dump path implies collection."""
    if _GATE_GEN != config.generation():
        _refresh_gate()
    return _GATE_ENABLED


# ---------------------------------------------------------------------------
# mutators — every one no-ops when the plane is off, so un-guarded call
# sites stay near-zero too
# ---------------------------------------------------------------------------


def counter_add(name: str, n: int = 1) -> None:
    """Bump a named event counter (op calls, rows, retries, ...)."""
    if not enabled():
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + int(n)


def bytes_add(name: str, n: int) -> None:
    """Bump a named byte counter (wire traffic, planned HBM, ...)."""
    if not enabled():
        return
    with _LOCK:
        _BYTES[name] = _BYTES.get(name, 0) + int(n)


def counter_values(names: Sequence[str]) -> Dict[str, int]:
    """Point-in-time values of named counters/byte-counters (0 when a
    name was never ticked) — the cheap targeted read planstats diffs
    around each profile session, vs snapshot() which copies every
    table."""
    with _LOCK:
        return {
            n: int(_COUNTERS.get(n) or _BYTES.get(n) or 0) for n in names
        }


def timer_record(name: str, seconds: float) -> None:
    """Fold one wall-clock duration into a named timer."""
    if not enabled():
        return
    s = float(seconds)
    with _LOCK:
        t = _TIMERS.get(name)
        if t is None:
            _TIMERS[name] = [1, s, s, s]
        else:
            t[0] += 1
            t[1] += s
            if s < t[2]:
                t[2] = s
            if s > t[3]:
                t[3] = s


def gauge_set(name: str, value) -> None:
    """Set a gauge, tracking its high-water mark (resident handles,
    planned capacities)."""
    if not enabled():
        return
    v = float(value)
    with _LOCK:
        g = _GAUGES.get(name)
        if g is None:
            _GAUGES[name] = [v, v]
        else:
            g[0] = v
            if v > g[1]:
                g[1] = v


def self_time_record(name: str, seconds: float) -> None:
    """Fold one span SELF-time observation (duration minus child spans)
    into the ``span_self`` table."""
    if not enabled():
        return
    s = max(float(seconds), 0.0)
    with _LOCK:
        t = _SELF.get(name)
        if t is None:
            _SELF[name] = [1, s]
        else:
            t[0] += 1
            t[1] += s


def hist_observe(
    name: str, value, bounds: Optional[Sequence[float]] = None
) -> None:
    """Record one observation into a bounded histogram. ``bounds`` (used
    only on the first observation of ``name``) are inclusive upper bucket
    edges; one overflow bucket is appended."""
    if not enabled():
        return
    v = float(value)
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            b = tuple(bounds) if bounds else _DEFAULT_BOUNDS
            h = _HISTS[name] = {
                "bounds": b,
                "counts": [0] * (len(b) + 1),
                "count": 0,
                "sum": 0.0,
            }
        h["counts"][bisect.bisect_left(h["bounds"], v)] += 1
        h["count"] += 1
        h["sum"] += v


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class _NullSpan:
    """Shared no-op span: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


# span-duration histogram edges in MILLISECONDS: ~x3 rungs from 10us to
# 30s + overflow — wide enough for a tunnel round-trip, fine enough that
# analyze_bench's p50/p95 estimates are meaningful. Public: subsystem-
# owned duration histograms (pipeline.stall_ms / pipeline.overlap_ms)
# share these edges so analyze_bench percentiles line up across planes.
SPAN_MS_BOUNDS = (
    0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0, 300.0,
    1000.0, 3000.0, 10000.0, 30000.0,
)


class _Span:
    __slots__ = ("name", "attrs", "qualname", "_t0", "_trace_cm",
                 "_child_s")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.qualname = name
        self._t0 = 0.0
        self._trace_cm = None
        self._child_s = 0.0

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        # nesting: the qualified name carries the enclosing span path so
        # the TRACE line / profiler range shows WHERE the op ran; the
        # timer aggregates under the plain name so repeated ops fold
        # into one stable registry row
        self.qualname = (
            stack[-1].qualname + "/" + self.name if stack else self.name
        )
        stack.append(self)
        if tracing.tracing_enabled():
            self._trace_cm = tracing.trace_range(self.qualname)
            self._trace_cm.__enter__()
        if _GATE_FLIGHT:
            # the ambient trace context rides the B arg (one contextvar
            # read; None outside a traced request, and flight omits
            # None args) — the join key tracequery/assign_trace_ids
            # merge per-process dumps on
            flight.record("B", self.qualname, tracing.current_traceparent())
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        # duration is recorded on the exception path too: a span that
        # dies mid-op is exactly the one the telemetry must explain
        dur = time.perf_counter() - self._t0
        if _GATE_FLIGHT:
            flight.record(
                "E", self.qualname,
                None if exc_type is None else exc_type.__name__,
            )
        stack = getattr(_TLS, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        if self._trace_cm is not None:
            self._trace_cm.__exit__(exc_type, exc, tb)
            self._trace_cm = None
        timer_record(self.name, dur)
        if _GATE_ENABLED:
            # self time: what THIS span spent outside its children —
            # the parent (still on the stack, same thread) absorbs our
            # whole duration into its child accumulator
            if stack:
                stack[-1]._child_s += dur
            self_time_record(self.name, dur - self._child_s)
            hist_observe(
                "span_ms." + self.name, dur * 1e3, bounds=SPAN_MS_BOUNDS
            )
        if exc_type is not None:
            counter_add("span." + self.name + ".errors")
        if log.enabled("TRACE", "span"):
            log.log(
                "TRACE", "span", self.qualname,
                dur_ms=round(dur * 1e3, 3),
                ok=exc_type is None,
                **self.attrs,
            )
        return False


def span(name: str, **attrs):
    """Context manager: a named, nestable timed region.

    Records duration into the timer registry under ``name`` (exception
    path included) plus self-time and a ``span_ms.*`` duration
    histogram, emits begin/end events into the flight recorder when
    ``SPARK_RAPIDS_TPU_FLIGHT`` is on, opens a profiler ``trace_range``
    when ``SPARK_RAPIDS_TPU_TRACE`` is on, and emits one
    ``[srt][span][TRACE]`` stderr line when the log level admits it.
    Returns a shared no-op object when every plane is off — the
    hot-path cost of a disabled span is one generation compare on the
    cached gate.
    """
    if _GATE_GEN != config.generation():
        _refresh_gate()
    if not _GATE_SPAN:
        return NULL_SPAN
    return _Span(name, attrs)


def traced(name: Optional[str] = None):
    """Decorator form of :func:`span` (tracing.annotate's metrics-aware
    sibling): wraps the function body in ``span(name or qualname)``."""

    def wrap(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


def span_depth() -> int:
    """Current nesting depth on this thread (test/introspection aid)."""
    stack = getattr(_TLS, "stack", None)
    return len(stack) if stack else 0


def span_stack() -> tuple:
    """Qualified names of the spans open on THIS thread, outermost
    first — the allocation provenance the resident-table leak report
    attaches to each handle."""
    stack = getattr(_TLS, "stack", None)
    return tuple(s.qualname for s in stack) if stack else ()


# ---------------------------------------------------------------------------
# export plane
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    """One JSON-able dict of everything measured so far."""
    with _LOCK:
        return {
            "counters": dict(_COUNTERS),
            "bytes": dict(_BYTES),
            "timers": {
                k: {
                    "count": int(t[0]),
                    "total_s": float(t[1]),
                    "min_s": float(t[2]),
                    "max_s": float(t[3]),
                }
                for k, t in _TIMERS.items()
            },
            "gauges": {
                k: {"value": g[0], "high_water": g[1]}
                for k, g in _GAUGES.items()
            },
            "histograms": {
                k: {
                    "bounds": list(h["bounds"]),
                    "counts": list(h["counts"]),
                    "count": int(h["count"]),
                    "sum": float(h["sum"]),
                }
                for k, h in _HISTS.items()
            },
            "span_self": {
                k: {"count": int(t[0]), "self_s": float(t[1])}
                for k, t in _SELF.items()
            },
        }


def _prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name: ``srt_`` prefix, dots
    and every other non-[a-zA-Z0-9_] character become underscores."""
    return "srt_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name
    )


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Prometheus text-exposition rendering of the metrics snapshot —
    the serving daemon's ``trace`` command returns this alongside the
    slow-request log so one scrape-shaped payload carries the whole
    registry. Counters/bytes render as ``counter``, gauges as ``gauge``
    (plus a ``_high_water`` series), timers as a summary-shaped
    ``_count``/``_total_seconds`` pair, histograms as a classic
    cumulative ``_bucket{le=...}`` family."""
    if snap is None:
        snap = snapshot()
    lines: List[str] = []

    def emit(name: str, kind: str, series) -> None:
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in series:
            lines.append(f"{name}{labels} {value}")

    for k in sorted(snap.get("counters", {})):
        emit(_prom_name(k) + "_total", "counter",
             [("", snap["counters"][k])])
    for k in sorted(snap.get("bytes", {})):
        emit(_prom_name(k) + "_bytes_total", "counter",
             [("", snap["bytes"][k])])
    for k in sorted(snap.get("gauges", {})):
        g = snap["gauges"][k]
        emit(_prom_name(k), "gauge", [("", g["value"])])
        emit(_prom_name(k) + "_high_water", "gauge",
             [("", g["high_water"])])
    for k in sorted(snap.get("timers", {})):
        t = snap["timers"][k]
        base = _prom_name(k) + "_seconds"
        emit(base + "_count", "counter", [("", t["count"])])
        emit(base + "_total", "counter", [("", t["total_s"])])
    for k in sorted(snap.get("histograms", {})):
        h = snap["histograms"][k]
        base = _prom_name(k)
        lines.append(f"# TYPE {base} histogram")
        cum = 0
        for bound, n in zip(h["bounds"], h["counts"]):
            cum += n
            lines.append(f'{base}_bucket{{le="{bound}"}} {cum}')
        cum += h["counts"][len(h["bounds"])] if (
            len(h["counts"]) > len(h["bounds"])
        ) else 0
        lines.append(f'{base}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{base}_count {h['count']}")
        lines.append(f"{base}_sum {h['sum']}")
    for k in sorted(snap.get("span_self", {})):
        s = snap["span_self"][k]
        base = _prom_name(k) + "_self_seconds"
        emit(base + "_count", "counter", [("", s["count"])])
        emit(base + "_total", "counter", [("", s["self_s"])])
    return "\n".join(lines) + "\n" if lines else ""


def reset() -> None:
    """Clear the registry (test isolation; bench per-config blocks)."""
    with _LOCK:
        _COUNTERS.clear()
        _BYTES.clear()
        _TIMERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _SELF.clear()


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write the snapshot as JSON to ``path`` (default: the
    ``SPARK_RAPIDS_TPU_METRICS_DUMP`` flag). Returns the path written,
    or None when no path is configured. Failures WARN on stderr instead
    of raising — a broken dump path must not take the process down at
    exit."""
    path = path or str(config.get_flag("METRICS_DUMP") or "")
    if not path:
        return None
    try:
        with open(path, "w") as f:
            json.dump(snapshot(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path
    except OSError as e:
        print(
            f"[srt][metrics][WARN] metrics dump to {path!r} failed: {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess
    dump()


atexit.register(_dump_at_exit)
