"""Shared utilities (IEEE-754 codecs, native-library loading, profiling)."""
