"""HBM footprint planning — the RMM-pool role, TPU-shaped.

The reference leans on RMM pools, streams and allocator statistics
(row_conversion.hpp:30-31; RMM_LOGGING_LEVEL, reference pom.xml:82) to
keep kernels inside device memory. Under XLA the allocator belongs to
the runtime and the tunneled PJRT client exposes no live pool state, so
this module plans ANTE-HOC instead: conservative per-op byte estimates
against a configurable per-chip budget, used to size batch/chunk
parameters so the batched/capped APIs never assemble a resident set
past the chip (round-3's 32M-join worker crash was discovered by
crashing; round-4 VERDICT item 7 asks for it to be planned for).

Budget plane: ``SPARK_RAPIDS_TPU_HBM_BUDGET_GB`` (utils/config.py) —
default 16 GiB (v5e per chip) scaled by a fixed reserve fraction that
covers XLA's own workspace, fusion temporaries and the framework's
transient double-buffering, which the estimates below deliberately do
not enumerate.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from . import config
from . import flight
from . import lockcheck
from . import log
from . import metrics
from . import profiler

GIB = 1 << 30

# fraction of the budget left to XLA workspace/temporaries; estimates
# here count steady-state buffers only
RESERVE_FRACTION = 0.35

_BACKEND_HBM_GB = {
    "tpu": 16.0,   # v5e
    "axon": 16.0,  # the tunneled v5 lite chip
}


def backend_hbm_gb(platform: Optional[str] = None) -> float:
    if platform is None:
        try:
            import jax

            platform = jax.default_backend()
        # srt: allow-broad-except(no backend at all degrades to cpu sizing; planning shapes still work)
        except Exception:  # pragma: no cover - no backend at all
            platform = "cpu"
    # CPU: pretend a v5e so planning behaves identically under the
    # test suite's forced-CPU backend (shapes, not host RAM, are what
    # the plans must exercise)
    return _BACKEND_HBM_GB.get(platform, 16.0)


def budget_bytes(platform: Optional[str] = None) -> int:
    """Usable device bytes for steady-state buffers."""
    gb = config.get_flag("HBM_BUDGET_GB")
    if not gb:
        gb = backend_hbm_gb(platform)
    return int(float(gb) * GIB * (1.0 - RESERVE_FRACTION))


def column_bytes(col) -> int:
    """Resident bytes of one device column (data + validity + lengths)."""
    total = col.data.size * col.data.dtype.itemsize
    if col.validity is not None:
        total += col.validity.size * col.validity.dtype.itemsize
    if col.lengths is not None:
        total += col.lengths.size * col.lengths.dtype.itemsize
    return int(total)


def table_bytes(table) -> int:
    return sum(column_bytes(c) for c in table.columns)


def row_bytes(table) -> int:
    """Per-row resident bytes (ceil) — sizing unit for join output."""
    n = max(table.row_count, 1)
    return -(-table_bytes(table) // n)


def key_word_count(cols: Sequence) -> int:
    """u64 order words per row for a key column list (ops/keys.py):
    strings cost pad/8 + 1 words, DECIMAL128 two, the rest one, plus a
    validity word per nullable column."""
    words = 0
    for c in cols:
        if c.dtype.is_string:
            words += c.data.shape[1] // 8 + 1
        elif getattr(c.dtype, "id", None) is not None and c.data.ndim == 2:
            words += c.data.shape[1]
        else:
            words += 1
        if c.validity is not None:
            words += 1
    return words


# cumulative donated bytes for the flight counter track (the
# bucket.pad_waste_bytes discipline: kept locally so the track survives
# flight-only mode and per-config metrics resets)
_DONATED_LOCK = lockcheck.make_lock("hbm.donated")
_DONATED_TOTAL = 0

# Donation listeners: the serving tier registers one so a tenant whose
# plan donated its buffers gets the bytes credited back against its
# per-session budget (serving/session.py). Listeners must be cheap and
# must not raise — they run on the hot donate path, unconditionally
# (budget credits can't depend on a telemetry flag).
_DONATION_LISTENERS: list = []


def register_donation_listener(fn) -> None:
    """Register ``fn(nbytes)`` to observe every buffer donation."""
    if fn not in _DONATION_LISTENERS:
        _DONATION_LISTENERS.append(fn)


def note_donation(nbytes: int) -> None:
    """Record one buffer donation: ``nbytes`` of input HBM the chained
    executable updated IN PLACE instead of allocating fresh output
    buffers next to. The plan-vs-budget picture reads this as peak
    relief — a fused chain that donates never holds input + output of
    a segment simultaneously, so the steady-state estimates above are
    conservative by exactly the donated volume."""
    global _DONATED_TOTAL
    profiler.note_donation(int(nbytes))
    for fn in tuple(_DONATION_LISTENERS):
        fn(int(nbytes))
    if not (metrics.enabled() or flight.enabled()):
        return
    metrics.counter_add("hbm.donations")
    metrics.bytes_add("hbm.donated_bytes", int(nbytes))
    if flight.enabled():
        # cumulative donated bytes as a counter track: the Chrome trace
        # shows WHEN in-place chaining kicked in alongside resident.live
        with _DONATED_LOCK:
            _DONATED_TOTAL += int(nbytes)
            total = _DONATED_TOTAL
        flight.record("C", "hbm.donated_bytes", total)


# Pressure listeners: the spill tier (utils/spill.py) registers one so
# a plan that does NOT fit the budget frees the deficit (coldest
# resident tables demote to host/disk) BEFORE the launch OOMs. Fired
# unconditionally — eviction can't depend on a telemetry flag — with
# the byte deficit; listeners gate themselves and must not raise.
_PRESSURE_LISTENERS: list = []


def register_pressure_listener(fn) -> None:
    """Register ``fn(deficit_bytes)`` to observe every over-budget plan."""
    if fn not in _PRESSURE_LISTENERS:
        _PRESSURE_LISTENERS.append(fn)


def _record_plan(kind: str, plan: dict, planned_bytes: int) -> None:
    """Plan-vs-budget decisions on the metrics plane: how many plans ran,
    how many bytes they committed, and how often a shape failed to fit
    (the spill/chunk trigger)."""
    if not plan["fits"]:
        deficit = max(planned_bytes - plan["budget_bytes"], 1)
        for fn in tuple(_PRESSURE_LISTENERS):
            fn(deficit)
    if not metrics.enabled():
        return
    metrics.counter_add("hbm.plan." + kind)
    metrics.bytes_add("hbm.planned_bytes", planned_bytes)
    metrics.gauge_set("hbm.budget_bytes", plan["budget_bytes"])
    if not plan["fits"]:
        metrics.counter_add("hbm.plan_over_budget")


def join_plan(
    left,
    right,
    left_on: Sequence,
    right_on: Sequence,
    platform: Optional[str] = None,
) -> dict:
    """Steady-state byte plan of a batched join: what is resident
    across one probe-chunk iteration, and the probe_rows that fits.

    Resident set per iteration (ops/join.py inner_join_batched):
      inputs        both tables
      build         sorted key words (W_r + 1 occupancy) * 8 B * m
                    + the permutation (4 B * m)
      probe chunk   chunk slice of left + lo/counts/lvalid (9 B/row)
      output        capacity * output row bytes (pow2 of the chunk's
                    matches; planned at 1x expansion and ENFORCED at
                    run time by re-splitting oversized chunks, since
                    fan-out is data-dependent)
    """
    lcols = [left.column(c) for c in left_on]
    rcols = [right.column(c) for c in right_on]
    m = right.row_count
    budget = budget_bytes(platform)
    fixed = (
        table_bytes(left)
        + table_bytes(right)
        + (key_word_count(rcols) + 1) * 8 * m
        + 4 * m
    )
    out_row = row_bytes(left) + row_bytes(right)
    per_probe_row = (
        row_bytes(left)            # the chunk slice
        + 9                        # lo (4) + counts (4) + lvalid (1)
        + 2 * out_row              # pow2 capacity overshoot at 1x fan-out
    )
    avail = budget - fixed
    probe_rows = max(1024, avail // max(per_probe_row, 1))
    plan = {
        "budget_bytes": budget,
        "fixed_bytes": int(fixed),
        "per_probe_row_bytes": int(per_probe_row),
        "output_row_bytes": int(out_row),
        "probe_rows": int(probe_rows),
        "fits": avail > 0,
    }
    log.log("INFO", "hbm", "join_plan", **plan)
    _record_plan("join", plan, int(fixed))
    if metrics.enabled() and probe_rows < left.row_count:
        # the plan decided the probe side must be chunked
        metrics.counter_add("hbm.join_chunk_decisions")
    return plan


def sort_plan(table, n_key_words: int, platform: Optional[str] = None) -> dict:
    """Variadic payload sort: operands (keys + iota + every 1-D buffer)
    live twice (input + output) during the sort."""
    n = table.row_count
    operand = n_key_words * 8 * n + 4 * n + table_bytes(table)
    total = 2 * operand
    plan = {
        "budget_bytes": budget_bytes(platform),
        "total_bytes": int(total),
        "fits": total <= budget_bytes(platform),
    }
    log.log("INFO", "hbm", "sort_plan", rows=n, **plan)
    _record_plan("sort", plan, int(total))
    return plan


def groupby_plan(
    table,
    by: Sequence,
    num_segments: int,
    platform: Optional[str] = None,
) -> dict:
    """Single-pass capped groupby: the variadic sort (keys + payload,
    doubled) plus the num_segments-sized output/bounds."""
    key_cols = [table.column(c) for c in by]
    n = table.row_count
    words = key_word_count(key_cols) + 1  # + occupancy/iota word
    sort_bytes = 2 * (words * 8 * n + 4 * n + table_bytes(table))
    seg_bytes = num_segments * (8 + 2 * 4) + num_segments * row_bytes(table)
    total = sort_bytes + seg_bytes
    plan = {
        "budget_bytes": budget_bytes(platform),
        "total_bytes": int(total),
        "fits": total <= budget_bytes(platform),
    }
    log.log("INFO", "hbm", "groupby_plan", rows=n, segments=num_segments,
            **plan)
    _record_plan("groupby", plan, int(total))
    return plan
