"""ctypes binding to the native runtime shim (libspark_rapids_tpu.so).

One of the two embedders of the C ABI (src/include/spark_rapids_tpu/
c_api.h) — the other is the JNI bridge (src/jni/). The loading contract
mirrors NativeLibraryLoader/NativeDepsLoader in the reference
(NativeLibraryLoader.java:22-37, resources staged per-platform at
spark-rapids-jni/pom.xml:179-188): resolve by explicit flag first, then
packaged location, then a dev build tree; load once, idempotently.

Everything degrades gracefully: ``available()`` is False when no library
exists, and callers (e.g. the host row-codec fast path) fall back to the
pure-Python/XLA implementations.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

from . import config
from . import lockcheck

# status codes (src/include/spark_rapids_tpu/c_api.h)
SRT_OK = 0

_lock = lockcheck.make_lock("native.load")
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _candidate_paths() -> list:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(here)
    out = []
    flag = config.get_flag("NATIVE_LIB")
    if flag:
        out.append(flag)
    out.append(os.path.join(here, "_native", "libspark_rapids_tpu.so"))
    out.append(os.path.join(repo, "build", "libspark_rapids_tpu.so"))
    return out


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.srt_last_error.restype = ctypes.c_char_p
    lib.srt_version.restype = ctypes.c_char_p
    lib.srt_type_width.restype = ctypes.c_int32
    lib.srt_type_width.argtypes = [ctypes.c_int32]
    lib.srt_compute_row_layout.restype = ctypes.c_int
    lib.srt_max_rows_per_batch.restype = ctypes.c_int64
    lib.srt_max_rows_per_batch.argtypes = [ctypes.c_int32]
    lib.srt_pack_rows.restype = ctypes.c_int
    lib.srt_unpack_rows.restype = ctypes.c_int
    lib.srt_buffer_create.restype = ctypes.c_int64
    lib.srt_buffer_create.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_char_p,
    ]
    lib.srt_buffer_alloc.restype = ctypes.c_int64
    lib.srt_buffer_alloc.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.srt_buffer_retain.restype = ctypes.c_int
    lib.srt_buffer_retain.argtypes = [ctypes.c_int64]
    lib.srt_buffer_release.restype = ctypes.c_int
    lib.srt_buffer_release.argtypes = [ctypes.c_int64]
    lib.srt_buffer_data.restype = ctypes.c_void_p
    lib.srt_buffer_data.argtypes = [ctypes.c_int64]
    lib.srt_buffer_size.restype = ctypes.c_int64
    lib.srt_buffer_size.argtypes = [ctypes.c_int64]
    lib.srt_set_refcount_debug.argtypes = [ctypes.c_int]
    lib.srt_live_handle_count.restype = ctypes.c_int64
    lib.srt_leak_report.restype = ctypes.c_int64
    lib.srt_leak_report.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.srt_jax_available.restype = ctypes.c_int32
    lib.srt_jax_init.restype = ctypes.c_int
    lib.srt_jax_platform.restype = ctypes.c_int
    lib.srt_jax_platform.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.srt_jax_table_op.restype = ctypes.c_int
    lib.srt_jax_table_op.argtypes = [
        ctypes.c_char_p,                     # op_json
        ctypes.POINTER(ctypes.c_int32),      # type_ids
        ctypes.POINTER(ctypes.c_int32),      # scales
        ctypes.c_int32,                      # num_columns
        ctypes.POINTER(ctypes.c_int64),      # col_data handles
        ctypes.POINTER(ctypes.c_int64),      # col_valid handles
        ctypes.c_int64,                      # num_rows
        ctypes.c_int32,                      # max_out_columns
        ctypes.POINTER(ctypes.c_int32),      # out_type_ids
        ctypes.POINTER(ctypes.c_int32),      # out_scales
        ctypes.POINTER(ctypes.c_int32),      # out_num_columns
        ctypes.POINTER(ctypes.c_int64),      # out_col_data
        ctypes.POINTER(ctypes.c_int64),      # out_col_valid
        ctypes.POINTER(ctypes.c_int64),      # out_num_rows
    ]
    lib.srt_jax_table_upload.restype = ctypes.c_int
    lib.srt_jax_table_upload.argtypes = [
        ctypes.POINTER(ctypes.c_int32),      # type_ids
        ctypes.POINTER(ctypes.c_int32),      # scales
        ctypes.c_int32,                      # num_columns
        ctypes.POINTER(ctypes.c_int64),      # col_data handles
        ctypes.POINTER(ctypes.c_int64),      # col_valid handles
        ctypes.c_int64,                      # num_rows
        ctypes.POINTER(ctypes.c_int64),      # out_table
    ]
    lib.srt_jax_table_op_resident.restype = ctypes.c_int
    lib.srt_jax_table_op_resident.argtypes = [
        ctypes.c_char_p,                     # op_json
        ctypes.POINTER(ctypes.c_int64),      # inputs
        ctypes.c_int32,                      # num_inputs
        ctypes.POINTER(ctypes.c_int64),      # out_table
    ]
    lib.srt_jax_table_download.restype = ctypes.c_int
    lib.srt_jax_table_download.argtypes = [
        ctypes.c_int64,                      # table
        ctypes.c_int32,                      # max_out_columns
        ctypes.POINTER(ctypes.c_int32),      # out_type_ids
        ctypes.POINTER(ctypes.c_int32),      # out_scales
        ctypes.POINTER(ctypes.c_int32),      # out_num_columns
        ctypes.POINTER(ctypes.c_int64),      # out_col_data
        ctypes.POINTER(ctypes.c_int64),      # out_col_valid
        ctypes.POINTER(ctypes.c_int64),      # out_num_rows
    ]
    lib.srt_jax_table_num_rows.restype = ctypes.c_int
    lib.srt_jax_table_num_rows.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.srt_jax_table_free.restype = ctypes.c_int
    lib.srt_jax_table_free.argtypes = [ctypes.c_int64]
    lib.srt_jax_resident_table_count.restype = ctypes.c_int
    lib.srt_jax_resident_table_count.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Idempotent load (NativeLibraryLoader.java:26-31 contract)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        for path in _candidate_paths():
            if path and os.path.exists(path):
                _lib = _bind(ctypes.CDLL(path))
                return _lib
        _load_failed = True
        return None


def available() -> bool:
    return load() is not None


def reset_for_tests() -> None:
    """Drop the cached load decision (used when tests build the lib)."""
    global _lib, _load_failed
    with _lock:
        _lib = None
        _load_failed = False


def _check(status: int) -> None:
    if status != SRT_OK:
        lib = load()
        msg = lib.srt_last_error().decode() if lib else "native lib missing"
        raise RuntimeError(f"native error ({status}): {msg}")


def _require() -> ctypes.CDLL:
    """load() with the documented failure mode: RuntimeError (never
    AttributeError on None) so callers can catch-and-fall-back."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library not available")
    return lib


def version() -> str:
    return _require().srt_version().decode()


# ---------------------------------------------------------------------------
# row codec over numpy host buffers
# ---------------------------------------------------------------------------

def compute_row_layout(type_ids: Sequence[int]):
    """-> (offsets, widths, validity_offset, validity_bytes, row_size)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library not available")
    n = len(type_ids)
    ids = np.asarray(type_ids, dtype=np.int32)
    offs = np.zeros(n, dtype=np.int32)
    widths = np.zeros(n, dtype=np.int32)

    class _Layout(ctypes.Structure):
        _fields_ = [
            ("num_columns", ctypes.c_int32),
            ("validity_offset", ctypes.c_int32),
            ("validity_bytes", ctypes.c_int32),
            ("row_size", ctypes.c_int32),
        ]

    layout = _Layout()
    _check(
        lib.srt_compute_row_layout(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.byref(layout),
        )
    )
    return (
        offs.tolist(),
        widths.tolist(),
        layout.validity_offset,
        layout.validity_bytes,
        layout.row_size,
    )


def pack_rows(
    type_ids: Sequence[int],
    col_data: Sequence[np.ndarray],
    col_valid: Sequence[Optional[np.ndarray]],
) -> np.ndarray:
    """Host columns -> (n, row_size) uint8 packed rows (native codec)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library not available")
    n_cols = len(type_ids)
    ids = np.asarray(type_ids, dtype=np.int32)
    num_rows = int(col_data[0].shape[0]) if n_cols else 0
    *_, row_size = compute_row_layout(type_ids)

    data_bufs = [np.ascontiguousarray(a) for a in col_data]
    valid_bufs = [
        None if v is None else np.ascontiguousarray(v, dtype=np.uint8)
        for v in col_valid
    ]
    data_ptrs = (ctypes.c_void_p * n_cols)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in data_bufs]
    )
    valid_ptrs = (ctypes.POINTER(ctypes.c_uint8) * n_cols)(
        *[
            ctypes.cast(None, ctypes.POINTER(ctypes.c_uint8))
            if v is None
            else v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            for v in valid_bufs
        ]
    )
    out = np.zeros((num_rows, row_size), dtype=np.uint8)
    _check(
        lib.srt_pack_rows(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_cols,
            data_ptrs,
            valid_ptrs,
            ctypes.c_int64(num_rows),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
    )
    return out


def unpack_rows(
    type_ids: Sequence[int], rows: np.ndarray, widths: Sequence[int]
):
    """(n, row_size) uint8 -> ([col bytes buffers], [validity byte arrays])."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library not available")
    n_cols = len(type_ids)
    ids = np.asarray(type_ids, dtype=np.int32)
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    num_rows = int(rows.shape[0])

    data_out = [np.zeros(num_rows * w, dtype=np.uint8) for w in widths]
    valid_out = [np.zeros(num_rows, dtype=np.uint8) for _ in range(n_cols)]
    data_ptrs = (ctypes.c_void_p * n_cols)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in data_out]
    )
    valid_ptrs = (ctypes.POINTER(ctypes.c_uint8) * n_cols)(
        *[v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) for v in valid_out]
    )
    _check(
        lib.srt_unpack_rows(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_cols,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(num_rows),
            data_ptrs,
            valid_ptrs,
        )
    )
    return data_out, valid_out


# ---------------------------------------------------------------------------
# handle registry
# ---------------------------------------------------------------------------

def buffer_create(data: bytes, tag: str = "") -> int:
    lib = _require()
    h = lib.srt_buffer_create(data, len(data), tag.encode())
    if h == 0:
        _check(1)
    return h


def buffer_release(handle: int) -> None:
    _check(_require().srt_buffer_release(handle))


def buffer_retain(handle: int) -> None:
    _check(_require().srt_buffer_retain(handle))


def buffer_bytes(handle: int) -> bytes:
    lib = _require()
    size = lib.srt_buffer_size(handle)
    if size < 0:
        _check(5)
    if size == 0:
        return b""
    ptr = lib.srt_buffer_data(handle)
    return ctypes.string_at(ptr, size)


def live_handle_count() -> int:
    return _require().srt_live_handle_count()


def set_refcount_debug(enabled: bool) -> None:
    _require().srt_set_refcount_debug(1 if enabled else 0)


def leak_report() -> str:
    lib = _require()
    needed = lib.srt_leak_report(None, 0)
    buf = ctypes.create_string_buffer(int(needed))
    lib.srt_leak_report(buf, needed)
    return buf.value.decode()


# ---------------------------------------------------------------------------
# embedded JAX device runtime (src/cpp/jax_runtime.cpp)
#
# From this (Python) process the library JOINS the live interpreter, so
# a ctypes round trip through these functions exercises the identical
# native code path a JVM embedder takes — minus interpreter startup.
# ---------------------------------------------------------------------------

def jax_runtime_available() -> bool:
    lib = load()
    return lib is not None and lib.srt_jax_available() == 1


def jax_init() -> None:
    _check(_require().srt_jax_init())


def jax_platform() -> str:
    lib = _require()
    buf = ctypes.create_string_buffer(64)
    _check(lib.srt_jax_platform(buf, 64))
    return buf.value.decode()


def jax_table_op(
    op_json: str,
    type_ids: Sequence[int],
    scales: Sequence[int],
    col_data: Sequence[int],
    col_valid: Sequence[Optional[int]],
    num_rows: int,
    max_out_columns: int = 64,
):
    """Dispatch a table op to the device runtime via registry handles.

    -> (out_type_ids, out_scales, out_data_handles, out_valid_handles,
    out_num_rows); output handles are owned by the caller.
    """
    lib = _require()
    n = len(type_ids)
    if not (len(scales) == len(col_data) == len(col_valid) == n):
        # ctypes zero-fills short initializer lists, which would turn a
        # caller bug into silently-wrong scales/validity
        raise ValueError(
            "jax_table_op: type_ids/scales/col_data/col_valid lengths "
            f"differ ({n}/{len(scales)}/{len(col_data)}/{len(col_valid)})"
        )
    ids = (ctypes.c_int32 * n)(*type_ids)
    scl = (ctypes.c_int32 * n)(*scales)
    hd = (ctypes.c_int64 * n)(*col_data)
    hv = (ctypes.c_int64 * n)(*[v or 0 for v in col_valid])
    out_ids = (ctypes.c_int32 * max_out_columns)()
    out_scl = (ctypes.c_int32 * max_out_columns)()
    out_hd = (ctypes.c_int64 * max_out_columns)()
    out_hv = (ctypes.c_int64 * max_out_columns)()
    out_cols = ctypes.c_int32(0)
    out_rows = ctypes.c_int64(0)
    _check(
        lib.srt_jax_table_op(
            op_json.encode(),
            ids,
            scl,
            n,
            hd,
            hv,
            ctypes.c_int64(num_rows),
            max_out_columns,
            out_ids,
            out_scl,
            ctypes.byref(out_cols),
            out_hd,
            out_hv,
            ctypes.byref(out_rows),
        )
    )
    m = out_cols.value
    return (
        list(out_ids[:m]),
        list(out_scl[:m]),
        list(out_hd[:m]),
        [h if h != 0 else None for h in out_hv[:m]],
        out_rows.value,
    )


# ---------------------------------------------------------------------------
# Device-resident table chaining (round-3 VERDICT item 4): upload once,
# chain ops over resident table ids, download once — the reference's
# device-pointer handle model (RowConversionJni.cpp:31,54).
# ---------------------------------------------------------------------------

def jax_table_upload(
    type_ids: Sequence[int],
    scales: Sequence[int],
    col_data: Sequence[int],
    col_valid: Sequence[Optional[int]],
    num_rows: int,
) -> int:
    """Host buffer handles -> device-resident table id."""
    lib = _require()
    n = len(type_ids)
    if not (len(scales) == len(col_data) == len(col_valid) == n):
        raise ValueError("jax_table_upload: column array lengths differ")
    ids = (ctypes.c_int32 * n)(*type_ids)
    scl = (ctypes.c_int32 * n)(*scales)
    hd = (ctypes.c_int64 * n)(*col_data)
    hv = (ctypes.c_int64 * n)(*[v or 0 for v in col_valid])
    out = ctypes.c_int64(0)
    _check(
        lib.srt_jax_table_upload(
            ids, scl, n, hd, hv, ctypes.c_int64(num_rows),
            ctypes.byref(out),
        )
    )
    return out.value


def jax_table_op_resident(op_json: str, inputs: Sequence[int]) -> int:
    """One device op over resident tables; result stays resident."""
    lib = _require()
    n = len(inputs)
    arr = (ctypes.c_int64 * n)(*inputs)
    out = ctypes.c_int64(0)
    _check(
        lib.srt_jax_table_op_resident(
            op_json.encode(), arr, n, ctypes.byref(out)
        )
    )
    return out.value


def jax_table_download(table: int, max_out_columns: int = 64):
    """Resident table -> (ids, scales, data handles, valid handles, rows);
    output handles are owned by the caller."""
    lib = _require()
    out_ids = (ctypes.c_int32 * max_out_columns)()
    out_scl = (ctypes.c_int32 * max_out_columns)()
    out_hd = (ctypes.c_int64 * max_out_columns)()
    out_hv = (ctypes.c_int64 * max_out_columns)()
    out_cols = ctypes.c_int32(0)
    out_rows = ctypes.c_int64(0)
    _check(
        lib.srt_jax_table_download(
            ctypes.c_int64(table), max_out_columns, out_ids, out_scl,
            ctypes.byref(out_cols), out_hd, out_hv, ctypes.byref(out_rows),
        )
    )
    m = out_cols.value
    return (
        list(out_ids[:m]),
        list(out_scl[:m]),
        list(out_hd[:m]),
        [h if h != 0 else None for h in out_hv[:m]],
        out_rows.value,
    )


def jax_table_num_rows(table: int) -> int:
    lib = _require()
    out = ctypes.c_int64(0)
    _check(lib.srt_jax_table_num_rows(ctypes.c_int64(table), ctypes.byref(out)))
    return out.value


def jax_table_free(table: int) -> None:
    lib = _require()
    _check(lib.srt_jax_table_free(ctypes.c_int64(table)))


def jax_resident_table_count() -> int:
    lib = _require()
    out = ctypes.c_int64(0)
    _check(lib.srt_jax_resident_table_count(ctypes.byref(out)))
    return out.value
