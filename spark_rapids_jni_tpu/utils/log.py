"""Runtime observability — the ``RMM_LOGGING_LEVEL`` role (reference
``pom.xml:82``) redesigned for an XLA-owned runtime.

The reference surfaces allocator internals because RMM owns every device
byte; here XLA/PJRT owns allocation, so the observable planes are the
ones THIS runtime owns: the ante-hoc HBM footprint planner's
plan-vs-budget decisions (``utils/hbm.py``), live resident-table /
native-handle counts (``runtime_bridge.py``, the leak-report analog),
and tunnel probe/retry events (``bench.py`` daemon).

One knob gates everything::

    SPARK_RAPIDS_TPU_LOG_LEVEL = OFF|ERROR|WARN|INFO|DEBUG|TRACE

``SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL`` (the direct RMM_LOGGING_LEVEL
analog, declared since round 3) overrides the level for the
allocation-ish channels (``hbm``, ``handles``) specifically, so a user
can trace memory planning without drowning in tunnel chatter.

Format: one line per event to stderr::

    [srt][<channel>][<LEVEL>] <msg> key=value ...

Lines go to stderr unbuffered so they interleave correctly with XLA's
own logging and never corrupt stdout protocols (bench JSON, wire dumps).
"""

from __future__ import annotations

import sys

LEVELS = {
    "OFF": 0,
    "ERROR": 1,
    "WARN": 2,
    "INFO": 3,
    "DEBUG": 4,
    "TRACE": 5,
}

_ALLOC_CHANNELS = frozenset({"hbm", "handles"})

# (flag, value) pairs already warned about — an invalid level must be
# reported exactly once, not on every gated call
_WARNED_INVALID: set = set()


def _warn_invalid_level(flag: str, value: str, fallback: str) -> None:
    """One-time, ungated WARN for a typo'd level value: the user
    explicitly asked for logging, so silently mapping the typo to OFF
    (the pre-fix behavior) silenced the one person who opted in."""
    key = (flag, value)
    if key in _WARNED_INVALID:
        return
    _WARNED_INVALID.add(key)
    print(
        f"[srt][log][WARN] invalid {flag}={value!r} "
        f"(expected {'|'.join(LEVELS)}); falling back to {fallback}",
        file=sys.stderr,
        flush=True,
    )


def _resolve_level(channel: str) -> int:
    from . import config

    if channel in _ALLOC_CHANNELS and config.flag_is_set(
        "ALLOC_LOG_LEVEL"
    ):
        alloc = str(config.get_flag("ALLOC_LOG_LEVEL")).upper()
        if alloc in LEVELS:
            # an explicitly SET value overrides in both directions:
            # ALLOC_LOG_LEVEL=OFF really silences hbm/handles even
            # under LOG_LEVEL=DEBUG
            return LEVELS[alloc]
        # invalid value: fall back to LOG_LEVEL rather than silently
        # killing the channel
        _warn_invalid_level(
            "SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL", alloc, "LOG_LEVEL"
        )
    level = str(config.get_flag("LOG_LEVEL")).upper()
    got = LEVELS.get(level)
    if got is None:
        default = str(config.flag_default("LOG_LEVEL")).upper()
        _warn_invalid_level("SPARK_RAPIDS_TPU_LOG_LEVEL", level, default)
        got = LEVELS.get(default, 0)
    return got


def enabled(level: str, channel: str = "general") -> bool:
    """True when an event at ``level`` on ``channel`` would print —
    callers guard expensive field construction with this."""
    return LEVELS.get(level, 0) <= _resolve_level(channel) and LEVELS.get(
        level, 0
    ) > 0


def log(level: str, channel: str, msg: str, **fields) -> None:
    """Emit one observability line if the channel's level admits it."""
    if not enabled(level, channel):
        return
    suffix = "".join(f" {k}={v}" for k, v in fields.items())
    print(
        f"[srt][{channel}][{level}] {msg}{suffix}",
        file=sys.stderr,
        flush=True,
    )
