"""Query profiler — per-plan EXPLAIN ANALYZE sessions (ISSUE 8 tentpole).

The metrics registry (PR 1) aggregates globally and the flight recorder
(PR 3) keeps a raw timeline; neither answers "why was THIS plan slow?".
This module scopes telemetry to one plan/stream execution — a *profile
session* — and attributes it to the plan's fused segments, the role the
reference ecosystem's profiling/qualification tools play for Spark SQL
plans on device:

* ``with profile_session(plan_json) as prof:`` opens a session around
  one execution. ``runtime_bridge.table_plan_wire`` /
  ``table_plan_resident`` / ``table_stream_wire`` auto-open one when
  ``SPARK_RAPIDS_TPU_PROFILE=on`` (``maybe_session``).
* ``plan.run_plan`` brackets each segment (``segment_begin`` /
  ``segment_end``); instrumented subsystems report into whatever
  segment (or session) is active on their thread: ``buckets.cached_jit``
  reports cache hits/misses and first-call compile time,
  ``runtime_bridge`` wire serde time/bytes, ``pipeline`` stall seconds,
  ``hbm`` donated bytes, ``buckets`` pad rows/waste. Per segment,
  ``execute = wall - compile - serde - stall`` (clamped at 0), so the
  splits sum to the segment wall time by construction; whatever the
  session wall covers that no segment does is reported honestly as
  ``boundary`` (wire serde outside segments, stalls) and
  ``unattributed_s``.
* Compile attribution rides jax's laziness: ``jax.jit`` traces and
  compiles at the FIRST invocation, so the cache-miss winner's first
  call is timed whole and reported as compile time (``time_first_call``)
  — a deliberate first-call≈trace+compile approximation. A forced cache
  miss therefore shows up as compile time on exactly the segment that
  launched it.
* Finished sessions land in a bounded in-process registry, ride flight
  dumps as the ``profile_sessions`` exit section, and are written to
  ``SPARK_RAPIDS_TPU_PROFILE_DUMP`` at exit. ``merge_sessions`` combines
  dumps from multiple processes/hosts into one report keyed by session
  id + ``(pid, host)`` — the multi-process story the ``parallel/`` mesh
  tier and the future serving daemon need (``tools/explain.py --merge``).

Gating follows the ship-it-disabled discipline: the flag gate caches
its verdict against ``config.generation()`` and every hot hook bails on
one module-global bool (``_ACTIVE``) when no session is open — the
~100ns class, asserted in tests/test_profiler.py.

Import discipline: this module imports ONLY ``config`` and ``flight``
(plus stdlib). metrics/buckets/pipeline/hbm/plan/runtime_bridge all
import *it*, so anything heavier here is an import cycle — which is why
the plan-stats hook (``utils/planstats.py``, PR 16) is lazy-imported at
session open/close behind its own cached flag gate, never at module
load.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import socket
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

from . import config
from . import flight
from . import lockcheck
from . import tracing

_HOST = socket.gethostname()

_TRUTHY = frozenset({"1", "true", "yes", "on"})

# ---------------------------------------------------------------------------
# flag gate (the metrics._GATE_GEN discipline)
# ---------------------------------------------------------------------------

_GATE_GEN = -1
_GATE_ON = False
_GATE_STATS = False


def _refresh_gate() -> None:
    global _GATE_GEN, _GATE_ON, _GATE_STATS
    v = config.get_flag("PROFILE")
    on = (v is True) or str(v or "").strip().lower() in _TRUTHY
    # the plan-stats store (utils/planstats.py) records per finished
    # session, so PLANSTATS implies auto-sessions; the flags are read
    # here directly (planstats imports metrics which imports us, so it
    # must never be imported at module load)
    s = config.get_flag("PLANSTATS")
    _GATE_STATS = (
        (s is True) or str(s or "").strip().lower() in _TRUTHY
        or bool(str(config.get_flag("PLANSTATS_DIR") or ""))
    )
    # a configured dump path implies profiling, the
    # METRICS_DUMP-implies-METRICS convention
    _GATE_ON = (
        on
        or bool(str(config.get_flag("PROFILE_DUMP") or ""))
        or _GATE_STATS
    )
    _GATE_GEN = config.generation()


def enabled() -> bool:
    """True when auto-sessions should open (cheap cached gate)."""
    if _GATE_GEN != config.generation():
        _refresh_gate()
    return _GATE_ON


def _planstats_on() -> bool:
    """True when finished sessions should append a stats record
    (same cached gate refresh; no planstats import on this path)."""
    if _GATE_GEN != config.generation():
        _refresh_gate()
    return _GATE_STATS


# ---------------------------------------------------------------------------
# session / segment state
# ---------------------------------------------------------------------------

# every OPEN session, in open order; the module-global fallback target
# for notes arriving on threads with no thread-local session (pipeline
# workers decoding for a stream session on the caller thread)
_OPEN: List["ProfileSession"] = []
_OPEN_LOCK = lockcheck.make_lock("profiler.open")

# THE hot-path gate: True iff any session is open anywhere. Every
# note_* hook reads this one bool first, so the no-session cost is a
# global load + branch regardless of the flag plane.
_ACTIVE = False

_TLS = threading.local()  # .sessions: list, .seg: (session, _Seg) or None

# finished session docs, newest last (bounded: a long-lived daemon must
# not grow a profile registry without bound)
_SESSIONS_KEEP = 64
_SESSIONS: "collections.deque" = collections.deque(maxlen=_SESSIONS_KEEP)
_SESSIONS_LOCK = lockcheck.make_lock("profiler.sessions")

_BOUNDARY_KEYS = (
    "compile_s", "serde_s", "serde_bytes_in", "serde_bytes_out",
    "stall_s", "cache_hits", "cache_misses", "pad_rows",
    "pad_waste_bytes", "donated_bytes", "fallbacks", "shuffle_rows",
    "shuffles",
)


class _Seg:
    """Accumulator for one plan segment (summed across stream batches)."""

    __slots__ = (
        "index", "kind", "ops", "calls", "wall_s", "compile_s",
        "serde_s", "stall_s", "cache_hits", "cache_misses", "rows_in",
        "rows_out", "out_bytes", "pad_rows", "pad_waste_bytes",
        "donated_bytes", "fallbacks",
    )

    def __init__(self, index: int, kind: str, ops: Sequence[str]):
        self.index = index
        self.kind = kind
        self.ops = list(ops)
        self.calls = 0
        self.wall_s = 0.0
        self.compile_s = 0.0
        self.serde_s = 0.0
        self.stall_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self.rows_in = 0
        self.rows_out = 0
        self.out_bytes = 0
        self.pad_rows = 0
        self.pad_waste_bytes = 0
        self.donated_bytes = 0
        self.fallbacks = 0

    def to_doc(self) -> dict:
        execute = max(
            self.wall_s - self.compile_s - self.serde_s - self.stall_s,
            0.0,
        )
        return {
            "index": self.index,
            "kind": self.kind,
            "ops": list(self.ops),
            "calls": self.calls,
            "wall_s": self.wall_s,
            "compile_s": self.compile_s,
            "execute_s": execute,
            "serde_s": self.serde_s,
            "stall_s": self.stall_s,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "launches": self.cache_hits + self.cache_misses,
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "out_bytes": self.out_bytes,
            "pad_rows": self.pad_rows,
            "pad_waste_bytes": self.pad_waste_bytes,
            "donated_bytes": self.donated_bytes,
            "fallbacks": self.fallbacks,
        }


def _schema_token(schema) -> Optional[str]:
    """Normalize a schema argument (ColType sequence or string) to the
    compact comma-joined token the stats store keys on; anything else
    degrades to None — same never-fail rule as :func:`_plan_ops`."""
    if schema is None:
        return None
    if isinstance(schema, str):
        return schema or None
    try:
        return ",".join(c.pretty() for c in schema) or None
    # srt: allow-broad-except(unrecognized schema shape degrades to None; the profiler must never fail the query it observes)
    except Exception:
        return None


def _compact_static(report) -> Optional[dict]:
    """Shrink a plancheck analyze/check report to the prediction fields
    the drift layer compares against — full reports carry per-op
    reasons/schemas that would bloat every stats record."""
    if not isinstance(report, dict):
        return None
    try:
        return {
            "segments": [
                {
                    "kind": s.get("kind"),
                    "ops": list(s.get("ops") or []),
                    "rows_bound": s.get("rows_bound"),
                    "est_hbm_bytes": s.get("est_hbm_bytes"),
                }
                for s in report.get("segments") or []
            ],
            "rows_out_bound": report.get("rows_out_bound"),
            "est_hbm_peak_bytes": report.get("est_hbm_peak_bytes"),
            # statically kernel-eligible op indices (plancheck kernel
            # tier) — lets planstats correlate predicted eligibility
            # with observed kernel.launches/declines
            "kernel_ops": list(report.get("kernel_ops") or []),
        }
    # srt: allow-broad-except(malformed static report degrades to no prediction; the profiler must never fail the query it observes)
    except Exception:
        return None


class ProfileSession:
    """Attribution state for ONE plan/stream execution."""

    def __init__(self, plan=None, label: str = "plan",
                 batches: Optional[int] = None, schema=None,
                 bucket: Optional[int] = None, static=None):
        self.session_id = uuid.uuid4().hex[:16]
        self.label = label
        self.plan = _plan_ops(plan)
        self.pid = os.getpid()
        self.host = _HOST
        self.epoch_ns = time.time_ns()
        # the request trace this session observes (None outside any
        # traced request): lets a tracequery join profile sessions to
        # the flight-ring span tree by one key
        self.trace_id = tracing.current_trace_id()
        self.batches = batches
        # the stats-store key parts + embedded static prediction
        # (planstats drift layer); None when the caller has none
        self.schema = _schema_token(schema)
        self.bucket = int(bucket) if bucket is not None else None
        self.pred = _compact_static(static)
        self._counter_base: Optional[Dict[str, int]] = None
        self.wall_s = 0.0
        self._t0 = time.perf_counter()
        self._lock = lockcheck.make_lock("profiler.session")
        self._segs: Dict[tuple, _Seg] = {}
        self._order: List[tuple] = []
        self.boundary: Dict[str, Any] = {k: 0 for k in _BOUNDARY_KEYS}
        self.boundary["compile_s"] = 0.0
        self.boundary["serde_s"] = 0.0
        self.boundary["stall_s"] = 0.0

    def _seg_for(self, index: int, kind: str, op_names: tuple) -> _Seg:
        key = (index, kind, op_names)
        with self._lock:
            seg = self._segs.get(key)
            if seg is None:
                seg = _Seg(index, kind, op_names)
                self._segs[key] = seg
                self._order.append(key)
            return seg

    def _close(self) -> None:
        self.wall_s = time.perf_counter() - self._t0

    def to_doc(self) -> dict:
        """One JSON-able session record — the profiler's wire format."""
        with self._lock:
            segs = [self._segs[k].to_doc() for k in self._order]
            boundary = dict(self.boundary)
        covered = (
            sum(s["wall_s"] for s in segs)
            + boundary["serde_s"] + boundary["stall_s"]
            + boundary["compile_s"]
        )
        doc = {
            "version": 1,
            "session_id": self.session_id,
            "label": self.label,
            "pid": self.pid,
            "host": self.host,
            "epoch_ns": self.epoch_ns,
            "wall_s": self.wall_s,
            "plan": self.plan,
            "segments": segs,
            "boundary": boundary,
            "unattributed_s": max(self.wall_s - covered, 0.0),
        }
        if self.trace_id is not None:
            doc["trace_id"] = self.trace_id
        if self.batches is not None:
            doc["batches"] = self.batches
        if self.schema is not None:
            doc["schema"] = self.schema
        if self.bucket is not None:
            doc["bucket"] = self.bucket
        if self.pred is not None:
            doc["pred"] = self.pred
        return doc


def _plan_ops(plan) -> Optional[list]:
    """Normalize a plan argument (JSON string, op-dict list, or None)
    to a list of op dicts; anything unparsable degrades to None — a
    profiler must never fail the query it observes."""
    if plan is None:
        return None
    if isinstance(plan, str):
        try:
            plan = json.loads(plan)
        # srt: allow-broad-except(unparsable plan degrades to None; the profiler must never fail the query it observes)
        except Exception:
            return None
    if isinstance(plan, (list, tuple)):
        out = []
        for op in plan:
            if isinstance(op, dict):
                out.append(dict(op))
            else:
                return None
        return out
    return None


def _session_fallback() -> Optional[ProfileSession]:
    """Session for a note with no thread-local binding: the thread's
    innermost session, else the process's most recently opened one
    (worker threads serving a caller-thread session)."""
    stack = getattr(_TLS, "sessions", None)
    if stack:
        return stack[-1]
    open_ = _OPEN  # snapshot the list object; append/pop are atomic
    return open_[-1] if open_ else None


def session_active() -> bool:
    """True iff any profile session is open in this process."""
    return _ACTIVE


def current_session_id() -> Optional[str]:
    """Session id for provenance stamping (``_RESIDENT_META``)."""
    if not _ACTIVE:
        return None
    sess = _session_fallback()
    return sess.session_id if sess is not None else None


# ---------------------------------------------------------------------------
# session scopes
# ---------------------------------------------------------------------------


class _SessionScope:
    """Context manager binding a new session to the opening thread (and
    as the process-wide fallback for worker-thread notes)."""

    def __init__(self, plan=None, label: str = "plan",
                 batches: Optional[int] = None, schema=None,
                 bucket: Optional[int] = None, static=None):
        self._plan = plan
        self._label = label
        self._batches = batches
        self._schema = schema
        self._bucket = bucket
        self._static = static
        self.session: Optional[ProfileSession] = None

    def __enter__(self) -> ProfileSession:
        global _ACTIVE
        sess = ProfileSession(
            self._plan, self._label, self._batches,
            schema=self._schema, bucket=self._bucket,
            static=self._static,
        )
        self.session = sess
        if _planstats_on():
            try:
                from . import planstats
                sess._counter_base = planstats.counter_snapshot()
            # srt: allow-broad-except(stats capture must never fail the query it observes)
            except Exception:
                sess._counter_base = None
        stack = getattr(_TLS, "sessions", None)
        if stack is None:
            stack = _TLS.sessions = []
        stack.append(sess)
        with _OPEN_LOCK:
            _OPEN.append(sess)
            _ACTIVE = True
        # correlate with the flight timeline + stamp the dump's process
        # metadata so multi-process merges can line traces up
        flight.set_process_meta(session_id=sess.session_id)
        if flight.enabled():
            flight.record("I", "profile.session", sess.session_id)
        return sess

    def __exit__(self, exc_type, exc, tb) -> bool:
        global _ACTIVE
        sess = self.session
        if sess is None:
            return False
        sess._close()
        stack = getattr(_TLS, "sessions", None)
        if stack and sess in stack:
            stack.remove(sess)
        with _OPEN_LOCK:
            if sess in _OPEN:
                _OPEN.remove(sess)
            _ACTIVE = bool(_OPEN)
        doc = sess.to_doc()
        with _SESSIONS_LOCK:
            _SESSIONS.append(doc)
        if _planstats_on():
            try:
                from . import planstats
                planstats.record_session(doc, sess._counter_base)
            # srt: allow-broad-except(stats persistence must never fail the query it observes)
            except Exception:
                pass
        return False


class bound_session:
    """Bind an already-OPEN :class:`ProfileSession` to the calling
    thread for the scope's duration.

    The serving daemon's executor threads interleave work from many
    tenants while several sessions are open at once; without an
    explicit binding their notes would fall through to the process-wide
    ``_OPEN[-1]`` fallback — i.e. whichever tenant opened a session
    most recently, not the tenant whose plan is actually running.
    ``sess=None`` is a no-op (work executed outside any stream)."""

    __slots__ = ("_sess",)

    def __init__(self, sess: Optional[ProfileSession]):
        self._sess = sess

    def __enter__(self):
        sess = self._sess
        if sess is not None:
            stack = getattr(_TLS, "sessions", None)
            if stack is None:
                stack = _TLS.sessions = []
            stack.append(sess)
        return sess

    def __exit__(self, exc_type, exc, tb) -> bool:
        sess = self._sess
        if sess is not None:
            stack = getattr(_TLS, "sessions", None)
            if stack and sess in stack:
                stack.remove(sess)
        return False


class _NullScope:
    """Shared no-op scope: the disabled ``maybe_session`` return."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SCOPE = _NullScope()


def profile_session(plan=None, label: str = "plan",
                    batches: Optional[int] = None, schema=None,
                    bucket: Optional[int] = None,
                    static=None) -> _SessionScope:
    """Explicit API: ``with profile_session(plan_json) as prof:`` scopes
    one plan/stream execution; ``prof.to_doc()`` (or
    ``profiler.sessions()[-1]`` after exit) is the structured record.
    Always collects, regardless of the PROFILE flag. ``schema`` /
    ``bucket`` / ``static`` (a plancheck report) key and seed the
    plan-stats record when PLANSTATS is on."""
    return _SessionScope(plan, label, batches, schema=schema,
                         bucket=bucket, static=static)


def maybe_session(plan=None, label: str = "plan",
                  batches: Optional[int] = None, schema=None,
                  bucket: Optional[int] = None, static=None):
    """Auto-session for the runtime_bridge entries: a real scope when
    ``SPARK_RAPIDS_TPU_PROFILE`` is on and this thread has no session
    yet (an explicit outer session owns nested plan runs), else the
    shared no-op — the disabled path is a cached-gate check plus one
    thread-local read."""
    if not enabled():
        return _NULL_SCOPE
    if getattr(_TLS, "sessions", None):
        return _NULL_SCOPE
    return _SessionScope(plan, label, batches, schema=schema,
                         bucket=bucket, static=static)


# ---------------------------------------------------------------------------
# attribution hooks (called by plan/buckets/pipeline/hbm/runtime_bridge)
#
# Every hook's first move is the _ACTIVE load — the no-session cost.
# Notes bind to the thread's current segment when one is open, else to
# the fallback session's boundary bucket (wire serde on pipeline
# workers, stalls between batches).
# ---------------------------------------------------------------------------


def segment_begin(index: int, kind: str, seg_ops: Sequence[dict],
                  rows_in: Optional[int] = None):
    """Open segment ``index`` on this thread; returns an opaque token
    for ``segment_end`` (None when no session is active)."""
    if not _ACTIVE:
        return None
    sess = _session_fallback()
    if sess is None:
        return None
    names = tuple(str(op.get("op", "?")) for op in seg_ops)
    seg = sess._seg_for(index, kind, names)
    with sess._lock:
        seg.calls += 1
        if rows_in:
            seg.rows_in += int(rows_in)
    prev = getattr(_TLS, "seg", None)
    _TLS.seg = (sess, seg)
    return (sess, seg, time.perf_counter(), prev)


def segment_end(token, rows_out: Optional[int] = None,
                out_bytes: int = 0, fallback: bool = False) -> None:
    if token is None:
        return
    sess, seg, t0, prev = token
    dur = time.perf_counter() - t0
    with sess._lock:
        seg.wall_s += dur
        if rows_out:
            seg.rows_out += int(rows_out)
        if out_bytes:
            seg.out_bytes += int(out_bytes)
        if fallback:
            seg.fallbacks += 1
    _TLS.seg = prev


def _target():
    """(session, segment-or-None) the calling thread's notes bind to."""
    entry = getattr(_TLS, "seg", None)
    if entry is not None:
        return entry
    sess = _session_fallback()
    return (sess, None) if sess is not None else (None, None)


def note_cache(hit: bool) -> None:
    """One compiled-executable cache lookup (buckets.cached_jit)."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    field = "cache_hits" if hit else "cache_misses"
    with sess._lock:
        if seg is not None:
            setattr(seg, field, getattr(seg, field) + 1)
        else:
            sess.boundary[field] += 1


def note_compile(name: str, seconds: float) -> None:
    """First-call (trace+compile) seconds of a cache-miss executable."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.compile_s += seconds
        else:
            sess.boundary["compile_s"] += seconds


def note_serde(direction: str, seconds: float, nbytes: int) -> None:
    """One wire serialize/deserialize pass (``direction`` in/out)."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.serde_s += seconds
        else:
            sess.boundary["serde_s"] += seconds
        sess.boundary[
            "serde_bytes_in" if direction == "in" else "serde_bytes_out"
        ] += int(nbytes)


def note_stall(seconds: float) -> None:
    """Pipeline backpressure/input wait seconds (pipeline._note_stall)."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.stall_s += seconds
        else:
            sess.boundary["stall_s"] += seconds


def note_pad(pad_rows: int, waste_bytes: int) -> None:
    """Bucket padding applied to a table (buckets._record_pad_metrics)."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.pad_rows += int(pad_rows)
            seg.pad_waste_bytes += int(waste_bytes)
        else:
            sess.boundary["pad_rows"] += int(pad_rows)
            sess.boundary["pad_waste_bytes"] += int(waste_bytes)


def note_donation(nbytes: int) -> None:
    """Buffer bytes donated in place (hbm.note_donation)."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.donated_bytes += int(nbytes)
        else:
            sess.boundary["donated_bytes"] += int(nbytes)


def note_fallback(kind: str) -> None:
    """A fused/bucketed dispatch fell back to the exact path."""
    if not _ACTIVE:
        return
    sess, seg = _target()
    if sess is None:
        return
    with sess._lock:
        if seg is not None:
            seg.fallbacks += 1
        else:
            sess.boundary["fallbacks"] += 1


def note_shuffle(rows: int) -> None:
    """One mesh shuffle exchange (parallel/shuffle.py)."""
    if not _ACTIVE:
        return
    sess, _seg = _target()
    if sess is None:
        return
    with sess._lock:
        sess.boundary["shuffles"] += 1
        sess.boundary["shuffle_rows"] += int(rows)


def time_first_call(fn, name: str):
    """Wrap a freshly-jitted callable so its FIRST invocation — the one
    jax traces and compiles on — is timed whole and reported via
    ``note_compile`` on whatever segment launches it. The wrapper is
    transient (the compile cache keeps the raw callable), so steady
    state pays nothing."""
    done = [False]

    def wrapper(*args, **kwargs):
        if done[0]:
            return fn(*args, **kwargs)
        done[0] = True
        # the compile span: trace-tagged on the flight ring, so the
        # request that paid the cache miss shows the trace+compile
        # wall in its merged trace (profiler sits below metrics in the
        # import graph — the tracing span pair is the sanctioned path)
        tok = tracing.span_begin("compile.jit")
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            note_compile(name, time.perf_counter() - t0)
            tracing.span_end(tok)

    wrapper.__name__ = getattr(fn, "__name__", name)
    return wrapper


# ---------------------------------------------------------------------------
# registry / dump / merge plane
# ---------------------------------------------------------------------------


def sessions(reset: bool = False) -> List[dict]:
    """Finished session docs, oldest first (bounded to the last
    ``_SESSIONS_KEEP``)."""
    with _SESSIONS_LOCK:
        out = list(_SESSIONS)
        if reset:
            _SESSIONS.clear()
    return out


def reset() -> None:
    """Drop finished sessions AND abandon open ones (test isolation)."""
    global _ACTIVE, _GATE_GEN
    with _SESSIONS_LOCK:
        _SESSIONS.clear()
    with _OPEN_LOCK:
        _OPEN.clear()
        _ACTIVE = False
    _TLS.sessions = []
    _TLS.seg = None
    _GATE_GEN = -1


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write finished sessions as JSON to ``path`` (default: the
    ``SPARK_RAPIDS_TPU_PROFILE_DUMP`` flag). The flight.dump()
    discipline: failures WARN instead of raising."""
    path = path or str(config.get_flag("PROFILE_DUMP") or "")
    if not path:
        return None
    doc = {
        "version": 1,
        "pid": os.getpid(),
        "host": _HOST,
        "sessions": sessions(),
    }
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return path
    except OSError as e:
        print(
            f"[srt][profiler][WARN] profile dump to {path!r} failed: {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def extract_sessions(doc) -> List[dict]:
    """Session docs found in ``doc``: a raw session, a profile dump
    (``{"sessions": [...]}``), a flight dump (``sections.
    profile_sessions``), a bench summary (per-config ``profile``
    blocks), or a list of any of those."""
    out: List[dict] = []
    if isinstance(doc, list):
        for d in doc:
            out.extend(extract_sessions(d))
        return out
    if not isinstance(doc, dict):
        return out
    if "segments" in doc and "session_id" in doc:
        return [doc]
    if isinstance(doc.get("sessions"), list):
        return [s for s in doc["sessions"] if isinstance(s, dict)]
    sections = doc.get("sections")
    if isinstance(sections, dict) and isinstance(
        sections.get("profile_sessions"), list
    ):
        return [
            s for s in sections["profile_sessions"] if isinstance(s, dict)
        ]
    summary = doc.get("parsed") or doc
    for e in summary.get("configs", []) or []:
        prof = e.get("profile") if isinstance(e, dict) else None
        if isinstance(prof, dict):
            # a bench block aggregates but keeps the last few full
            # session docs under "sessions_tail"
            tail = prof.get("sessions_tail") or prof.get("sessions")
            if isinstance(tail, list):
                out.extend(s for s in tail if isinstance(s, dict))
    return out


def merge_sessions(docs: Sequence) -> dict:
    """Combine session/dump docs from multiple processes/hosts into ONE
    report document: sessions ordered on the shared wall-clock timeline
    (``epoch_ns``), with a per-process index keyed by ``(pid, host)`` —
    the multi-process merge the mesh tier's one-dump-per-process
    reality needs."""
    sess: List[dict] = []
    for d in docs:
        sess.extend(extract_sessions(d))
    sess.sort(key=lambda s: (s.get("epoch_ns") or 0, s.get("session_id", "")))
    procs: Dict[tuple, list] = {}
    for s in sess:
        procs.setdefault((str(s.get("host", "?")), s.get("pid")), []).append(
            s.get("session_id")
        )
    return {
        "version": 1,
        "processes": [
            {"host": h, "pid": p, "session_ids": ids}
            for (h, p), ids in sorted(procs.items(), key=lambda kv: (
                kv[0][0], str(kv[0][1]),
            ))
        ],
        "sessions": sess,
    }


def summarize(docs: Optional[Sequence[dict]] = None) -> dict:
    """Aggregate per-segment summary across session docs — the compact
    ``profile`` block bench.py embeds per config (full session docs
    would bloat a many-batch config's record)."""
    if docs is None:
        docs = sessions()
    segs: Dict[tuple, dict] = {}
    order: List[tuple] = []
    wall = 0.0
    for s in docs:
        wall += float(s.get("wall_s") or 0.0)
        for sd in s.get("segments", []) or []:
            key = (sd.get("index"), sd.get("kind"), tuple(sd.get("ops", [])))
            agg = segs.get(key)
            if agg is None:
                agg = {
                    "index": sd.get("index"),
                    "kind": sd.get("kind"),
                    "ops": list(sd.get("ops", [])),
                }
                segs[key] = agg
                order.append(key)
            for f in (
                "calls", "wall_s", "compile_s", "execute_s", "serde_s",
                "stall_s", "cache_hits", "cache_misses", "launches",
                "rows_in", "rows_out", "pad_rows", "pad_waste_bytes",
                "donated_bytes", "fallbacks",
            ):
                agg[f] = agg.get(f, 0) + (sd.get(f) or 0)
    return {
        "sessions": len(list(docs)),
        "wall_s": wall,
        "segments": [segs[k] for k in order],
    }


def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess
    dump()


atexit.register(_dump_at_exit)
# finished sessions ride every flight dump: one postmortem file carries
# the timeline AND the per-plan attribution that explains it
flight.register_exit_section("profile_sessions", lambda: sessions())
