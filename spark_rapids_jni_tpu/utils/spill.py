"""Tiered memory hierarchy: HBM -> host RAM -> disk spill.

The reference survives memory pressure because RMM pools plus the
plugin's spill framework (RapidsBufferCatalog and its device/host/disk
buffer stores) let a Spark task degrade to SLOWER instead of dying.
Our fault plane classifies ``ResourceExhausted`` and chunk-replays
row-local segments (utils/faults.py, plan.py), and the serving daemon
sheds with typed ``OverBudget``/``Busy`` (serving/) — but until this
module nothing ever moved a cold buffer off the device, so a tenant
over budget was rejected and a working set larger than HBM died.

Design:

* Every device-resident table (runtime_bridge registry) has a residency
  state: ``device`` (a live Table), ``host`` (numpy copies of its
  storage buffers), or ``disk`` (an .npz file under ``SPILL_DIR``).
  Storage buffers round-trip EXACTLY (FLOAT64 is already stored as its
  uint64 bit pattern — column.storage_host_view), so spill/repage is
  byte-identical by construction.
* Eviction is LRU by last touch: every registry access stamps a
  monotonic clock; pressure picks the coldest UNREFERENCED tables.
  "Referenced" reuses the registry's own in-flight accounting — a
  table with live pipelined readers (``_RESIDENT_READERS``), active
  wire downloads (``_RESIDENT_ACTIVE_READS``), or an explicit pin
  (sync dispatch paths) is never evicted: the pin wins.
* Pressure sources: serving admission about to shed (session.admit),
  a dispatch raising typed ``ResourceExhausted`` (plan.py's OOM ladder
  rung 1), an hbm plan that does not fit (hbm pressure listeners), and
  proactive demotion when the tracked device tier passes
  ``hbm.budget_bytes()`` on a new put.
* Host tier is bounded by ``HOST_SPILL_BUDGET_GB``; past it the
  coldest host entries demote to disk, with the file write offloaded
  to the pipeline's dedicated IO worker (pipeline.submit_io) so
  compute overlaps eviction. Repage resolves any pending write first.
* Observability by construction: ``spill.bytes_{out,in}`` /
  ``spill.evictions`` / ``spill.demotions`` counters, per-tier byte
  gauges with high-water marks, flight instants for every
  eviction/repage, and repage stalls attributed to the profiler's
  stall channel (utils/profiler.note_stall).

Flag plane: ``SPARK_RAPIDS_TPU_SPILL`` (off by default — the shipped
path costs one cached generation compare per registry access),
``SPARK_RAPIDS_TPU_SPILL_DIR``, ``SPARK_RAPIDS_TPU_HOST_SPILL_BUDGET_GB``
(utils/config.py). Leftover spill files are swept at exit.
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import tempfile
import threading
import time as _time
from collections import deque
from typing import Optional

import numpy as np

from . import config
from . import faults
from . import flight
from . import hbm
from . import lockcheck
from . import log
from . import metrics
from . import profiler

GIB = 1 << 30

DEVICE = "device"
HOST = "host"
DISK = "disk"

# ---------------------------------------------------------------------------
# flag gates (the faults.py discipline: disabled costs one generation
# compare, not an environ read per registry access)
# ---------------------------------------------------------------------------

_GATE = (None, False)


def _truthy(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def enabled() -> bool:
    global _GATE
    gen = config.generation()
    if _GATE[0] != gen:
        _GATE = (gen, _truthy(config.get_flag("SPILL")))
    return _GATE[1]


def spill_dir() -> str:
    """Directory for disk-tier files; created lazily. The default is a
    per-process directory under the system temp dir, removed at exit
    when empty (no orphaned spill files)."""
    d = str(config.get_flag("SPILL_DIR") or "").strip()
    if not d:
        d = os.path.join(
            tempfile.gettempdir(), f"srt-spill-{os.getpid()}"
        )
    os.makedirs(d, exist_ok=True)
    return d


def host_budget_bytes() -> int:
    """Host-RAM tier budget; past it the coldest host entries demote to
    disk. 0 = skip the host tier (spill straight to disk)."""
    return int(float(config.get_flag("HOST_SPILL_BUDGET_GB")) * GIB)


# ---------------------------------------------------------------------------
# registry binding: the spill tier operates UNDER the resident
# registry's own lock (runtime_bridge binds its structures at import),
# so eviction vs capture vs reclaim ordering is decided by exactly one
# lock — the same one the donate barrier and active-read drain use.
# ---------------------------------------------------------------------------

_REG_LOCK = None            # runtime_bridge._RESIDENT_LOCK (RLock)
_REG_TABLES: Optional[dict] = None   # id -> Table | Pending | SpilledTable
_REG_READERS: Optional[dict] = None  # id -> [in-flight reader Pendings]
_REG_ACTIVE_READS: Optional[dict] = None  # id -> wire-download count


def bind_registry(lock, tables, readers, active_reads) -> None:
    global _REG_LOCK, _REG_TABLES, _REG_READERS, _REG_ACTIVE_READS
    _REG_LOCK = lock
    _REG_TABLES = tables
    _REG_READERS = readers
    _REG_ACTIVE_READS = active_reads


# ---------------------------------------------------------------------------
# tracking state (guarded by the bound registry lock unless noted)
# ---------------------------------------------------------------------------

_CLOCK = itertools.count(1)
_LAST_TOUCH: dict = {}      # id -> monotonic touch stamp (GIL-atomic)
_TRACK: dict = {}           # id -> device bytes, for DEVICE-tier entries
_PINS: dict = {}            # id -> explicit pin count (sync dispatches)

_DEVICE_BYTES = 0           # tracked device-tier total
_HOST_BYTES = 0             # host-tier total (actual numpy bytes)
_DISK_BYTES = 0             # disk-tier total
_HOST_HW = 0
_DISK_HW = 0

_FILE_SEQ = itertools.count(1)
_FILES: set = {*()}         # disk paths this process created, for the sweep

# Residency events for the serving tier (session budget credit on
# spill-out, re-charge on repage). Fired DEFERRED — never while the
# registry lock is held — because listeners take Session locks and a
# teardown path holds a Session lock while taking the registry lock
# (table_reclaim): firing inline would be a lock-order inversion.
_EVENTS_LOCK = lockcheck.make_lock("spill.events")
_EVENTS: deque = deque()
_RESIDENCY_LISTENERS: list = []


def register_residency_listener(fn) -> None:
    """Register ``fn(event, table_id, nbytes)`` with event ``"out"``
    (table left the device tier) or ``"in"`` (repaged back). Fired from
    ``flush_events()`` with no spill/registry lock held; listeners must
    not raise."""
    if fn not in _RESIDENCY_LISTENERS:
        _RESIDENCY_LISTENERS.append(fn)


def flush_events() -> None:
    """Deliver queued residency events (see register_residency_listener).
    Called by the bridge right after it releases the registry lock at
    every repage site, and by request_headroom before returning."""
    while _EVENTS:  # cheap empty check before any lock (hot paths)
        with _EVENTS_LOCK:
            if not _EVENTS:
                return
            ev = _EVENTS.popleft()
        for fn in tuple(_RESIDENCY_LISTENERS):
            fn(*ev)


def _queue_event(event: str, tid: int, nbytes: int) -> None:
    if not _RESIDENCY_LISTENERS:
        return
    with _EVENTS_LOCK:
        _EVENTS.append((event, tid, nbytes))


# ---------------------------------------------------------------------------
# the spilled entry: what replaces a Table in the resident registry
# ---------------------------------------------------------------------------


class SpilledTable:
    """Host/disk backing of one evicted resident table.

    ``cols`` (host state) is a list of per-column tuples
    ``(type_id, scale, data, validity, lengths)`` holding numpy copies
    of the DEVICE storage buffers — already in storage layout, so
    repage is a pure batched upload. On demotion the buffers move into
    the disk-write closure (``_write``, a pipeline IO Pending returning
    the path); repage resolves it first, so a demotion in flight is
    never a correctness hazard, only a latency one."""

    __slots__ = (
        "tid", "state", "nbytes", "host_nbytes", "names", "rows",
        "logical_rows", "cols", "path", "_write",
    )

    def __init__(self, tid, nbytes, host_nbytes, names, rows,
                 logical_rows, cols):
        self.tid = tid
        self.state = HOST
        self.nbytes = nbytes            # device bytes freed / re-added
        self.host_nbytes = host_nbytes  # actual host payload bytes
        self.names = names
        self.rows = rows                # logical row count (leak report)
        self.logical_rows = logical_rows
        self.cols = cols
        self.path = None
        self._write = None

    @property
    def num_columns(self) -> int:
        return len(self.cols) if self.cols is not None else 0


def _device_arrays(col) -> list:
    out = []
    for name in ("data", "validity", "lengths"):
        a = getattr(col, name, None)
        if a is not None and hasattr(a, "delete"):
            out.append(a)
    return out


def _host_copy(a) -> np.ndarray:
    # np.array(copy=True): on the CPU backend np.asarray can be a
    # ZERO-COPY view of the device buffer we are about to delete
    return np.array(a, copy=True)


# ---------------------------------------------------------------------------
# bookkeeping hooks called by the bridge (hot paths: one cached gate)
# ---------------------------------------------------------------------------


def note_put(tid: int, table) -> None:
    """Track a device-resident table. Idempotent per id; also the
    proactive pressure point — a put that carries the tracked device
    tier past ``hbm.budget_bytes()`` evicts the coldest entries first,
    which is how a stream whose working set exceeds HBM keeps running
    instead of dying."""
    global _DEVICE_BYTES
    if not enabled() or _REG_LOCK is None:
        return
    tid = int(tid)
    try:
        nbytes = int(hbm.table_bytes(table))
    # srt: allow-broad-except(an unsizeable table is untrackable, not an error; the exact path still owns it)
    except Exception:
        return
    with _REG_LOCK:
        if tid not in _REG_TABLES:
            return  # freed while we sized it
        prev = _TRACK.get(tid)
        _TRACK[tid] = nbytes
        _DEVICE_BYTES += nbytes - (prev or 0)
        _LAST_TOUCH[tid] = next(_CLOCK)
        excess = _DEVICE_BYTES - hbm.budget_bytes()
    if excess > 0:
        request_headroom(excess, reason="put", exclude=(tid,))


def touch(tid: int) -> None:
    """LRU stamp on registry access (dict write; GIL-atomic — a stale
    stamp only makes LRU slightly less exact, never incorrect)."""
    if not enabled():
        return
    _LAST_TOUCH[int(tid)] = next(_CLOCK)


def note_free(tid: int, entry=None) -> int:
    """Drop all tracking for a freed/reclaimed/donated id; when the
    popped registry entry was a ``SpilledTable``, release its host or
    disk backing too (no orphaned spill files). Returns the device-tier
    bytes the entry would have occupied (the reclaim credit for a
    spilled table)."""
    global _DEVICE_BYTES, _HOST_BYTES, _DISK_BYTES
    if _REG_LOCK is None:
        return 0
    tid = int(tid)
    write = path = None
    nbytes = 0
    with _REG_LOCK:
        _LAST_TOUCH.pop(tid, None)
        _PINS.pop(tid, None)
        tracked = _TRACK.pop(tid, None)
        if tracked:
            _DEVICE_BYTES -= tracked
        if isinstance(entry, SpilledTable):
            nbytes = entry.nbytes
            if entry.state == HOST:
                _HOST_BYTES -= entry.host_nbytes
            else:
                _DISK_BYTES -= entry.host_nbytes
            entry.cols = None
            write, path = entry._write, entry.path
            entry._write = None
    if write is not None or path is not None:
        _drop_backing(write, path)
        _tier_gauges()
    return int(nbytes)


def _drop_backing(write, path) -> None:
    """Release a disk entry's file, resolving an in-flight IO write
    first (the write closure owns the buffers; waiting it out is the
    simple way to guarantee no file lands after the unlink)."""
    if write is not None:
        try:
            path = write.resolve()
        # srt: allow-broad-except(a failed IO write left nothing on disk; there is no file to unlink)
        except Exception:
            path = None  # the write itself failed: nothing on disk
    if path:
        try:
            os.unlink(path)
        except OSError:
            pass
        _FILES.discard(path)


def pin_ids(ids) -> tuple:
    """Explicitly pin ids against eviction (sync dispatch paths, where
    no reader Pending exists to reuse). Must be called under the
    registry lock or before any concurrent evictor can see the ids.
    Returns the pinned tuple for the matching ``unpin_ids``."""
    if not enabled() or _REG_LOCK is None:
        return ()
    out = tuple(int(t) for t in ids)
    with _REG_LOCK:
        for t in out:
            _PINS[t] = _PINS.get(t, 0) + 1
    return out


def unpin_ids(ids) -> None:
    if _REG_LOCK is None:
        return
    with _REG_LOCK:
        for t in ids:
            n = _PINS.get(int(t), 0) - 1
            if n > 0:
                _PINS[int(t)] = n
            else:
                _PINS.pop(int(t), None)


def residency_of(entry) -> str:
    """Residency tier of one registry entry (for leak_report)."""
    if isinstance(entry, SpilledTable):
        return entry.state
    return DEVICE


# ---------------------------------------------------------------------------
# eviction: device -> host (-> disk past the host budget)
# ---------------------------------------------------------------------------


def _buffer_counts_locked() -> dict:
    """id(device buffer) -> number of live registry tables holding it.
    A buffer seen by MORE than one table must never be deleted out from
    under the other (aliasing op outputs) — such tables are simply not
    eviction candidates this round."""
    counts: dict = {}
    for o in _REG_TABLES.values():
        if hasattr(o, "value_nowait"):  # a pipeline.Pending
            o = o.value_nowait()
            if o is None:
                continue
        cols = getattr(o, "columns", None)
        if cols is None:
            continue
        for c in cols:
            for a in _device_arrays(c):
                counts[id(a)] = counts.get(id(a), 0) + 1
    return counts


def _evictable_locked(tid, entry, exclude, counts) -> bool:
    if tid in exclude or getattr(entry, "columns", None) is None:
        return False  # Pending or already spilled
    if _PINS.get(tid) or _REG_ACTIVE_READS.get(tid):
        return False  # the pin wins
    readers = _REG_READERS.get(tid)
    if readers and any(not p.done() for p in readers):
        return False
    for c in entry.columns:
        arrs = _device_arrays(c)
        if not arrs:
            return False
        for a in arrs:
            if counts.get(id(a), 0) > 1:
                return False  # aliased buffer
            try:
                if a.is_deleted():
                    return False  # consumed by a donated executable
            # srt: allow-broad-except(backends without is_deleted: assume live and evictable)
            except Exception:
                pass
    return True


def _evict_one_locked(tid: int, table) -> int:
    """Spill one device table to the host tier; returns device bytes
    freed. Runs under the registry lock: the readback is a stall for
    concurrent registry ops, but correctness needs the swap (copy out,
    delete, replace with the SpilledTable) to be atomic vs capture."""
    global _DEVICE_BYTES, _HOST_BYTES, _HOST_HW
    faults.inject("spill")
    nbytes = int(hbm.table_bytes(table))
    cols = []
    host_nbytes = 0
    for c in table.columns:
        data = _host_copy(c.data)
        validity = None if c.validity is None else _host_copy(c.validity)
        lengths = None if c.lengths is None else _host_copy(c.lengths)
        host_nbytes += data.nbytes
        host_nbytes += validity.nbytes if validity is not None else 0
        host_nbytes += lengths.nbytes if lengths is not None else 0
        cols.append(
            (int(c.dtype.id), int(c.dtype.scale), data, validity, lengths)
        )
    entry = SpilledTable(
        tid, nbytes, host_nbytes,
        None if table.names is None else list(table.names),
        int(table.logical_row_count), table.logical_rows, cols,
    )
    for c in table.columns:
        for a in _device_arrays(c):
            try:
                a.delete()
            # srt: allow-broad-except(aliased or already-deleted device buffer; the host copy is authoritative now)
            except Exception:
                pass
    _REG_TABLES[tid] = entry
    tracked = _TRACK.pop(tid, None)
    if tracked:
        _DEVICE_BYTES -= tracked
    _HOST_BYTES += host_nbytes
    _HOST_HW = max(_HOST_HW, _HOST_BYTES)
    metrics.counter_add("spill.evictions")
    metrics.bytes_add("spill.bytes_out", nbytes)
    if flight.enabled():
        flight.record("I", "spill.out", nbytes)
    log.log("INFO", "spill", "evict", table_id=tid, bytes=nbytes,
            host_bytes=_HOST_BYTES)
    _queue_event("out", tid, nbytes)
    return nbytes


def _demote_one_locked(entry: SpilledTable) -> None:
    """Move one host entry's payload to disk: the numpy buffers transfer
    into a write closure run on the pipeline IO worker, so the file
    write overlaps whatever compute triggered the pressure."""
    global _HOST_BYTES, _DISK_BYTES, _DISK_HW
    from .. import pipeline

    path = os.path.join(
        spill_dir(),
        f"srt-spill-{os.getpid()}-{entry.tid}-{next(_FILE_SEQ)}.npz",
    )
    cols, entry.cols = entry.cols, None
    meta = {
        "type_ids": [c[0] for c in cols],
        "scales": [c[1] for c in cols],
        "names": entry.names,
        "logical_rows": entry.logical_rows,
    }

    def write():
        arrays = {
            "meta": np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8
            ),
        }
        for i, (_, _, data, validity, lengths) in enumerate(cols):
            arrays[f"d{i}"] = data
            if validity is not None:
                arrays[f"v{i}"] = validity
            if lengths is not None:
                arrays[f"l{i}"] = lengths
        with open(path, "wb") as f:
            np.savez(f, **arrays)
        return path

    entry.state = DISK
    entry.path = path
    entry._write = pipeline.submit_io(write, "spill.write")
    _FILES.add(path)
    _HOST_BYTES -= entry.host_nbytes
    _DISK_BYTES += entry.host_nbytes
    _DISK_HW = max(_DISK_HW, _DISK_BYTES)
    metrics.counter_add("spill.demotions")
    metrics.bytes_add("spill.disk_bytes_out", entry.host_nbytes)
    if flight.enabled():
        flight.record("I", "spill.demote", entry.host_nbytes)
    log.log("INFO", "spill", "demote", table_id=entry.tid, path=path)


def _rebalance_host_locked() -> None:
    """Demote coldest host entries until the host tier fits its budget
    (a 0 budget skips the host tier outright — everything demotes)."""
    budget = host_budget_bytes()
    while _HOST_BYTES > budget:
        coldest = None
        for tid, o in _REG_TABLES.items():
            if isinstance(o, SpilledTable) and o.state == HOST:
                stamp = _LAST_TOUCH.get(tid, 0)
                if coldest is None or stamp < coldest[0]:
                    coldest = (stamp, o)
        if coldest is None:
            return
        _demote_one_locked(coldest[1])


def request_headroom(
    need_bytes: int, reason: str = "pressure", exclude=()
) -> int:
    """Evict the coldest unreferenced device tables until ``need_bytes``
    of device-tier bytes are freed (or no candidates remain). Returns
    the bytes actually freed. The pressure entry point for serving
    admission (session.admit), the plan OOM ladder, hbm plan
    listeners, and proactive puts."""
    if not enabled() or _REG_TABLES is None:
        return 0
    need = max(int(need_bytes), 1)
    freed = 0
    exclude = {int(t) for t in exclude}
    with _REG_LOCK:
        counts = _buffer_counts_locked()
        candidates = sorted(
            (
                (_LAST_TOUCH.get(tid, 0), tid, o)
                for tid, o in _REG_TABLES.items()
                if _evictable_locked(tid, o, exclude, counts)
            ),
        )
        for _, tid, table in candidates:
            if freed >= need:
                break
            try:
                freed += _evict_one_locked(tid, table)
            except faults.FaultError:
                metrics.counter_add("spill.errors")
                continue  # chaos: this victim failed, try the next
        if freed:
            _rebalance_host_locked()
        host, disk = _HOST_BYTES, _DISK_BYTES
    if freed:
        _tier_gauges(host, disk)
        log.log("INFO", "spill", "headroom", reason=reason,
                need=int(need_bytes), freed=freed)
    flush_events()
    return freed


# ---------------------------------------------------------------------------
# repage: host/disk -> device, transparently on access
# ---------------------------------------------------------------------------


def _load_cols(entry: SpilledTable) -> list:
    if entry.cols is not None:
        return entry.cols
    # blocking disk read: the lockcheck shim reports any tracked lock
    # held across it (holding the registry lock here is deliberate —
    # the table must not be freeable mid-load — but it must be VISIBLE)
    lockcheck.note_blocking("spill_disk_read")
    path = entry._write.resolve() if entry._write is not None else entry.path
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        cols = []
        for i, (ti, sc) in enumerate(
            zip(meta["type_ids"], meta["scales"])
        ):
            cols.append((
                ti, sc, z[f"d{i}"],
                z[f"v{i}"] if f"v{i}" in z else None,
                z[f"l{i}"] if f"l{i}" in z else None,
            ))
    entry.names = meta["names"]
    entry.logical_rows = meta["logical_rows"]
    return cols


def repage_locked(tid: int):
    """Rebuild the device Table for a spilled id and swap it back into
    the registry. MUST run under the registry lock (every bridge access
    path holds it at the lookup); the caller flushes residency events
    after releasing the lock. Retries under the fault plane — the
    backing store is only released after a successful upload, so a
    transient (or injected) failure is always retryable."""
    global _DEVICE_BYTES, _HOST_BYTES, _DISK_BYTES
    entry = _REG_TABLES.get(int(tid))
    if not isinstance(entry, SpilledTable):
        return entry
    t0 = _time.perf_counter()

    def attempt():
        faults.inject("spill")
        return _upload(entry)

    with metrics.span("spill.repage"):
        table = faults.run_with_retry(attempt, "spill.in")
    _REG_TABLES[int(tid)] = table
    _TRACK[int(tid)] = entry.nbytes
    _DEVICE_BYTES += entry.nbytes
    if entry.state == HOST:
        _HOST_BYTES -= entry.host_nbytes
    else:
        _DISK_BYTES -= entry.host_nbytes
    entry.cols = None
    write, path = entry._write, entry.path
    entry._write = None
    _drop_backing(write, path)
    _LAST_TOUCH[int(tid)] = next(_CLOCK)
    dt_s = _time.perf_counter() - t0
    metrics.counter_add("spill.repages")
    metrics.bytes_add("spill.bytes_in", entry.nbytes)
    profiler.note_stall(dt_s)  # repage stalls show in the 4-way split
    if flight.enabled():
        flight.record("I", "spill.in", entry.nbytes)
    log.log("INFO", "spill", "repage", table_id=int(tid),
            bytes=entry.nbytes, tier=entry.state,
            stall_ms=round(dt_s * 1e3, 3))
    _queue_event("in", int(tid), entry.nbytes)
    _tier_gauges()
    return table


def _upload_cols(cols, names, logical_rows):
    """Batched upload of spill-format column tuples — the
    _upload_host_columns discipline: ONE jax.device_put over the flat
    leaf list, then rebuild Columns/Table around the device arrays.
    Shared by the repage path and the checkpoint restore path."""
    import jax

    from .. import dtype as dt
    from ..column import Column, Table

    leaves = []
    for _, _, data, validity, lengths in cols:
        leaves.append(data)
        if validity is not None:
            leaves.append(validity)
        if lengths is not None:
            leaves.append(lengths)
    dev = jax.device_put(leaves) if leaves else []
    it = iter(dev)
    out = []
    for ti, sc, data, validity, lengths in cols:
        d = next(it)
        if d.dtype != data.dtype:
            from ..column import x64_downgrade_error

            raise x64_downgrade_error(d.dtype, data.dtype, "types")
        v = next(it) if validity is not None else None
        lens = next(it) if lengths is not None else None
        out.append(
            Column(d, dt.DType(dt.TypeId(ti), sc), v, lens)
        )
    return Table(out, names, logical_rows)


def _upload(entry: SpilledTable):
    cols = _load_cols(entry)  # sets entry.names/logical_rows from meta
    return _upload_cols(cols, entry.names, entry.logical_rows)


# ---------------------------------------------------------------------------
# checkpoint serde: the durable serving plane (serving/durable.py) reuses
# the disk-tier .npz format (meta + d{i}/v{i}/l{i}) as its payload
# substrate, but with synchronous fsync'd writes and atomic rename —
# a checkpoint that exists must be complete
# ---------------------------------------------------------------------------


def save_table_npz(path: str, table) -> int:
    """Write a device Table's payload as a spill-format .npz at ``path``
    (tmp + fsync + atomic rename, synchronous). Returns the host byte
    size. The file is NOT registered in ``_FILES``: the caller owns its
    lifetime and the exit sweep must never touch checkpoints."""
    cols = []
    for c in table.columns:
        data = _host_copy(c.data)
        validity = None if c.validity is None else _host_copy(c.validity)
        lengths = None if c.lengths is None else _host_copy(c.lengths)
        cols.append(
            (int(c.dtype.id), int(c.dtype.scale), data, validity, lengths)
        )
    meta = {
        "type_ids": [c[0] for c in cols],
        "scales": [c[1] for c in cols],
        "names": None if table.names is None else list(table.names),
        "logical_rows": table.logical_rows,
    }
    arrays = {
        "meta": np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
    }
    nbytes = 0
    for i, (_, _, data, validity, lengths) in enumerate(cols):
        arrays[f"d{i}"] = data
        nbytes += data.nbytes
        if validity is not None:
            arrays[f"v{i}"] = validity
            nbytes += validity.nbytes
        if lengths is not None:
            arrays[f"l{i}"] = lengths
            nbytes += lengths.nbytes
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return nbytes


def load_table_npz(path: str):
    """Read a .npz written by ``save_table_npz`` (or the demote path)
    back into a device Table — the restore-time repage."""
    lockcheck.note_blocking("spill_disk_read")
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        cols = []
        for i, (ti, sc) in enumerate(
            zip(meta["type_ids"], meta["scales"])
        ):
            cols.append((
                ti, sc, z[f"d{i}"],
                z[f"v{i}"] if f"v{i}" in z else None,
                z[f"l{i}"] if f"l{i}" in z else None,
            ))
    return _upload_cols(cols, meta["names"], meta["logical_rows"])


# ---------------------------------------------------------------------------
# stats / reset / exit sweep
# ---------------------------------------------------------------------------


def _tier_gauges(host: Optional[int] = None,
                 disk: Optional[int] = None) -> None:
    host = _HOST_BYTES if host is None else host
    disk = _DISK_BYTES if disk is None else disk
    metrics.gauge_set("spill.host_bytes", host)
    metrics.gauge_set("spill.disk_bytes", disk)
    metrics.gauge_set("spill.host_bytes_hw", _HOST_HW)
    metrics.gauge_set("spill.disk_bytes_hw", _DISK_HW)
    if flight.enabled():
        flight.record("C", "spill.host_bytes", host)
        flight.record("C", "spill.disk_bytes", disk)


def stats_doc() -> dict:
    """Per-tier bytes + high-water marks (served by server.stats)."""
    with _EVENTS_LOCK:
        pending_events = len(_EVENTS)
    return {
        "enabled": enabled(),
        "device_bytes": int(_DEVICE_BYTES),
        "host_bytes": int(_HOST_BYTES),
        "disk_bytes": int(_DISK_BYTES),
        "host_bytes_hw": int(_HOST_HW),
        "disk_bytes_hw": int(_DISK_HW),
        "files": len(_FILES),
        "pending_events": pending_events,
    }


def spill_file_count() -> int:
    """Disk-tier files currently on disk (0 after clean teardown)."""
    return len(_FILES)


def _checkpoint_prefix() -> str:
    """Absolute checkpoint-dir prefix (trailing separator) the sweep
    must never cross. Spill scratch is process-scoped and swept at
    exit; checkpoints (SPARK_RAPIDS_TPU_CHECKPOINT_DIR, or the stable
    default under the system temp dir) exist precisely to outlive the
    process, so any path under this prefix is exempt even if it was
    (wrongly) registered for sweeping."""
    d = config.get_flag("CHECKPOINT_DIR") or os.path.join(
        tempfile.gettempdir(), "srt-checkpoint"
    )
    return os.path.abspath(d) + os.sep


def reset() -> None:
    """Test hook: drop all tracking and remove every spill file."""
    global _DEVICE_BYTES, _HOST_BYTES, _DISK_BYTES, _HOST_HW, _DISK_HW
    if _REG_LOCK is not None:
        with _REG_LOCK:
            _LAST_TOUCH.clear()
            _TRACK.clear()
            _PINS.clear()
            _DEVICE_BYTES = _HOST_BYTES = _DISK_BYTES = 0
            _HOST_HW = _DISK_HW = 0
    with _EVENTS_LOCK:
        _EVENTS.clear()
    keep = _checkpoint_prefix()
    for path in list(_FILES):
        if not os.path.abspath(path).startswith(keep):
            try:
                os.unlink(path)
            except OSError:
                pass
        _FILES.discard(path)


def _sweep_at_exit() -> None:  # pragma: no cover - atexit path
    """No orphaned spill files: remove anything this process wrote and
    the per-process default directory when it is left empty — except
    checkpoints, which must survive the process (the durable-serving
    restore depends on it)."""
    keep = _checkpoint_prefix()
    for path in list(_FILES):
        if os.path.abspath(path).startswith(keep):
            _FILES.discard(path)
            continue
        try:
            os.unlink(path)
        except OSError:
            pass
        _FILES.discard(path)
    default_dir = os.path.join(
        tempfile.gettempdir(), f"srt-spill-{os.getpid()}"
    )
    try:
        os.rmdir(default_dir)
    except OSError:
        pass


atexit.register(_sweep_at_exit)
flight.register_exit_section("spill", stats_doc)


def _on_hbm_pressure(deficit: int) -> None:
    """hbm plan listener: a shape that does not fit the budget is the
    planner telling us the device tier is about to blow — free the
    deficit before the launch instead of reacting to the OOM."""
    if enabled():
        request_headroom(deficit, reason="hbm_plan")


hbm.register_pressure_listener(_on_hbm_pressure)
