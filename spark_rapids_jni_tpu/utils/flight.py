"""Flight recorder — the crash-surviving telemetry ring for the dispatch plane.

The PR-1 metrics registry answers "how much / how long" but its data
dies with the process: five bench rounds ended as ``"device
unreachable"`` with no timeline of what the device was doing in the
seconds before the tunnel dropped. This module is the postmortem plane
— the black-box flight recorder of the reference stack's
NVTX-timeline-in-Nsight workflow:

* a **lock-cheap ring buffer** of the last N telemetry events (span
  begin/end, dispatch ops, wire transfers, compile-cache misses, probe
  retries, counter samples) with monotonic nanosecond timestamps and
  thread ids. Recording is a sequence fetch plus one list-slot store —
  no lock on the hot path (CPython guarantees both are atomic), so an
  event costs O(100ns) and the recorder can stay on under production
  traffic;
* a **dump plane**: ``SPARK_RAPIDS_TPU_FLIGHT_DUMP`` names a file the
  tail is written to at interpreter exit (atexit) and from the bench
  SIGTERM handler — the two windows a killed run still owns. The dump
  is the input of ``tools/trace2chrome.py`` / ``tracing.to_chrome_trace``
  which turn it into a chrome://tracing / Perfetto timeline;
* **exit sections**: subsystems register callables whose results ride
  along in the dump (``runtime_bridge`` contributes the resident-table
  leak report — the RMM-leak-report analog).

Gating follows the registry's ship-it-disabled discipline:
``SPARK_RAPIDS_TPU_FLIGHT`` truthy (or an integer ring capacity), or a
configured ``FLIGHT_DUMP`` path, turns the recorder on; the disabled
``record()`` costs one cached generation compare (~100ns, asserted in
tests/test_flight.py). ``bench.py`` forces it on the way it forces
METRICS on.

Event wire format (one tuple per slot, JSON-ified by ``tail_records``):

    (seq, t_ns, tid, ph, name, arg)

``ph`` is Chrome-trace-flavored: ``"B"``/``"E"`` span begin/end (name =
the qualified span path), ``"I"`` instant (op dispatched, cache miss,
probe retry; ``arg`` carries the payload), ``"C"`` counter sample
(``arg`` = the current value — ``resident.live``,
``bucket.pad_waste_bytes``).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import config

_HOSTNAME = socket.gethostname()

DEFAULT_CAPACITY = 8192
# pow2 ceiling on env-sized rings: a typo'd huge capacity must not
# allocate gigabytes of slots at the first record() call
MAX_CAPACITY = 1 << 22

_TRUTHY = frozenset({"1", "true", "yes", "on"})
_FALSY = frozenset({"", "0", "false", "no", "off", "none"})

# wall-clock anchor: perf_counter_ns is monotonic but epoch-less; the
# dump carries both so a postmortem can place the timeline in real time
_EPOCH_NS = time.time_ns()
_ANCHOR_NS = time.perf_counter_ns()

# ring state — (re)built under _SETUP_LOCK on config-generation change;
# the record() hot path reads the module globals without taking it.
# RLock: the bench SIGTERM handler dumps from the main thread and must
# not self-deadlock if the signal lands inside _refresh()
_SETUP_LOCK = threading.RLock()
_SLOTS: Optional[list] = None
_SEQ = itertools.count()
_GEN = -1
_WARNED_SPEC = False

_EXIT_SECTIONS: Dict[str, Callable[[], Any]] = {}

# (pid, host, session_id, ...) metadata stamped into every dump so a
# multi-process merge (tools/explain.py --merge) can tell the dumps
# apart; the profiler stamps the current session id through here
_PROCESS_META: Dict[str, Any] = {}


def set_process_meta(**kv) -> None:
    """Attach metadata keys to every future ``snapshot()``/``dump()``
    (``utils/profiler.py`` stamps ``session_id``); a None value removes
    the key."""
    for k, v in kv.items():
        if v is None:
            _PROCESS_META.pop(k, None)
        else:
            _PROCESS_META[k] = v


def _capacity_of(value) -> int:
    """Ring capacity implied by the FLIGHT flag value: 0 = disabled,
    truthy = DEFAULT_CAPACITY, an integer = that many slots (rounded up
    to a power of two, clamped to MAX_CAPACITY)."""
    global _WARNED_SPEC
    if value is None:
        return 0
    if isinstance(value, bool):
        return DEFAULT_CAPACITY if value else 0
    if isinstance(value, int):
        n = value
    else:
        s = str(value).strip().lower()
        if s in _FALSY:
            return 0
        if s in _TRUTHY:
            return DEFAULT_CAPACITY
        try:
            n = int(s)
        except ValueError:
            # the log.py invalid-LOG_LEVEL discipline: warn once and
            # fall back to the default capacity — the operator clearly
            # wanted the recorder ON, a typo must not silence the one
            # plane that explains the next crash
            if not _WARNED_SPEC:
                _WARNED_SPEC = True
                print(
                    f"[srt][flight][WARN] SPARK_RAPIDS_TPU_FLIGHT="
                    f"{value!r} is not on|off|<capacity>; using default "
                    f"capacity {DEFAULT_CAPACITY}",
                    file=sys.stderr,
                    flush=True,
                )
            return DEFAULT_CAPACITY
    if n <= 0:
        return 0
    n = min(n, MAX_CAPACITY)
    size = 1
    while size < n:
        size *= 2
    return size


def _refresh() -> None:
    global _SLOTS, _GEN
    with _SETUP_LOCK:
        cap = _capacity_of(config.get_flag("FLIGHT"))
        if cap == 0 and str(config.get_flag("FLIGHT_DUMP") or ""):
            # a configured dump path implies recording, the
            # METRICS_DUMP-implies-METRICS convention
            cap = DEFAULT_CAPACITY
        if cap == 0:
            _SLOTS = None
        elif _SLOTS is None or len(_SLOTS) != cap:
            _SLOTS = [None] * cap
        _GEN = config.generation()


def enabled() -> bool:
    """True when the recorder is collecting (cheap cached gate)."""
    if _GEN != config.generation():
        _refresh()
    return _SLOTS is not None


def capacity() -> int:
    """Current ring capacity in events (0 when disabled)."""
    if _GEN != config.generation():
        _refresh()
    return len(_SLOTS) if _SLOTS is not None else 0


def record(ph: str, name: str, arg=None, t_ns: Optional[int] = None) -> None:
    """Record one event. THE hot path: a generation compare when
    disabled; a sequence fetch + timestamp + one list-slot store when
    on. No lock — ``next()`` on ``itertools.count`` and a list index
    assignment are both atomic under the GIL, and each writer owns its
    slot outright (distinct seq => distinct slot modulo wraparound, and
    a wraparound race merely picks which of two complete events
    survives — torn events are impossible). The index mask is derived
    from the CAPTURED slots list (capacity is always a power of two),
    never from a second global — pairing the list with a separately
    published mask could index out of bounds across a concurrent
    resize.

    ``t_ns`` (perf_counter_ns timebase) backdates the event: the
    scheduler records a queue-wait span AFTER the wait is known, with
    the B stamped at submit time — both events land on the recording
    thread so the Chrome exporter's per-tid pairing still holds."""
    if _GEN != config.generation():
        _refresh()
    slots = _SLOTS
    if slots is None:
        return
    seq = next(_SEQ)
    slots[seq & (len(slots) - 1)] = (
        seq,
        time.perf_counter_ns() if t_ns is None else int(t_ns),
        threading.get_ident(),
        ph,
        name,
        arg,
    )


def events(limit: Optional[int] = None) -> List[tuple]:
    """The ring's surviving events, oldest -> newest (raw tuples).
    Sequence numbers are unique so the sort never compares payloads."""
    slots = _SLOTS
    if slots is None:
        return []
    got = sorted(e for e in slots if e is not None)
    if limit is not None and limit >= 0:
        got = got[len(got) - limit:] if limit < len(got) else got
    return got


def tail_records(limit: Optional[int] = None) -> List[dict]:
    """JSON-able view of the tail: the shape the flight dump, the bench
    ``flight_tail`` failure field, and the Chrome exporter all consume."""
    out = []
    for seq, t_ns, tid, ph, name, arg in events(limit):
        e = {"seq": seq, "t_ns": t_ns, "tid": tid, "ph": ph, "name": name}
        if arg is not None:
            e["arg"] = arg
        out.append(e)
    return out


def dropped() -> int:
    """Events lost to wraparound so far."""
    got = events()
    if not got:
        return 0
    return max(0, got[-1][0] + 1 - len(got))


def register_exit_section(name: str, fn: Callable[[], Any]) -> None:
    """Attach a named provider whose result is embedded in every dump
    (``runtime_bridge`` registers the resident-table leak report)."""
    _EXIT_SECTIONS[name] = fn


def snapshot(limit: Optional[int] = None) -> dict:
    """One JSON-able dict: the event tail + anchors + exit sections."""
    evs = tail_records(limit)
    doc = {
        "version": 1,
        "pid": os.getpid(),
        "host": _HOSTNAME,
        "capacity": capacity(),
        "dropped": dropped(),
        "epoch_ns": _EPOCH_NS,
        "anchor_perf_ns": _ANCHOR_NS,
        "events": evs,
    }
    for k, v in _PROCESS_META.items():
        doc.setdefault(k, v)
    sections = {}
    for name, fn in _EXIT_SECTIONS.items():
        try:
            sections[name] = fn()
        # srt: allow-broad-except(a broken exit-section provider must not eat the dump; its error is embedded instead)
        except Exception as e:
            sections[name] = {"error": f"{type(e).__name__}: {e}"}
    if sections:
        doc["sections"] = sections
    return doc


def reset() -> None:
    """Drop every recorded event and re-read the config (test isolation)."""
    global _SLOTS, _SEQ, _GEN
    with _SETUP_LOCK:
        _SLOTS = None
        _SEQ = itertools.count()
        _GEN = -1
        _PROCESS_META.clear()


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write the snapshot as JSON to ``path`` (default: the
    ``SPARK_RAPIDS_TPU_FLIGHT_DUMP`` flag). Returns the path written, or
    None when no path is configured. Failures WARN on stderr instead of
    raising — the metrics.dump() discipline: a broken dump path must not
    take the process down at exit (or inside a signal handler)."""
    path = path or str(config.get_flag("FLIGHT_DUMP") or "")
    if not path:
        return None
    try:
        with open(path, "w") as f:
            json.dump(snapshot(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path
    except OSError as e:
        print(
            f"[srt][flight][WARN] flight dump to {path!r} failed: {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess
    dump()


atexit.register(_dump_at_exit)
