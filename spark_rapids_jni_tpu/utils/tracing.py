"""Profiler range annotation — the NVTX analog (SURVEY.md §5.1).

The reference toggles NVTX ranges from Java via the
``ai.rapids.cudf.nvtx.enabled`` system property (pom.xml:85,200-201); the
ranges show up in Nsight. The TPU equivalent is
``jax.profiler.TraceAnnotation``, which lands named ranges in
Perfetto/XProf traces captured with ``jax.profiler.trace``.

Enabled via the ``SPARK_RAPIDS_TPU_TRACE`` flag (utils/config.py); when
off, ``trace_range`` is a no-op with near-zero overhead, matching the
reference's ship-it-disabled default.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Iterator, Optional

from . import config


def tracing_enabled() -> bool:
    return bool(config.get_flag("TRACE"))


@contextlib.contextmanager
def trace_range(name: str) -> Iterator[None]:
    """Named range in the profiler timeline (no-op unless TRACE is on)."""
    if not tracing_enabled():
        yield
        return
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


def annotate(name: Optional[str] = None):
    """Decorator form: wraps a function body in a trace_range."""

    def wrap(fn):
        import functools

        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with trace_range(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export of flight-recorder events
#
# The flight recorder (utils/flight.py) captures span begin/end,
# instants and counter samples with perf_counter_ns timestamps + thread
# ids; this converter turns that tail into the Chrome Trace Event JSON
# that chrome://tracing and https://ui.perfetto.dev load directly —
# the Nsight-timeline role for a postmortem that has no live profiler
# attached. Pure stdlib: usable from tools/trace2chrome.py on a dump
# file long after the process that wrote it died.
# ---------------------------------------------------------------------------


def _chrome_cat(name: str) -> str:
    """Category = the subsystem prefix of the LEAF span (dispatch,
    wire, bucketed, shuffle, distributed, resident, ...) so Perfetto
    can filter by plane. Span names are qualified paths
    ('dispatch.sort_by/bucketed.sort_by'): the leaf segment names the
    subsystem that actually ran, not the outermost wrapper."""
    leaf = name.rsplit("/", 1)[-1]
    return leaf.split(".", 1)[0] if "." in leaf else leaf


def to_chrome_trace(
    events,
    pid: int = 0,
    process_name: Optional[str] = None,
    process_sort_index: Optional[int] = None,
    t0_ns: Optional[int] = None,
) -> dict:
    """Flight-recorder event dicts -> a Chrome Trace Event JSON object.

    ``events`` is the ``tail_records()`` / flight-dump ``"events"``
    list. Span begin/end pairs are matched per thread into complete
    ``"X"`` events (ts/dur in microseconds), which keeps the file valid
    even when the ring's wraparound or a mid-span crash broke the
    pairing:

    * an ``E`` whose ``B`` fell off the ring becomes an ``X`` starting
      at the timeline origin with ``args.truncated_begin`` — the span
      was already running when the recorder's window opened;
    * a ``B`` that never saw its ``E`` (the SIGTERM/abort case — the
      exact spans the flight recorder exists to explain) becomes an
      ``X`` running to the end of the timeline with
      ``args.unterminated``.

    ``I`` events become instants (``ph:"i"``), ``C`` events become
    counter tracks (``ph:"C"``, one series per name). Thread-name
    metadata rows give each tid a stable label; ``process_name`` /
    ``process_sort_index`` label the process track (a multi-process
    merge passes "host:pid" per dump so timelines stop colliding on tid
    alone), and ``t0_ns`` pins the timeline origin so several dumps
    share one clock (``merge_chrome_traces``).
    """
    evs = sorted(events, key=lambda e: e.get("seq", 0))
    if not evs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e["t_ns"] for e in evs) if t0_ns is None else t0_ns
    t_end = max(e["t_ns"] for e in evs)

    def us(t_ns: int) -> float:
        return round((t_ns - t0) / 1e3, 3)

    out = []
    tids: list = []
    open_spans: dict = {}  # tid -> stack of B events
    for e in evs:
        tid = e["tid"]
        if tid not in open_spans:
            open_spans[tid] = []
            tids.append(tid)
        ph, name = e["ph"], e["name"]
        if ph == "B":
            open_spans[tid].append(e)
        elif ph == "E":
            stack = open_spans[tid]
            begin = None
            # match from the top down: a same-thread E always closes
            # the innermost open span with its name; mismatches (lost
            # B's) leave deeper frames alone
            for i in range(len(stack) - 1, -1, -1):
                if stack[i]["name"] == name:
                    begin = stack.pop(i)
                    break
            x = {
                "name": name,
                "cat": _chrome_cat(name),
                "ph": "X",
                "pid": pid,
                "tid": tid,
            }
            args = {}
            if e.get("arg") is not None:
                args["error"] = e["arg"]
            if begin is None:
                x["ts"] = us(t0)
                x["dur"] = us(e["t_ns"])
                args["truncated_begin"] = True
            else:
                x["ts"] = us(begin["t_ns"])
                x["dur"] = round((e["t_ns"] - begin["t_ns"]) / 1e3, 3)
            if args:
                x["args"] = args
            out.append(x)
        elif ph == "C":
            out.append({
                "name": name,
                "ph": "C",
                "pid": pid,
                "tid": tid,
                "ts": us(e["t_ns"]),
                "args": {"value": e.get("arg", 0)},
            })
        else:  # "I" and anything future-shaped degrades to an instant
            ev = {
                "name": name,
                "cat": _chrome_cat(name),
                "ph": "i",
                "s": "t",
                "pid": pid,
                "tid": tid,
                "ts": us(e["t_ns"]),
            }
            if e.get("arg") is not None:
                ev["args"] = {"arg": e["arg"]}
            out.append(ev)
    # crash case: spans still open at the end of the tail run to t_end
    for tid, stack in open_spans.items():
        for begin in stack:
            out.append({
                "name": begin["name"],
                "cat": _chrome_cat(begin["name"]),
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": us(begin["t_ns"]),
                "dur": round((t_end - begin["t_ns"]) / 1e3, 3),
                "args": {"unterminated": True},
            })
    meta = [{
        "name": "process_name",
        "ph": "M",
        "pid": pid,
        "tid": 0,
        "args": {"name": process_name or "spark-rapids-tpu"},
    }]
    if process_sort_index is not None:
        meta.append({
            "name": "process_sort_index",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"sort_index": int(process_sort_index)},
        })
    for i, tid in enumerate(tids):
        meta.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": f"thread-{i} ({tid})"},
        })
        meta.append({
            "name": "thread_sort_index",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"sort_index": i},
        })
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def merge_chrome_traces(dumps) -> dict:
    """Several flight dumps -> ONE Chrome/Perfetto trace with one
    process track per dump.

    Each dump's ``perf_counter_ns`` timestamps are epoch-less and
    process-local; the wall-clock anchors every dump carries
    (``epoch_ns`` + ``anchor_perf_ns``, utils/flight.py) shift each
    event to wall time, and the earliest event across ALL dumps becomes
    the shared origin — so two processes' timelines line up the way
    they actually overlapped. Per dump: its own ``pid`` (bumped on
    collision — two hosts can reuse a pid), a ``process_name`` of
    "host:pid" (plus the profiler session id when stamped), and a
    ``process_sort_index`` preserving input order."""
    prepped = []
    for d in dumps:
        evs = [
            e for e in (d.get("events") or [])
            if isinstance(e, dict) and "t_ns" in e
        ]
        if not evs:
            continue
        epoch = d.get("epoch_ns")
        anchor = d.get("anchor_perf_ns")
        shift = (epoch - anchor) if (
            epoch is not None and anchor is not None
        ) else 0
        evs = [dict(e, t_ns=e["t_ns"] + shift) for e in evs]
        prepped.append((d, evs))
    if not prepped:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    origin = min(e["t_ns"] for _, evs in prepped for e in evs)
    merged: list = []
    used_pids: set = set()
    for i, (d, evs) in enumerate(prepped):
        pid = int(d.get("pid") or (i + 1))
        while pid in used_pids:
            pid += 1
        used_pids.add(pid)
        name = f"{d.get('host', '?')}:{d.get('pid', pid)}"
        sid = d.get("session_id")
        if sid:
            name = f"{name} [{str(sid)[:8]}]"
        tr = to_chrome_trace(
            evs, pid=pid, process_name=name, process_sort_index=i,
            t0_ns=origin,
        )
        merged.extend(tr["traceEvents"])
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


@contextlib.contextmanager
def capture_trace(log_dir: str) -> Iterator[None]:
    """Capture a full profiler trace (Perfetto) into ``log_dir``.

    Creates ``log_dir`` if missing, and WARNs (ungated — a silent empty
    capture wasted a round-5 debugging session) when the capture leaves
    the directory empty, which usually means the profiler backend never
    attached (e.g. a tunnel drop mid-capture).
    """
    import jax.profiler

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
    if not any(files for _, _, files in os.walk(log_dir)):
        print(
            f"[srt][trace][WARN] capture_trace({log_dir!r}) produced no "
            "files — the profiler backend likely never attached; the "
            "capture is empty",
            file=sys.stderr,
            flush=True,
        )
