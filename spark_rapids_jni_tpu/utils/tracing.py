"""Profiler range annotation — the NVTX analog (SURVEY.md §5.1).

The reference toggles NVTX ranges from Java via the
``ai.rapids.cudf.nvtx.enabled`` system property (pom.xml:85,200-201); the
ranges show up in Nsight. The TPU equivalent is
``jax.profiler.TraceAnnotation``, which lands named ranges in
Perfetto/XProf traces captured with ``jax.profiler.trace``.

Enabled via the ``SPARK_RAPIDS_TPU_TRACE`` flag (utils/config.py); when
off, ``trace_range`` is a no-op with near-zero overhead, matching the
reference's ship-it-disabled default.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Iterator, Optional

from . import config


def tracing_enabled() -> bool:
    return bool(config.get_flag("TRACE"))


@contextlib.contextmanager
def trace_range(name: str) -> Iterator[None]:
    """Named range in the profiler timeline (no-op unless TRACE is on)."""
    if not tracing_enabled():
        yield
        return
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


def annotate(name: Optional[str] = None):
    """Decorator form: wraps a function body in a trace_range."""

    def wrap(fn):
        import functools

        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with trace_range(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


@contextlib.contextmanager
def capture_trace(log_dir: str) -> Iterator[None]:
    """Capture a full profiler trace (Perfetto) into ``log_dir``.

    Creates ``log_dir`` if missing, and WARNs (ungated — a silent empty
    capture wasted a round-5 debugging session) when the capture leaves
    the directory empty, which usually means the profiler backend never
    attached (e.g. a tunnel drop mid-capture).
    """
    import jax.profiler

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
    if not any(files for _, _, files in os.walk(log_dir)):
        print(
            f"[srt][trace][WARN] capture_trace({log_dir!r}) produced no "
            "files — the profiler backend likely never attached; the "
            "capture is empty",
            file=sys.stderr,
            flush=True,
        )
