"""Profiler ranges + the request trace-context plane — the NVTX analog.

The reference toggles NVTX ranges from Java via the
``ai.rapids.cudf.nvtx.enabled`` system property (pom.xml:85,200-201); the
ranges show up in Nsight. The TPU equivalent is
``jax.profiler.TraceAnnotation``, which lands named ranges in
Perfetto/XProf traces captured with ``jax.profiler.trace``.

Enabled via the ``SPARK_RAPIDS_TPU_TRACE`` flag (utils/config.py); when
off, ``trace_range`` is a no-op with near-zero overhead, matching the
reference's ship-it-disabled default.

On top of the ranges, this module owns the **trace context** (ISSUE 18
tentpole): a per-request ``trace_id``/``span_id`` pair held in a
``contextvars`` ambient context, carried across the serving wire as a
W3C-traceparent-style header, and stamped onto every span the metrics
plane records into the flight ring — the one join key the four
telemetry silos (metrics registry, flight ring, query profiler,
planstats store) previously lacked. Rules of the plane:

* the context is AMBIENT: ``activate(ctx)`` binds it on the current
  thread/task; plain function calls and same-thread retries (lineage
  replay, the mesh degradation ladder) inherit it for free — a replay
  must never mint a fresh trace;
* contexts do NOT flow into pool threads by themselves: the scheduler
  captures the submitter's context into the ticket and the pipeline
  captures it at ``Pending`` construction, re-activating around the
  work body;
* span records reuse the flight ring's lock-cheap event path — the
  traceparent rides as the ``arg`` of the span's ``"B"`` event, so the
  always-on cost stays at the ring's ~100ns/event and the disabled
  path at one cached gate check (``span_begin``/``span_end``, asserted
  within 2x of disabled ``flight.record()`` in tests);
* instants recorded by code that never heard of tracing
  (``mesh.replay``, ``shuffle.giveup``) are attributed after the fact
  by :func:`assign_trace_ids`: per thread, every event inside a
  trace-tagged span belongs to that span's trace.

The tail-sampled slow-request log (:func:`note_request` /
:func:`slow_requests`) backs the serving daemon's ``trace`` command:
top-K finished requests by duration, with full span detail kept only
for requests that breached ``SPARK_RAPIDS_TPU_TRACE_SLO_MS`` or ended
in a typed error. ``tools/tracequery.py`` merges per-process flight
dumps by trace id on top of :func:`assign_trace_ids`.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import itertools
import os
import re
import sys
import time
from typing import Iterator, List, Optional

from . import config
from . import flight
from . import lockcheck


def tracing_enabled() -> bool:
    return bool(config.get_flag("TRACE"))


@contextlib.contextmanager
def trace_range(name: str) -> Iterator[None]:
    """Named range in the profiler timeline (no-op unless TRACE is on)."""
    if not tracing_enabled():
        yield
        return
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


def annotate(name: Optional[str] = None):
    """Decorator form: wraps a function body in a trace_range."""

    def wrap(fn):
        import functools

        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with trace_range(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


# ---------------------------------------------------------------------------
# Trace context — per-request identity threaded through every layer
# ---------------------------------------------------------------------------


class TraceContext:
    """One request's identity: ``trace_id`` (32 hex chars, shared by
    every span of the request across threads and processes) plus
    ``span_id`` (16 hex chars, this hop). ``header`` is the precomputed
    W3C-traceparent wire form (``00-<trace_id>-<span_id>-01``) so the
    hot tagging path is an attribute read, not a format call."""

    __slots__ = ("trace_id", "span_id", "header")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.header = f"00-{trace_id}-{span_id}-01"

    def __repr__(self) -> str:
        return f"TraceContext({self.header})"


_CTX: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("srt_trace_ctx", default=None)
)

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def new_context(trace_id: Optional[str] = None) -> TraceContext:
    """Mint a context: a fresh trace when ``trace_id`` is None, else a
    new hop span under the given trace. THE id mint — srt-check SRT011
    flags serving handlers that hand-roll trace ids instead."""
    return TraceContext(trace_id or new_trace_id(), new_span_id())


def child_context(ctx: TraceContext) -> TraceContext:
    """A new hop under ``ctx``'s trace (the receiver side of a wire
    hop: same trace_id, fresh span_id)."""
    return new_context(ctx.trace_id)


def format_traceparent(ctx: TraceContext) -> str:
    """Wire encoding for hello/command headers (serving/frames.py)."""
    return ctx.header


def parse_traceparent(value) -> Optional[TraceContext]:
    """Wire header -> :class:`TraceContext`. Anything malformed (wrong
    field widths, non-hex, all-zero ids, the reserved ``ff`` version)
    degrades to None — a bad peer header must never fail the request
    it arrived on. Future versions with the same field shape are
    accepted, per the W3C forward-compatibility rule."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff" or set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id, span_id)


def current() -> Optional[TraceContext]:
    """The ambient context (None outside any traced request)."""
    return _CTX.get()


def current_traceparent() -> Optional[str]:
    """Wire/tag form of the ambient context — THE hot tagging path
    (one contextvar read + one attribute access), called once per span
    begin by metrics._Span."""
    ctx = _CTX.get()
    return None if ctx is None else ctx.header


def current_trace_id() -> Optional[str]:
    ctx = _CTX.get()
    return None if ctx is None else ctx.trace_id


class activate:
    """Bind ``ctx`` as the ambient trace context for the scope's
    duration (``None`` = no-op scope). Restores the previous binding on
    exit, exception path included. This is how captured contexts cross
    thread hops: scheduler workers and pipeline workers re-activate the
    submitter's context around each work item."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> Optional[TraceContext]:
        if self._ctx is not None:
            self._token = _CTX.set(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CTX.reset(self._token)
            self._token = None
        return False


# cached gate (the metrics._GATE_GEN discipline): the context plane is
# live when the flight ring records (trace spans are only observable
# through it) or the TRACE flag is on
_CTX_GEN = -1
_CTX_ON = False


def context_enabled() -> bool:
    """True when serving should mint/propagate trace contexts (cheap
    cached gate, invalidated by config.generation())."""
    global _CTX_GEN, _CTX_ON
    if _CTX_GEN != config.generation():
        _CTX_ON = bool(config.get_flag("TRACE")) or flight.enabled()
        _CTX_GEN = config.generation()
    return _CTX_ON


def ensure_context(traceparent=None) -> Optional[TraceContext]:
    """Server-side context establishment for ONE incoming request: a
    valid peer header joins that trace with a fresh hop span id (a
    retried or replayed request therefore keeps its original trace —
    replay must never mint a new one), no header mints a fresh context
    when the plane is on, and a disabled plane yields None."""
    ctx = parse_traceparent(traceparent)
    if ctx is not None:
        return child_context(ctx)
    if context_enabled():
        return new_context()
    return None


def span_begin(name: str):
    """Trace-layer span open: one trace-tagged ``"B"`` event on the
    flight ring (the traceparent rides as the event arg). Returns the
    token ``span_end`` closes; None when the ring is off — the
    disabled path is one cached gate check, the flight ``record()``
    cost class (asserted within 2x of disabled record() in tests).
    Callers below metrics in the import graph (profiler) use this
    pair; everything else gets the same tagging through
    ``metrics.span``."""
    if not flight.enabled():
        return None
    ctx = _CTX.get()
    flight.record("B", name, None if ctx is None else ctx.header)
    return name


def span_end(token, error: Optional[str] = None) -> None:
    """Close a :func:`span_begin` span (no-op on a None token)."""
    if token is not None:
        flight.record("E", token, error)


# ---------------------------------------------------------------------------
# tail-sampled slow-request log — the serving `trace` command's data
# ---------------------------------------------------------------------------

_SLOW_LOCK = lockcheck.make_lock("tracing.slow")
_SLOW: List[tuple] = []  # min-heap of (ms, seq, record)
_SLOW_SEQ = itertools.count()


def note_request(label: str, duration_ms: float, *,
                 trace_id: Optional[str] = None,
                 session: Optional[str] = None,
                 error: Optional[str] = None,
                 spans=None) -> None:
    """Feed one FINISHED request into the slow-request log: top-K by
    duration (``SPARK_RAPIDS_TPU_TRACE_TOPK``), tail-sampled — the
    ``spans`` detail is kept only when the request breached the SLO
    threshold (``SPARK_RAPIDS_TPU_TRACE_SLO_MS``) or ended in a typed
    error, so the always-on cost stays one cached gate plus a bounded
    heap push. ``spans`` may be a callable evaluated only when the
    record samples in (pulling span detail out of the flight tail is
    itself not free)."""
    if not context_enabled():
        return
    slo_ms = float(config.get_flag("TRACE_SLO_MS"))
    topk = int(config.get_flag("TRACE_TOPK"))
    ms = float(duration_ms)
    rec: dict = {"label": str(label), "ms": round(ms, 3),
                 "t_s": time.time()}
    if trace_id:
        rec["trace_id"] = trace_id
    if session:
        rec["session"] = session
    if error:
        rec["error"] = str(error)
    if error or ms >= slo_ms:
        detail = spans() if callable(spans) else spans
        if detail:
            rec["spans"] = detail
    with _SLOW_LOCK:
        heapq.heappush(_SLOW, (ms, next(_SLOW_SEQ), rec))
        while len(_SLOW) > topk:
            heapq.heappop(_SLOW)


def slow_requests() -> List[dict]:
    """The slow-request log, slowest first (bounded to TRACE_TOPK)."""
    with _SLOW_LOCK:
        items = sorted(_SLOW, key=lambda t: (t[0], t[1]), reverse=True)
    return [dict(rec) for _, _, rec in items]


def reset_requests() -> None:
    """Drop the slow-request log (test isolation; serving restarts)."""
    with _SLOW_LOCK:
        del _SLOW[:]


# ---------------------------------------------------------------------------
# trace attribution over flight events — the tracequery substrate
# ---------------------------------------------------------------------------


def assign_trace_ids(events) -> List[dict]:
    """Annotate flight-event dicts with the trace that owns them.

    Per thread, walked in seq order: a ``"B"`` whose arg parses as a
    traceparent opens a trace scope; every event recorded while a scope
    is open inherits the innermost scope's trace id — so instants
    emitted by code that never heard of tracing (``mesh.replay``,
    ``shuffle.giveup``, compile-cache misses) land in the right
    request. Returns copies with a ``trace_id`` key added where one
    applies; events outside any scope pass through untagged. Tolerates
    older/partial dumps (missing seq/tid/arg keys, non-dict rows)."""
    out: List[dict] = []
    stacks: dict = {}  # tid -> [(name, trace_id-or-None), ...]
    evs = [e for e in events if isinstance(e, dict)]
    for e in sorted(evs, key=lambda e: e.get("seq", 0)):
        tid = e.get("tid", 0)
        stack = stacks.setdefault(tid, [])
        ph, name = e.get("ph"), e.get("name", "?")
        e = dict(e)
        if ph == "B":
            ctx = parse_traceparent(e.get("arg"))
            trace = ctx.trace_id if ctx is not None else (
                stack[-1][1] if stack else None
            )
            stack.append((name, trace))
        elif ph == "E":
            trace = stack[-1][1] if stack else None
            # same top-down match as the Chrome exporter: an E closes
            # the innermost open span with its name
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == name:
                    trace = stack.pop(i)[1]
                    break
        else:
            trace = stack[-1][1] if stack else None
        if trace:
            e["trace_id"] = trace
        out.append(e)
    return out


def trace_span_records(events, trace_id: str) -> List[dict]:
    """Flattened span/instant records of ONE trace: the compact span
    detail the slow-request log samples and tests assert on. Begin/end
    pairs are matched per thread into ``{name, tid, t_ns, dur_ms}``
    records (plus ``error`` from the E arg); unmatched opens — the
    kill-mid-stage case — come back with ``unterminated: true``;
    instants keep their payload under ``arg``."""
    spans: List[dict] = []
    open_: dict = {}  # tid -> stack of B events
    for e in assign_trace_ids(events):
        if e.get("trace_id") != trace_id:
            continue
        ph = e.get("ph")
        tid = e.get("tid", 0)
        if ph == "B":
            open_.setdefault(tid, []).append(e)
        elif ph == "E":
            stack = open_.get(tid) or []
            begin = None
            for i in range(len(stack) - 1, -1, -1):
                if stack[i].get("name") == e.get("name"):
                    begin = stack.pop(i)
                    break
            rec: dict = {"name": e.get("name", "?"), "tid": tid}
            if begin is not None:
                rec["t_ns"] = begin.get("t_ns", 0)
                rec["dur_ms"] = round(
                    (e.get("t_ns", 0) - begin.get("t_ns", 0)) / 1e6, 3
                )
            if e.get("arg") is not None:
                rec["error"] = e["arg"]
            spans.append(rec)
        elif ph in ("I", "C"):
            rec = {"name": e.get("name", "?"), "tid": tid,
                   "t_ns": e.get("t_ns", 0), "instant": True}
            if e.get("arg") is not None:
                rec["arg"] = e["arg"]
            spans.append(rec)
    for tid, stack in open_.items():
        for b in stack:
            spans.append({
                "name": b.get("name", "?"), "tid": tid,
                "t_ns": b.get("t_ns", 0), "unterminated": True,
            })
    spans.sort(key=lambda r: r.get("t_ns", 0))
    return spans


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export of flight-recorder events
#
# The flight recorder (utils/flight.py) captures span begin/end,
# instants and counter samples with perf_counter_ns timestamps + thread
# ids; this converter turns that tail into the Chrome Trace Event JSON
# that chrome://tracing and https://ui.perfetto.dev load directly —
# the Nsight-timeline role for a postmortem that has no live profiler
# attached. Pure stdlib: usable from tools/trace2chrome.py on a dump
# file long after the process that wrote it died.
# ---------------------------------------------------------------------------


def _chrome_cat(name: str) -> str:
    """Category = the subsystem prefix of the LEAF span (dispatch,
    wire, bucketed, shuffle, distributed, resident, ...) so Perfetto
    can filter by plane. Span names are qualified paths
    ('dispatch.sort_by/bucketed.sort_by'): the leaf segment names the
    subsystem that actually ran, not the outermost wrapper."""
    leaf = name.rsplit("/", 1)[-1]
    return leaf.split(".", 1)[0] if "." in leaf else leaf


def to_chrome_trace(
    events,
    pid: int = 0,
    process_name: Optional[str] = None,
    process_sort_index: Optional[int] = None,
    t0_ns: Optional[int] = None,
) -> dict:
    """Flight-recorder event dicts -> a Chrome Trace Event JSON object.

    ``events`` is the ``tail_records()`` / flight-dump ``"events"``
    list. Span begin/end pairs are matched per thread into complete
    ``"X"`` events (ts/dur in microseconds), which keeps the file valid
    even when the ring's wraparound or a mid-span crash broke the
    pairing:

    * an ``E`` whose ``B`` fell off the ring becomes an ``X`` starting
      at the timeline origin with ``args.truncated_begin`` — the span
      was already running when the recorder's window opened;
    * a ``B`` that never saw its ``E`` (the SIGTERM/abort case — the
      exact spans the flight recorder exists to explain) becomes an
      ``X`` running to the end of the timeline with
      ``args.unterminated``.

    ``I`` events become instants (``ph:"i"``), ``C`` events become
    counter tracks (``ph:"C"``, one series per name). Thread-name
    metadata rows give each tid a stable label; ``process_name`` /
    ``process_sort_index`` label the process track (a multi-process
    merge passes "host:pid" per dump so timelines stop colliding on tid
    alone), and ``t0_ns`` pins the timeline origin so several dumps
    share one clock (``merge_chrome_traces``).
    """
    # tolerate older/partial flight formats: non-dict rows are dropped,
    # missing keys degrade (tid 0, t_ns 0, unknown ph -> instant)
    evs = sorted(
        (e for e in events if isinstance(e, dict)),
        key=lambda e: e.get("seq", 0),
    )
    if not evs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e.get("t_ns", 0) for e in evs) if t0_ns is None else t0_ns
    t_end = max(e.get("t_ns", 0) for e in evs)

    def us(t_ns: int) -> float:
        return round((t_ns - t0) / 1e3, 3)

    out = []
    tids: list = []
    open_spans: dict = {}  # tid -> stack of B events
    for e in evs:
        tid = e.get("tid", 0)
        if tid not in open_spans:
            open_spans[tid] = []
            tids.append(tid)
        ph, name = e.get("ph", "I"), e.get("name", "?")
        if "t_ns" not in e:
            e = dict(e, t_ns=t0)
        if ph == "B":
            open_spans[tid].append(e)
        elif ph == "E":
            stack = open_spans[tid]
            begin = None
            # match from the top down: a same-thread E always closes
            # the innermost open span with its name; mismatches (lost
            # B's) leave deeper frames alone
            for i in range(len(stack) - 1, -1, -1):
                if stack[i]["name"] == name:
                    begin = stack.pop(i)
                    break
            x = {
                "name": name,
                "cat": _chrome_cat(name),
                "ph": "X",
                "pid": pid,
                "tid": tid,
            }
            args = {}
            if e.get("arg") is not None:
                args["error"] = e["arg"]
            if begin is not None and begin.get("arg") is not None:
                # a trace-tagged span: the traceparent rode the B arg
                args["traceparent"] = begin["arg"]
            if begin is None:
                x["ts"] = us(t0)
                x["dur"] = us(e["t_ns"])
                args["truncated_begin"] = True
            else:
                x["ts"] = us(begin["t_ns"])
                x["dur"] = round((e["t_ns"] - begin["t_ns"]) / 1e3, 3)
            if args:
                x["args"] = args
            out.append(x)
        elif ph == "C":
            arg = e.get("arg", 0)
            if isinstance(arg, (int, float)):
                out.append({
                    "name": name,
                    "ph": "C",
                    "pid": pid,
                    "tid": tid,
                    "ts": us(e["t_ns"]),
                    "args": {"value": arg},
                })
            else:
                # a counter sample with a non-numeric payload would
                # break the Chrome counter track (and used to be
                # dropped silently): keep it visible as an instant
                # carrying the string form
                out.append({
                    "name": name,
                    "cat": _chrome_cat(name),
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": tid,
                    "ts": us(e["t_ns"]),
                    "args": {"arg": str(arg)},
                })
        else:  # "I" and anything future-shaped degrades to an instant
            ev = {
                "name": name,
                "cat": _chrome_cat(name),
                "ph": "i",
                "s": "t",
                "pid": pid,
                "tid": tid,
                "ts": us(e["t_ns"]),
            }
            if e.get("arg") is not None:
                ev["args"] = {"arg": e["arg"]}
            out.append(ev)
    # crash case: spans still open at the end of the tail run to t_end
    for tid, stack in open_spans.items():
        for begin in stack:
            args = {"unterminated": True}
            if begin.get("arg") is not None:
                args["traceparent"] = begin["arg"]
            out.append({
                "name": begin["name"],
                "cat": _chrome_cat(begin["name"]),
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": us(begin["t_ns"]),
                "dur": round((t_end - begin["t_ns"]) / 1e3, 3),
                "args": args,
            })
    meta = [{
        "name": "process_name",
        "ph": "M",
        "pid": pid,
        "tid": 0,
        "args": {"name": process_name or "spark-rapids-tpu"},
    }]
    if process_sort_index is not None:
        meta.append({
            "name": "process_sort_index",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"sort_index": int(process_sort_index)},
        })
    for i, tid in enumerate(tids):
        meta.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": f"thread-{i} ({tid})"},
        })
        meta.append({
            "name": "thread_sort_index",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"sort_index": i},
        })
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def merge_chrome_traces(dumps) -> dict:
    """Several flight dumps -> ONE Chrome/Perfetto trace with one
    process track per dump.

    Each dump's ``perf_counter_ns`` timestamps are epoch-less and
    process-local; the wall-clock anchors every dump carries
    (``epoch_ns`` + ``anchor_perf_ns``, utils/flight.py) shift each
    event to wall time, and the earliest event across ALL dumps becomes
    the shared origin — so two processes' timelines line up the way
    they actually overlapped. Per dump: its own ``pid`` (bumped on
    collision — two hosts can reuse a pid), a ``process_name`` of
    "host:pid" (plus the profiler session id when stamped), and a
    ``process_sort_index`` preserving input order."""
    prepped = []
    for d in dumps:
        evs = [
            e for e in (d.get("events") or [])
            if isinstance(e, dict) and "t_ns" in e
        ]
        if not evs:
            continue
        epoch = d.get("epoch_ns")
        anchor = d.get("anchor_perf_ns")
        shift = (epoch - anchor) if (
            epoch is not None and anchor is not None
        ) else 0
        evs = [dict(e, t_ns=e["t_ns"] + shift) for e in evs]
        prepped.append((d, evs))
    if not prepped:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    origin = min(e["t_ns"] for _, evs in prepped for e in evs)
    merged: list = []
    used_pids: set = set()
    for i, (d, evs) in enumerate(prepped):
        pid = int(d.get("pid") or (i + 1))
        while pid in used_pids:
            pid += 1
        used_pids.add(pid)
        name = f"{d.get('host', '?')}:{d.get('pid', pid)}"
        sid = d.get("session_id")
        if sid:
            name = f"{name} [{str(sid)[:8]}]"
        tr = to_chrome_trace(
            evs, pid=pid, process_name=name, process_sort_index=i,
            t0_ns=origin,
        )
        merged.extend(tr["traceEvents"])
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


@contextlib.contextmanager
def capture_trace(log_dir: str) -> Iterator[None]:
    """Capture a full profiler trace (Perfetto) into ``log_dir``.

    Creates ``log_dir`` if missing, and WARNs (ungated — a silent empty
    capture wasted a round-5 debugging session) when the capture leaves
    the directory empty, which usually means the profiler backend never
    attached (e.g. a tunnel drop mid-capture).
    """
    import jax.profiler

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
    if not any(files for _, _, files in os.walk(log_dir)):
        print(
            f"[srt][trace][WARN] capture_trace({log_dir!r}) produced no "
            "files — the profiler backend likely never attached; the "
            "capture is empty",
            file=sys.stderr,
            flush=True,
        )
