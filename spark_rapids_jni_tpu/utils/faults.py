"""Fault-tolerant execution plane: taxonomy, injection, retry, cancel.

The reference stack is a resident executor process that must survive
flaky devices, OOMs, and misbehaving tasks without dying or leaking
(PAPER.md §0: the JNI substrate a long-lived Spark executor loads).
This module is that survival kit for the TPU runtime, four planes in
one file so every dispatch boundary shares a single vocabulary:

* a **typed error taxonomy** — :class:`TransientDeviceError`,
  :class:`PermanentError`, :class:`ResourceExhausted`,
  :class:`Cancelled`, :class:`DeadlineExceeded`, plus the serving-only
  :class:`Degraded` shed state — with :func:`classify` mapping raw
  jax/XLA/runtime exceptions onto it by type and message markers (the
  same markers bench.py's ad-hoc unreachable heuristic used; the
  heuristic now routes through here).
* a **deterministic fault-injection harness** —
  ``SPARK_RAPIDS_TPU_FAULTS="[seed=N,]site:kind:prob[:count],..."``
  registers seeded fault rules against the named injection sites
  (:data:`SITES`: dispatch, compile, serde, hbm_admit, serve_accept).
  Decisions are a pure function of ``(seed, site, per-site call
  index)``, so a chaos plan replays identically run-to-run and tests
  can provoke every failure mode on CPU.
* **retry with exponential backoff + deterministic jitter** for
  transient-classified errors (:func:`run_with_retry`), metered through
  the metrics registry (``retry.attempts`` / ``retry.giveups`` /
  ``retry.backoff_ms``) and the flight recorder. Retry is at-most-once
  for donated work: callers gate on their consumed-input checks (the
  PR 5 doomed-replay rule) BEFORE entering the retry loop.
* **deadlines + cooperative cancellation** — :class:`CancelToken`
  carries an optional monotonic deadline; :func:`scoped_token` binds it
  to the calling thread and :func:`check_cancel` (called between plan
  segments and stream batches) raises the typed ``Cancelled`` /
  ``DeadlineExceeded`` at the next checkpoint.
* a **circuit breaker** (:class:`CircuitBreaker`) for the serving
  daemon: N consecutive transient failures flip it OPEN (requests shed
  with the typed ``Degraded``), a probe interval later one HALF_OPEN
  trial runs, and a trial success closes it again.

Gating follows the metrics/profiler discipline: the injection plan is
compiled once per ``config.generation()`` and every hot-path check
(:func:`inject`, :func:`check_cancel`) costs an int compare + attribute
read when the plane is idle — tests/test_faults.py asserts < 5 µs/op.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Optional

from . import config, flight, lockcheck, log, metrics

# ---------------------------------------------------------------------------
# typed error taxonomy
# ---------------------------------------------------------------------------


class FaultError(RuntimeError):
    """Base of the typed taxonomy; ``str(e)`` is the operator message."""


class TransientDeviceError(FaultError):
    """The device/tunnel hiccuped (UNAVAILABLE, reset, unreachable):
    the op is intact and a retry with backoff may succeed."""


class PermanentError(FaultError):
    """A deterministic failure (bad plan, unknown op, genuine bug):
    retrying burns chip time for the same answer. Unrecognized raw
    exceptions classify here and are surfaced UNCHANGED."""


class ResourceExhausted(FaultError):
    """HBM/allocation pressure: retrying at the same shape will fail
    the same way, but half-batch chunking or the exact path may fit."""


class Cancelled(FaultError):
    """The request's cancellation token fired (client gone, explicit
    cancel): stop at the next checkpoint and reclaim."""


class DeadlineExceeded(FaultError):
    """The request's deadline passed: same checkpoint contract as
    :class:`Cancelled`, distinct type so clients can tell them apart."""


class Degraded(FaultError):
    """The serving circuit breaker is OPEN: the daemon sheds requests
    with this typed state instead of burning them against a dead
    device. Answers immediately — a degraded daemon never hangs."""


# message markers for transient device/tunnel failures — the superset
# of bench.py's historical _UNREACHABLE_MARKERS (gRPC/absl capitalize
# freely, so matching is casefolded)
_TRANSIENT_MARKERS = (
    "unreachable", "unavailable", "deadline_exceeded",
    "failed to connect", "connection reset", "socket closed",
    "connection refused", "broken pipe", "device or resource busy",
)

# "timeout" covers bench's structured {type: "timeout"} per-arm
# records; "TimeoutExpired" stays for live subprocess exceptions and
# old failure records
_TRANSIENT_TYPES = (
    "DeviceUnreachable", "TimeoutExpired", "Unavailable", "timeout",
)

_OOM_MARKERS = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "out_of_memory", "allocation failure", "failed to allocate",
    "exceeds hbm budget",
)


def classify_text(type_name: str, message: str) -> type:
    """Map an exception's (type name, message) onto a taxonomy CLASS —
    the string form shared with bench.py, whose failure records carry
    text, not live exceptions. Unrecognized input is PermanentError:
    retrying an unknown failure is how retry storms start."""
    msg = f"{type_name} {message}".lower()
    if any(m in msg for m in _OOM_MARKERS):
        return ResourceExhausted
    if type_name in _TRANSIENT_TYPES or any(
        m in msg for m in _TRANSIENT_MARKERS
    ):
        return TransientDeviceError
    if "cancelled" in msg or "canceled" in msg:
        return Cancelled
    return PermanentError


def classify(exc: BaseException) -> type:
    """Taxonomy class for a raw exception (identity for exceptions
    already typed)."""
    if isinstance(exc, FaultError):
        return type(exc)
    return classify_text(type(exc).__name__, str(exc))


def retryable_class(cls: type) -> bool:
    """May a failure of this class be retried at all? Transient errors
    retry in place; ResourceExhausted retries via degradation (smaller
    chunks / exact path) — both are worth another attempt. Permanent /
    Cancelled / DeadlineExceeded / Degraded never retry."""
    return cls in (TransientDeviceError, ResourceExhausted)


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------

# the injection-site registry: every name a FAULTS plan may target.
# Each site is armed at exactly one choke point:
#   dispatch     runtime_bridge._dispatch + plan._run_fused (per-op and
#                fused-segment device launches)
#   compile      buckets.cached_jit (executable build, miss path)
#   serde        runtime_bridge._table_from_wire / _table_to_wire
#   hbm_admit    serving session.Session.admit (HBM budget admission)
#   serve_accept serving server._dispatch (per-command accept point)
#   spill        utils/spill.py eviction copy-out + repage upload
#   checkpoint   serving/durable.py journal append (torn-write
#                emulation), payload persist, and restore-time read
#   shuffle      parallel/shuffle.py host wrappers: every exchange
#                pack/all_to_all/unpack launch boundary
#   collective   parallel/distributed.py + parallel/planmesh.py: every
#                shard_map launch of a distributed op or mesh stage
#   mesh         parallel/mesh.py: mesh construction (make_mesh) and
#                the MeshHealth heartbeat probe
#   kernel       kernels/registry.py dispatch_kernel: the Pallas
#                kernel-tier launch boundary (a seeded fault here must
#                fall back to the bucketed/exact path byte-identically)
SITES = ("dispatch", "compile", "serde", "hbm_admit", "serve_accept",
         "spill", "checkpoint", "shuffle", "collective", "mesh",
         "kernel")

KINDS = ("transient", "oom", "permanent")

_KIND_ERRORS = {
    "transient": TransientDeviceError,
    "oom": ResourceExhausted,
    "permanent": PermanentError,
}


class _Rule:
    """One compiled ``site:kind:prob[:count]`` entry with its per-site
    deterministic decision stream and injection budget."""

    __slots__ = ("site", "kind", "prob", "count", "calls", "injected")

    def __init__(self, site: str, kind: str, prob: float, count: int):
        self.site = site
        self.kind = kind
        self.prob = prob
        self.count = count  # 0 = unlimited
        self.calls = 0
        self.injected = 0


class FaultPlan:
    """A compiled FAULTS spec: rules grouped by site + the seed. The
    per-rule decision for call index ``i`` hashes ``(seed, site, kind,
    i)`` — independent of thread interleaving across sites and of wall
    clock, so a seeded chaos run is replayable."""

    def __init__(self, seed: int, rules):
        self.seed = seed
        self._by_site = {}
        self._lock = lockcheck.make_lock("faults.plan")
        for r in rules:
            self._by_site.setdefault(r.site, []).append(r)

    def _decide(self, rule: _Rule, index: int) -> bool:
        if rule.prob >= 1.0:
            return True
        if rule.prob <= 0.0:
            return False
        h = hashlib.sha256(
            f"{self.seed}:{rule.site}:{rule.kind}:{index}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < rule.prob

    def fire(self, site: str) -> None:
        """Raise the first armed rule for ``site`` whose deterministic
        decision stream says "inject now"; no-op otherwise."""
        rules = self._by_site.get(site)
        if not rules:
            return
        hit: Optional[_Rule] = None
        with self._lock:
            for r in rules:
                i = r.calls
                r.calls += 1
                if r.count and r.injected >= r.count:
                    continue
                if self._decide(r, i):
                    r.injected += 1
                    hit = r
                    break
        if hit is None:
            return
        metrics.counter_add("faults.injected")
        metrics.counter_add(f"faults.injected.{site}.{hit.kind}")
        if flight.enabled():
            flight.record("I", "fault.injected", f"{site}:{hit.kind}")
        raise _KIND_ERRORS[hit.kind](
            f"injected {hit.kind} fault at site {site!r} "
            f"(call {hit.calls - 1}, injection {hit.injected}"
            f"{'/' + str(hit.count) if hit.count else ''}, "
            f"seed {self.seed})"
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                f"{r.site}:{r.kind}": {
                    "calls": r.calls, "injected": r.injected,
                }
                for rs in self._by_site.values() for r in rs
            }


def parse_spec(spec: str, _env="SPARK_RAPIDS_TPU_FAULTS") -> FaultPlan:
    """Compile ``[seed=N,]site:kind:prob[:count],...`` into a
    :class:`FaultPlan`; raises ValueError naming the env var on any
    grammar/vocabulary error (the loud-fail contract of config.py)."""
    seed = 0
    rules = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            try:
                seed = int(entry[len("seed="):])
            except ValueError:
                raise ValueError(
                    f"{_env}: bad seed in {entry!r} (want seed=<int>)"
                )
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"{_env}: entry {entry!r} must be "
                "site:kind:prob[:count]"
            )
        site, kind, prob_s = parts[0], parts[1], parts[2]
        if site not in SITES:
            raise ValueError(
                f"{_env}: unknown site {site!r} "
                f"(registered sites: {', '.join(SITES)})"
            )
        if kind not in KINDS:
            raise ValueError(
                f"{_env}: unknown kind {kind!r} "
                f"(kinds: {', '.join(KINDS)})"
            )
        try:
            prob = float(prob_s)
        except ValueError:
            raise ValueError(f"{_env}: bad probability in {entry!r}")
        if not (0.0 <= prob <= 1.0):
            raise ValueError(
                f"{_env}: probability must be in [0, 1], got {prob_s!r}"
            )
        count = 0
        if len(parts) == 4:
            try:
                count = int(parts[3])
            except ValueError:
                raise ValueError(f"{_env}: bad count in {entry!r}")
            if count < 0:
                raise ValueError(
                    f"{_env}: count must be >= 0, got {parts[3]!r}"
                )
        rules.append(_Rule(site, kind, prob, count))
    return FaultPlan(seed, rules)


# compiled plan cached against config.generation(): the disabled path
# (no FAULTS configured) costs one int compare + global read per
# inject() — the metrics._refresh_gate discipline
_PLAN: Optional[FaultPlan] = None
_PLAN_GEN = -1
_PLAN_LOCK = lockcheck.make_lock("faults.plan_cache")


def _plan() -> Optional[FaultPlan]:
    global _PLAN, _PLAN_GEN
    gen = config.generation()
    if _PLAN_GEN != gen:
        with _PLAN_LOCK:
            if _PLAN_GEN != gen:
                spec = str(config.get_flag("FAULTS") or "")
                _PLAN = parse_spec(spec) if spec.strip() else None
                _PLAN_GEN = gen
                if _PLAN is not None:
                    log.log(
                        "WARN", "faults", "fault_injection_armed",
                        spec=spec, seed=_PLAN.seed,
                    )
    return _PLAN


def active() -> bool:
    """Is a fault plan armed? (cached gate; see :func:`_plan`)."""
    return _plan() is not None


def inject(site: str) -> None:
    """The injection hook every registered site calls. One int compare
    when no plan is armed; with a plan, the site's rules decide
    deterministically whether to raise a typed fault here."""
    p = _plan()
    if p is not None:
        p.fire(site)


def injection_stats() -> dict:
    """Per-rule calls/injected counts of the armed plan ({} when off)."""
    p = _plan()
    return p.stats() if p is not None else {}


# ---------------------------------------------------------------------------
# retry with exponential backoff + deterministic jitter
# ---------------------------------------------------------------------------


def retry_max() -> int:
    return int(config.get_flag("RETRY_MAX"))


def backoff_ms(attempt: int, label: str = "", seed: int = 0) -> float:
    """Backoff for retry ``attempt`` (1-based): ``RETRY_BASE_MS *
    2^(attempt-1)``, jittered into [0.5x, 1.0x) by a hash of
    ``(seed, label, attempt)`` — decorrelated across call sites without
    wall-clock or global-RNG nondeterminism."""
    base = float(config.get_flag("RETRY_BASE_MS"))
    raw = base * (2.0 ** (max(int(attempt), 1) - 1))
    h = hashlib.sha256(f"{seed}:{label}:{attempt}".encode()).digest()
    frac = int.from_bytes(h[:8], "big") / 2.0 ** 64
    return raw * (0.5 + 0.5 * frac)


def sleep_backoff(attempt: int, label: str, error=None) -> float:
    """Meter one retry (``retry.attempts``, ``retry.backoff_ms``,
    flight instant, WARN log) and sleep its backoff — capped to the
    bound token's remaining deadline, which is re-checked first so an
    expired request never sleeps. Returns the ms slept."""
    check_cancel()
    ms = backoff_ms(attempt, label)
    tok = current_token()
    if tok is not None:
        rem = tok.remaining()
        if rem is not None:
            ms = min(ms, max(rem, 0.0) * 1e3)
    metrics.counter_add("retry.attempts")
    metrics.hist_observe(
        "retry.backoff_ms", ms, bounds=metrics.SPAN_MS_BOUNDS
    )
    if flight.enabled():
        flight.record("I", "retry", f"{label}:{attempt}")
    log.log(
        "WARN", "faults", "transient_retry", site=label,
        attempt=attempt, backoff_ms=round(ms, 2),
        error=(
            f"{type(error).__name__}: {str(error)[:200]}"
            if error is not None else None
        ),
    )
    if ms > 0:
        time.sleep(ms / 1e3)
    return ms


def run_with_retry(fn: Callable[[], object], label: str):
    """Run ``fn`` with transient-retry semantics at one boundary:

    * Cancelled / DeadlineExceeded / Degraded pass straight through
      (a cancelled request must stop, not persist).
    * PermanentError-classified raw exceptions surface UNCHANGED —
      genuine op errors (ValueError, KeyError, ...) keep their exact
      type and message (tests pin them).
    * Transient/OOM-classified failures retry up to RETRY_MAX with
      backoff; exhaustion raises the typed class chained to the last
      raw error (``retry.giveups``).

    Callers whose ``fn`` consumes its input (donation) must NOT route
    through here — at-most-once is their invariant (plan.run_plan gates
    on ``_input_consumed`` before retrying)."""
    attempt = 0
    while True:
        check_cancel()
        try:
            return fn()
        except (Cancelled, DeadlineExceeded, Degraded):
            raise
        except Exception as e:
            cls = classify(e)
            if not retryable_class(cls):
                raise
            if attempt >= retry_max():
                metrics.counter_add("retry.giveups")
                if isinstance(e, FaultError):
                    raise
                raise cls(
                    f"{label}: retries exhausted after {attempt} "
                    f"attempt(s): {type(e).__name__}: {str(e)[:200]}"
                ) from e
            attempt += 1
            sleep_backoff(attempt, label, error=e)


# ---------------------------------------------------------------------------
# deadlines + cooperative cancellation
# ---------------------------------------------------------------------------


class CancelToken:
    """Cooperative cancellation + optional deadline for one request.

    Checked between plan segments and stream batches
    (:func:`check_cancel`); holders call :meth:`cancel` to stop the
    work at its next checkpoint. ``clock`` is injectable for tests."""

    __slots__ = ("_cancelled", "_reason", "deadline", "_clock")

    def __init__(self, deadline_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._cancelled = False
        self._reason = ""
        self._clock = clock
        self.deadline = (
            clock() + float(deadline_s)
            if deadline_s is not None and deadline_s > 0 else None
        )

    def cancel(self, reason: str = "cancelled") -> None:
        self._cancelled = True
        self._reason = reason

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def remaining(self) -> Optional[float]:
        """Seconds until the deadline (None when none is set)."""
        if self.deadline is None:
            return None
        return self.deadline - self._clock()

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def check(self) -> None:
        """Raise the typed Cancelled/DeadlineExceeded when due."""
        if self._cancelled:
            metrics.counter_add("faults.cancelled")
            raise Cancelled(self._reason or "request cancelled")
        if self.expired():
            metrics.counter_add("faults.deadline_exceeded")
            raise DeadlineExceeded(
                "request deadline exceeded "
                f"({-self.remaining():.3f}s past)"
            )


_TLS = threading.local()


def current_token() -> Optional[CancelToken]:
    return getattr(_TLS, "token", None)


class scoped_token:
    """Bind ``token`` to the calling thread for the scope — every
    :func:`check_cancel` checkpoint under it observes the token.
    ``scoped_token(None)`` is a no-op scope (keeps call sites
    branch-free)."""

    __slots__ = ("_tok", "_prev")

    def __init__(self, token: Optional[CancelToken]):
        self._tok = token

    def __enter__(self):
        self._prev = getattr(_TLS, "token", None)
        if self._tok is not None:
            _TLS.token = self._tok
        return self._tok

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._tok is not None:
            _TLS.token = self._prev
        return False


def check_cancel() -> None:
    """The cooperative checkpoint: raises the bound token's typed
    Cancelled/DeadlineExceeded, no-op (one TLS read) when no token is
    bound — cheap enough for between-segment and between-batch use."""
    tok = getattr(_TLS, "token", None)
    if tok is not None:
        tok.check()


# ---------------------------------------------------------------------------
# circuit breaker (serving daemon)
# ---------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """N-consecutive-transient-failures circuit breaker.

    CLOSED counts consecutive transient-classified failures (other
    classes neither count nor reset — a bad_request burst must not mask
    a dying device, and must not trip the breaker either). At
    ``threshold`` it flips OPEN: :meth:`allow` sheds every request with
    the typed :class:`Degraded`. After ``probe_interval_s`` one caller
    is admitted as the HALF_OPEN trial (the serving daemon also runs a
    background probe so recovery does not wait for client traffic);
    trial success closes the breaker, trial failure re-opens it and
    re-arms the probe timer. State transitions are metered
    (``breaker.opened``/``breaker.closed``/``breaker.half_open``
    counters + flight instants — the smoke-chaos trace gate)."""

    def __init__(self, threshold: Optional[int] = None,
                 probe_interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "serving"):
        self.threshold = (
            int(config.get_flag("BREAKER_THRESHOLD"))
            if threshold is None else int(threshold)
        )
        self.probe_interval_s = (
            float(config.get_flag("BREAKER_PROBE_S"))
            if probe_interval_s is None else float(probe_interval_s)
        )
        self.name = name
        self._clock = clock
        self._lock = lockcheck.make_lock("faults.breaker")
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._opens = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _record(self, event: str) -> None:
        metrics.counter_add(f"breaker.{event}")
        if flight.enabled():
            flight.record("I", f"breaker.{event}", self.name)
        log.log("WARN", "faults", f"breaker_{event}",
                name=self.name, failures=self._failures)

    def allow(self) -> bool:
        """Admission check before serving a request. CLOSED: pass.
        OPEN: shed (typed Degraded) until the probe interval elapses,
        then admit ONE caller as the half-open trial (returns True for
        the trial so it can label itself). HALF_OPEN: shed everyone but
        the in-flight trial."""
        with self._lock:
            if self._state == CLOSED:
                return False
            now = self._clock()
            if (
                self._state == OPEN
                and now - self._opened_at >= self.probe_interval_s
            ):
                self._state = HALF_OPEN
                self._record("half_open")
                return True  # this caller IS the probe
            wait = max(
                self.probe_interval_s - (now - self._opened_at), 0.0
            )
            raise Degraded(
                f"{self.name} degraded: circuit breaker {self._state} "
                f"after {self._failures} consecutive transient "
                f"failure(s); next probe in {wait:.2f}s"
            )

    def note_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self._record("closed")

    def note_failure(self, exc: BaseException) -> bool:
        """Record a request failure; only transient-classified ones
        count toward the trip. Returns True when this failure opened
        (or re-opened) the breaker."""
        if classify(exc) is not TransientDeviceError:
            return False
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._failures >= self.threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self._opens += 1
                self._record("opened")
                return True
            if self._state == OPEN:
                # a straggler failing while open: re-arm the timer
                self._opened_at = self._clock()
        return False

    def to_doc(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "threshold": self.threshold,
                "probe_interval_s": self.probe_interval_s,
                "opens": self._opens,
            }


def default_probe() -> None:
    """The background half-open trial: one trivial device op through
    the serve_accept injection site — succeeds iff the device answers
    AND the armed fault plan lets it."""
    import jax.numpy as jnp

    inject("serve_accept")
    jnp.add(jnp.ones((8,), jnp.int32), 1).block_until_ready()


def note_error_class(exc: BaseException, where: str) -> None:
    """Meter one classified failure at a dispatch boundary
    (``faults.class.<Class>`` counters + flight instant) — the
    classifier's presence at boundaries that do not retry (pipeline
    workers, the serving command loop)."""
    if not (metrics.enabled() or flight.enabled()):
        return
    cls = classify(exc).__name__
    metrics.counter_add(f"faults.class.{cls}")
    if flight.enabled():
        flight.record("I", "fault.classified", f"{where}:{cls}")
