"""Shape buckets + the compiled-executable cache — the anti-recompile plane.

Spark executors stream thousands of ``ColumnarBatch``es with ONE schema
but ragged row counts. Row counts are static shape metadata here
(column.py), so under XLA every distinct batch size would recompile
every op in the chain — a recompile storm on the measured hot path
(round-5 put the winning groupby at 0.17% of HBM peak largely on
dispatch/compile overhead). The standard TPU serving fix is applied
centrally in this module:

* **Bucket policy** — a small geometric ladder of row-count buckets
  (default ×2 from a 1024 floor, capped at 2^23 rows), env-tunable via
  ``SPARK_RAPIDS_TPU_BUCKETS``. A ragged stream of N sizes maps onto
  O(log) buckets, so the op plane compiles O(#buckets) executables
  instead of O(N) — the compiled-shape analog of the reference's one
  central two-phase 2 GB batch splitter (row_conversion.cu:505-511).
* **Pad-to-bucket** — ``pad_table`` zero-pads every column buffer to the
  bucket and records the LOGICAL row count on the Table
  (``Table.logical_rows``); op semantics are preserved by validity-aware
  tail masking in the bucketed runners (``bucketed.py``): padded rows are
  dead for filters, sorts, groupbys, joins and distinct via the existing
  ``row_valid`` occupancy machinery of the capped ops.
* **Executable cache** — ``cached_jit`` keys a jitted callable on
  ``(op, schema signature, bucket)``; a hit means the XLA executable is
  reused outright. ``compile_cache.hit``/``compile_cache.miss`` counters,
  the ``bucket.pad_waste_bytes`` counter and per-bucket histograms feed
  the PR-1 metrics registry so ``tools/analyze_bench.py`` can report
  cache efficiency next to throughput.

Debugging: ``SPARK_RAPIDS_TPU_BUCKETS=off`` disables the whole plane —
every dispatch then runs the exact-shape path, which remains the
semantic reference (the bucketed runners fall back to it on any error).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from . import config
from . import flight
from . import lockcheck
from . import log
from . import metrics
from . import profiler
from . import tracing

# default ladder: 1024, 2048, ... 2^23 (8.4M rows). The cap keeps the
# fused join graphs the bucketed runners build below the TPU worker
# fault threshold (ops/join.py FUSED_PROBE_MAX_ROWS = 16M) and bounds
# pad waste on huge batches; sizes above it dispatch exact.
DEFAULT_FLOOR = 1024
DEFAULT_GROWTH = 2
DEFAULT_CAP = 1 << 23

_OFF_VALUES = frozenset({"off", "none", "false", "disabled", "0"})


@dataclasses.dataclass(frozen=True)
class BucketPolicy:
    enabled: bool
    floor: int = DEFAULT_FLOOR
    growth: int = DEFAULT_GROWTH
    cap: int = DEFAULT_CAP
    explicit: Optional[Tuple[int, ...]] = None

    def buckets_upto(self, n: int) -> Tuple[int, ...]:
        """Every bucket the ladder can produce for sizes <= n (test and
        introspection aid; the recompile-regression test sizes its
        compile budget with this)."""
        if not self.enabled:
            return ()
        if self.explicit is not None:
            return tuple(b for b in self.explicit if b <= max(n, self.explicit[0]))
        out = []
        b = self.floor
        while b <= self.cap:
            out.append(b)
            if b >= n:
                break
            b *= self.growth
        return tuple(out)


_OFF = BucketPolicy(enabled=False)


def _parse_spec(raw: str) -> BucketPolicy:
    got = raw.strip().lower()
    if not got:
        return BucketPolicy(enabled=True)
    if got in _OFF_VALUES:
        return _OFF
    try:
        if "," in got:
            sizes = sorted({int(p) for p in got.split(",") if p.strip()})
            if not sizes or sizes[0] <= 0:
                raise ValueError
            return BucketPolicy(
                enabled=True, floor=sizes[0], cap=sizes[-1],
                explicit=tuple(sizes),
            )
        parts = [int(p) for p in got.split(":")]
        if len(parts) == 1:
            floor, growth, cap = parts[0], DEFAULT_GROWTH, DEFAULT_CAP
        elif len(parts) == 2:
            floor, growth, cap = parts[0], parts[1], DEFAULT_CAP
        elif len(parts) == 3:
            floor, growth, cap = parts
        else:
            raise ValueError
        if floor <= 0 or growth < 2 or cap < floor:
            raise ValueError
        return BucketPolicy(enabled=True, floor=floor, growth=growth, cap=cap)
    except ValueError:
        # a typo'd bucket spec must fail loudly, not silently measure /
        # serve with the default ladder under the wrong label (the
        # GROUPBY_FORMULATION discipline)
        raise ValueError(
            f"SPARK_RAPIDS_TPU_BUCKETS must be 'floor:growth[:cap]', an "
            f"explicit 'a,b,c' list, or off|none|0 — got {raw!r}"
        ) from None


# policy cache, invalidated by config.generation() (the metrics-gate
# pattern: a dispatch-path check costs an int compare, not an environ
# read per call)
_POLICY: BucketPolicy = _OFF
_POLICY_GEN = -1
_POLICY_LOCK = lockcheck.make_lock("buckets.policy")


def policy() -> BucketPolicy:
    global _POLICY, _POLICY_GEN
    gen = config.generation()
    if _POLICY_GEN != gen:
        with _POLICY_LOCK:
            if _POLICY_GEN != gen:
                _POLICY = _parse_spec(str(config.get_flag("BUCKETS")))
                _POLICY_GEN = gen
    return _POLICY


def enabled() -> bool:
    """True when pad-to-bucket batching is on for the dispatch plane."""
    return policy().enabled


def bucket_for(n: int) -> Optional[int]:
    """Smallest bucket >= ``n``, or None when ``n`` has no bucket
    (bucketing disabled, empty input, or past the ladder cap — those
    dispatch on the exact-shape path)."""
    p = policy()
    if not p.enabled or n <= 0:
        return None
    if p.explicit is not None:
        for b in p.explicit:
            if b >= n:
                return b
        return None
    if n > p.cap:
        return None
    b = p.floor
    while b < n:
        b *= p.growth
    return b if b <= p.cap else None


# ---------------------------------------------------------------------------
# pad / unpad: the Table-level bucket transforms
# ---------------------------------------------------------------------------


def tail_valid(physical: int, n):
    """Row-occupancy mask for a padded buffer: True for the first ``n``
    of ``physical`` rows. ``n`` is a device scalar so one compiled
    executable serves every logical count within a bucket."""
    import jax.numpy as jnp

    return jnp.arange(physical, dtype=jnp.int32) < n


def pad_column(col, target: int):
    """Zero-pad one column's buffers to ``target`` rows (tail validity
    False, tail lengths 0)."""
    import jax.numpy as jnp

    from ..column import Column

    n = col.row_count
    if n == target:
        return col
    if n > target:
        raise ValueError(f"cannot pad {n} rows down to {target}")
    extra = target - n
    data = jnp.concatenate(
        [col.data, jnp.zeros((extra,) + col.data.shape[1:], col.data.dtype)]
    )
    validity = (
        None
        if col.validity is None
        else jnp.concatenate(
            [col.validity, jnp.zeros((extra,), col.validity.dtype)]
        )
    )
    lengths = (
        None
        if col.lengths is None
        else jnp.concatenate(
            [col.lengths, jnp.zeros((extra,), col.lengths.dtype)]
        )
    )
    return Column(data, col.dtype, validity, lengths)


# running pad-waste total for the flight counter track: kept locally so
# the track survives flight-only mode (metrics off => bytes_add no-ops)
# and isn't zeroed by the bench's per-config metrics.reset()
_PAD_WASTE_LOCK = lockcheck.make_lock("buckets.pad_waste")
_PAD_WASTE_TOTAL = 0


def _record_pad_metrics(table, target: int, logical: int) -> None:
    """Pad-waste accounting shared by the device-side ``pad_table`` and
    the host-side wire upload padding (runtime_bridge)."""
    global _PAD_WASTE_TOTAL
    if not (metrics.enabled() or flight.enabled()
            or profiler.session_active()):
        return
    from . import hbm

    extra = target - logical
    if extra > 0 and table.columns:
        # per-row bytes from the logical region (the padded buffers
        # would skew the denominator)
        per_row = -(-hbm.table_bytes(table) // max(table.row_count, 1))
        waste = extra * per_row
        metrics.bytes_add("bucket.pad_waste_bytes", waste)
        profiler.note_pad(extra, waste)
        if flight.enabled():
            # cumulative waste as a flight counter track: the Chrome
            # trace shows WHEN padding cost spiked, not just how much
            with _PAD_WASTE_LOCK:
                _PAD_WASTE_TOTAL += waste
                total = _PAD_WASTE_TOTAL
            flight.record("C", "bucket.pad_waste_bytes", total)
    metrics.counter_add("bucket.pad_tables")
    metrics.hist_observe("bucket.size", target)
    metrics.hist_observe("bucket.pad_rows", max(extra, 0))


def note_padded(table) -> None:
    """Record pad metrics for a table that was padded elsewhere (the
    wire path pads host-side before upload)."""
    if table.logical_rows is not None:
        _record_pad_metrics(table, table.row_count, table.logical_rows)


def pad_table(table, target: Optional[int] = None):
    """Pad every column to ``target`` rows (default: the table's bucket)
    and carry the logical row count on the result. Returns the input
    unchanged when no bucket applies."""
    from ..column import Table

    n = table.logical_row_count
    if target is None:
        target = bucket_for(n)
        if target is None:
            return table
    if table.logical_rows is not None and table.row_count >= target:
        # already padded to a bucket at or above the target (e.g. a
        # capped-filter output kept at its input's bucket): the
        # invariant physical >= bucket >= logical holds — pass through
        # instead of trying to pad DOWN
        return table
    _record_pad_metrics(table, target, n)
    return Table(
        [pad_column(c, target) for c in table.columns],
        table.names,
        logical_rows=n,
    )


def unpad_table(table):
    """Exact-shape view of a possibly padded table (device slice to the
    logical row count; identity for exact tables)."""
    from ..column import Column, Table

    lr = table.logical_rows
    if lr is None:
        return table
    if lr == table.row_count:
        return Table(table.columns, table.names)
    cols = [
        Column(
            c.data[:lr],
            c.dtype,
            None if c.validity is None else c.validity[:lr],
            None if c.lengths is None else c.lengths[:lr],
        )
        for c in table.columns
    ]
    return Table(cols, table.names)


def table_signature(table) -> tuple:
    """Cache-key signature of a table: per-column (type id, scale,
    matrix width, validity/lengths presence) plus names — everything
    that changes the traced program besides the bucketed row count."""
    cols = tuple(
        (
            int(c.dtype.id.value),
            int(c.dtype.scale),
            int(c.data.shape[1]) if c.data.ndim > 1 else 0,
            c.validity is not None,
            c.lengths is not None,
        )
        for c in table.columns
    )
    return (cols, table.names)


def cache_key(kind: str, payload, tables, extra: tuple = ()) -> tuple:
    """Canonical compiled-executable cache key: ``(kind, canonical
    payload JSON, per-table schema signatures, per-table physical row
    counts, extra)``. Shared by the per-op bucketed runners (payload =
    one op dict) and the plan compiler (payload = a fused segment's op
    LIST — the plan signature), so every cached executable is keyed the
    same way and each key sees exactly one input shape signature."""
    import json

    return (
        kind,
        json.dumps(payload, sort_keys=True),
        tuple(table_signature(t) for t in tables),
        tuple(t.row_count for t in tables),
        extra,
    )


# ---------------------------------------------------------------------------
# compiled-executable cache
# ---------------------------------------------------------------------------

# LRU of jitted callables keyed on (op, schema signature, bucket). Each
# key sees exactly ONE input shape signature by construction (buckets
# are part of the key), so a cache hit means the XLA executable is
# reused — hit/miss counters are honest compile counters.
CACHE_CAPACITY = 256

_CACHE_LOCK = lockcheck.make_lock("buckets.cache")
_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()


def cached_jit(
    key: tuple, build: Callable[[], Callable], name: str,
    donate_args: tuple = (),
):
    """Jitted callable for ``key``; ``build`` constructs the python fn
    on a miss. ``name`` becomes the callable's __name__ so compile-log
    lines (jax.log_compiles) are attributable to the bucket plane —
    the recompile-regression test greps for it.

    ``donate_args`` (jax ``donate_argnums``) marks positional arguments
    whose buffers the executable may consume IN PLACE — resident chains
    and fused plan segments pass their padded input table here when its
    table id is consumed, so an N-op chain updates HBM instead of
    doubling peak. Donation is part of the executable (XLA aliases
    output to input buffers), so it is folded into the cache key: a
    donated and a non-donated call of the same op compile separately
    and never serve each other. Callers must never reuse a donated
    argument's buffers after the call."""
    if donate_args:
        key = key + (("donate", tuple(donate_args)),)
    with _CACHE_LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _CACHE.move_to_end(key)
    if fn is not None:
        metrics.counter_add("compile_cache.hit")
        profiler.note_cache(True)
        return fn
    from . import faults

    faults.inject("compile")
    import jax

    raw = build()
    raw.__name__ = name
    raw.__qualname__ = name
    jfn = jax.jit(raw, donate_argnums=tuple(donate_args))
    with _CACHE_LOCK:
        cur = _CACHE.setdefault(key, jfn)
        won = cur is jfn
        if won:
            while len(_CACHE) > CACHE_CAPACITY:
                _CACHE.popitem(last=False)
        size = len(_CACHE)
    if won:
        metrics.counter_add("compile_cache.miss")
        profiler.note_cache(False)
        metrics.gauge_set("compile_cache.size", size)
        if flight.enabled():
            # a miss on the hot path means an XLA compile is coming —
            # the timeline explains the latency spike right after it
            flight.record("I", "compile_cache.miss", name)
        if log.enabled("DEBUG", "buckets"):
            log.log("DEBUG", "buckets", "compile_cache_miss", name=name,
                    size=size)
        if profiler.session_active() or tracing.context_enabled():
            # jax.jit compiles lazily at the FIRST call: hand this
            # caller (the miss winner — the launch about to pay the
            # compile) a transient wrapper that times that call and
            # attributes it as compile_s to the active segment. The
            # cache keeps the raw jfn, so steady state is untouched.
            # The wrapper also opens the trace-tagged `compile.jit`
            # span, so a traced request shows its compile wall even
            # without an active profile session.
            cur = profiler.time_first_call(cur, name)
    else:
        # another thread built the same key first; use theirs
        metrics.counter_add("compile_cache.hit")
        profiler.note_cache(True)
    return cur


def cache_stats() -> dict:
    with _CACHE_LOCK:
        return {"size": len(_CACHE), "capacity": CACHE_CAPACITY}


def cache_clear() -> None:
    """Drop every cached executable (test isolation)."""
    with _CACHE_LOCK:
        _CACHE.clear()
