"""Exact float64 ⇄ IEEE-754 bit-pattern codec in pure arithmetic.

Why this exists: TPU XLA emulates f64 arithmetic exactly (verified: 1+2^-52
round-trips) but its X64 legalizer cannot lower ``bitcast_convert`` involving
f64 (nor frexp/ldexp/signbit, which use bitcasts internally). The packed row
format (rows.py) needs the raw 8 bytes of each FLOAT64 value, so we compute
the bit pattern with operations the TPU does support: compares, gathers from
a constant power-of-two table, exact power-of-two multiplies/divides, and
u64 integer arithmetic (legalized to u32 pairs).

Exactness argument:
* The biased exponent comes from ``searchsorted`` over the 2^e table —
  pure comparisons, no rounding.
* ``|x| / 2^e`` for 2^e a representable power of two is exact (mantissa
  unchanged), giving m in [1,2); ``(m-1)*2^52`` is an exact <=52-bit
  integer, and f64→u64 value conversion is exact for it.
Contract (the envelope where this codec is used — compute-path decode/
encode; FLOAT64 *storage* is exact uint64 bits and never passes through
here, see DType.storage_dtype):
* Exact for normals, zeros and infinities.
* f64 subnormals flush to zero: XLA compiles with DAZ/FTZ, so arithmetic
  can never observe a subnormal payload on any backend — and on TPU the
  f64 emulation can't represent them anyway.
* NaNs are canonicalized (quiet bit, zero payload, positive sign) — a
  divergence from the reference's raw ``memcpy`` semantics
  (row_conversion.cu:217-254), observationally equivalent under Spark,
  which canonicalizes NaN itself.

``float_to_bits``/``bits_to_float`` dispatch to a plain bitcast on the CPU
backend (exact for everything, including subnormal payloads) and to this
arithmetic codec on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# 2^e for e in [-1022, 1023]: every normal binade boundary, exact in f64.
_EXPS = np.arange(-1022, 1024)
_POW2 = np.ldexp(1.0, _EXPS)  # shape (2046,)

_EXP_BIAS = 1023
_FRAC_BITS = 52
_QNAN_BITS = np.uint64(0x7FF8000000000000)
_TWO_P537 = np.ldexp(1.0, 537)
_TWO_M537 = np.ldexp(1.0, -537)


def f64_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """float64 array -> uint64 IEEE-754 bit patterns (exact; NaN canonical)."""
    absx = jnp.abs(x)
    # sign: x<0, or -0.0 (detected via 1/x = -inf). NaN -> canonical sign 0.
    neg_zero = (absx == 0) & (jnp.asarray(1.0, x.dtype) / x < 0)
    sign = jnp.where((x < 0) | neg_zero, jnp.uint64(1), jnp.uint64(0))

    table = jnp.asarray(_POW2)
    idx = jnp.searchsorted(table, absx, side="right") - 1  # -1 => subnormal
    # Explicit zero guard: on TPU the table's tiniest entries flush to zero
    # under the f64 emulation, which would misclassify absx == 0.
    is_zero = absx == 0
    is_sub = (idx < 0) | is_zero
    is_inf = jnp.isinf(absx)
    is_nan = jnp.isnan(x)

    safe_idx = jnp.clip(idx, 0, table.shape[0] - 1)
    binade = table[safe_idx]
    # normals: m in [1,2); frac = (m-1)*2^52 exact
    m = absx / binade
    frac_norm = ((m - 1.0) * jnp.asarray(np.ldexp(1.0, 52), x.dtype)).astype(
        jnp.uint64
    )
    biased_norm = (safe_idx + 1).astype(jnp.uint64)  # table[0]=2^-1022 -> biased 1

    # subnormals: frac = |x| * 2^1074, staged to stay finite
    frac_sub = ((absx * _TWO_P537) * _TWO_P537).astype(jnp.uint64)

    biased = jnp.where(is_sub, jnp.uint64(0), biased_norm)
    frac = jnp.where(is_zero, jnp.uint64(0), jnp.where(is_sub, frac_sub, frac_norm))
    bits = (
        (sign << 63)
        | (biased << _FRAC_BITS)
        | (frac & jnp.uint64((1 << 52) - 1))
    )
    bits = jnp.where(
        is_inf, (sign << 63) | jnp.uint64(0x7FF0000000000000), bits
    )
    bits = jnp.where(is_nan, jnp.uint64(_QNAN_BITS), bits)
    return bits


def bits_to_f64(bits: jnp.ndarray) -> jnp.ndarray:
    """uint64 IEEE-754 bit patterns -> float64 array (exact)."""
    bits = bits.astype(jnp.uint64)
    sign = (bits >> 63) != 0
    biased = ((bits >> _FRAC_BITS) & jnp.uint64(0x7FF)).astype(jnp.int32)
    frac = (bits & jnp.uint64((1 << 52) - 1)).astype(jnp.float64)

    table = jnp.asarray(_POW2)
    # normal: (1 + frac*2^-52) * 2^(biased-1023); biased-1023-(-1022) = biased-1
    safe_pow = table[jnp.clip(biased - 1, 0, table.shape[0] - 1)]
    m = 1.0 + frac * jnp.asarray(np.ldexp(1.0, -52))
    val_norm = m * safe_pow
    # subnormal: frac * 2^-1074, staged
    val_sub = (frac * _TWO_M537) * _TWO_M537

    is_special = biased == 0x7FF
    val = jnp.where(biased == 0, val_sub, val_norm)
    val = jnp.where(
        is_special,
        jnp.where(frac == 0, jnp.asarray(np.inf), jnp.asarray(np.nan)),
        val,
    )
    return jnp.where(sign, -val, val)


def float_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """f64 -> u64 bits; bitcast on CPU, arithmetic codec on TPU."""
    import jax

    if jax.default_backend() == "cpu":
        return jax.lax.bitcast_convert_type(x, jnp.uint64)
    return f64_to_bits(x)


def bits_to_float(bits: jnp.ndarray) -> jnp.ndarray:
    """u64 bits -> f64; bitcast on CPU, arithmetic codec on TPU."""
    import jax

    if jax.default_backend() == "cpu":
        return jax.lax.bitcast_convert_type(bits, jnp.float64)
    return bits_to_f64(bits)
