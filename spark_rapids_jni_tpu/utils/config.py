"""The flag plane: one place every runtime knob is declared.

Mirrors the reference's config system (SURVEY.md §5.6): Maven ``-D``
properties are the single source of truth with defaults in pom.xml:79-100,
fanned out to Ant/CMake/sysprops and documented in CONTRIBUTING.md:57-70.
Here the single plane is ``SPARK_RAPIDS_TPU_*`` environment variables with
defaults declared below; Java callers set the same knobs as system
properties which the JNI shim exports into the embedded runtime's
environment (native/ runtime).

Flags (reference analog in parens):

* ``TRACE``            — profiler range annotations on/off
                         (``ai.rapids.cudf.nvtx.enabled``, pom.xml:85,200).
* ``METRICS``          — op-level metrics registry (utils/metrics.py),
                         the per-operator ``GpuMetric`` counters analog.
* ``METRICS_DUMP``     — path to write the metrics snapshot JSON at
                         process exit; setting it implies ``METRICS``.
* ``REFCOUNT_DEBUG``   — buffer-registry leak tracking with provenance
                         (``ai.rapids.refcount.debug``, pom.xml:86,199).
* ``ALLOC_LOG_LEVEL``  — allocation logging verbosity
                         (``RMM_LOGGING_LEVEL``, pom.xml:82).
* ``DISABLE_X64``      — refuse 64-bit device types (debug aid; the x64
                         guard in column.py raises when data would narrow).
* ``TEST_PLATFORM``    — test-suite backend selection (cpu | axon/tpu);
                         the "GPU required" gate of ci/premerge-build.sh:20
                         inverted into an opt-in.
* ``NATIVE_LIB``       — explicit path to libspark_rapids_tpu.so
                         (NativeDepsLoader's resource-path override).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

_PREFIX = "SPARK_RAPIDS_TPU_"


def _as_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


def _parse_formulation(v: str) -> str:
    got = v.strip().lower()
    if got not in ("single", "packed", "chunked"):
        # a typo'd A/B arm must fail loudly, not silently measure
        # the default formulation under the wrong label
        raise ValueError(
            f"GROUPBY_FORMULATION must be single|packed|chunked, "
            f"got {v!r}"
        )
    return got


def _parse_kernels(v: str) -> str:
    got = v.strip().lower()
    if got not in ("on", "off", "auto"):
        # a typo'd A/B arm must fail loudly, not silently measure the
        # default routing under the wrong label (GROUPBY_FORMULATION
        # precedent)
        raise ValueError(
            f"KERNELS must be on|off|auto, got {v!r}"
        )
    return got


def _parse_port(v: str) -> int:
    try:
        got = int(v.strip())
    except ValueError:
        raise ValueError(f"SERVE_PORT must be an integer, got {v!r}")
    if not (0 <= got <= 65535):
        # a silently-clamped port would bind somewhere the operator
        # never asked for; refuse instead
        raise ValueError(f"SERVE_PORT must be in [0, 65535], got {v!r}")
    return got


def _parse_positive_int(name: str):
    def parse(v: str) -> int:
        try:
            got = int(v.strip())
        except ValueError:
            raise ValueError(f"{name} must be an integer, got {v!r}")
        if got <= 0:
            raise ValueError(f"{name} must be > 0, got {v!r}")
        return got

    return parse


def _parse_fraction(name: str):
    def parse(v: str) -> float:
        try:
            got = float(v.strip())
        except ValueError:
            raise ValueError(f"{name} must be a float, got {v!r}")
        if not (0.0 < got <= 1.0):
            # a fraction outside (0, 1] silently hands one tenant more
            # than the whole device (or nothing at all)
            raise ValueError(f"{name} must be in (0, 1], got {v!r}")
        return got

    return parse


def _parse_nonneg_int(name: str):
    def parse(v: str) -> int:
        try:
            got = int(v.strip())
        except ValueError:
            raise ValueError(f"{name} must be an integer, got {v!r}")
        if got < 0:
            raise ValueError(f"{name} must be >= 0, got {v!r}")
        return got

    return parse


def _parse_nonneg_float(name: str):
    def parse(v: str) -> float:
        try:
            got = float(v.strip())
        except ValueError:
            raise ValueError(f"{name} must be a float, got {v!r}")
        if got < 0.0:
            raise ValueError(f"{name} must be >= 0, got {v!r}")
        return got

    return parse


def _parse_positive_float(name: str):
    def parse(v: str) -> float:
        try:
            got = float(v.strip())
        except ValueError:
            raise ValueError(f"{name} must be a float, got {v!r}")
        if got <= 0.0:
            raise ValueError(f"{name} must be > 0, got {v!r}")
        return got

    return parse


def _parse_fault_spec(v: str) -> str:
    """Validate a SPARK_RAPIDS_TPU_FAULTS plan
    (``[seed=N,]site:kind:prob[:count],...``) at flag-read time so a
    typo'd chaos plan fails loudly instead of silently injecting
    nothing. The compiled (seeded) form lives in utils/faults.py; the
    site and kind vocabularies are declared there."""
    from . import faults

    spec = v.strip()
    if spec:
        faults.parse_spec(spec)  # raises ValueError naming the env var
    return spec


def _parse_checkpoint_dir(v: str) -> str:
    """Validate SPARK_RAPIDS_TPU_CHECKPOINT_DIR at flag-read time: a
    whitespace-only value or a path that exists but is not a directory
    is a deployment mistake that would silently disable durability, so
    fail loudly (the directory itself is created lazily on first
    checkpoint)."""
    if v and not v.strip():
        raise ValueError(
            "SPARK_RAPIDS_TPU_CHECKPOINT_DIR must be a directory path, "
            f"got whitespace {v!r}"
        )
    path = v.strip()
    if path and os.path.exists(path) and not os.path.isdir(path):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_CHECKPOINT_DIR={path!r} exists and is "
            "not a directory"
        )
    return path


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str
    default: Any
    parse: Callable[[str], Any]
    doc: str

    @property
    def env_var(self) -> str:
        return _PREFIX + self.name


_FLAGS = {
    f.name: f
    for f in [
        Flag("TRACE", False, _as_bool, "profiler trace annotations"),
        Flag(
            "METRICS", False, _as_bool,
            "op-level metrics registry + spans (utils/metrics.py): op "
            "counts, wire bytes, timers, resident-handle high-water",
        ),
        Flag(
            "METRICS_DUMP", "", str,
            "path to write metrics.snapshot() JSON at process exit "
            "(atexit); a non-empty path implies METRICS",
        ),
        Flag("REFCOUNT_DEBUG", False, _as_bool, "buffer leak tracking"),
        Flag(
            "LOG_LEVEL", "OFF", str.upper,
            "runtime observability level (OFF|ERROR|WARN|INFO|DEBUG|"
            "TRACE) for every utils/log.py channel",
        ),
        Flag(
            "ALLOC_LOG_LEVEL", "OFF", str.upper,
            "allocation log level; overrides LOG_LEVEL for the "
            "hbm/handles channels (RMM_LOGGING_LEVEL analog)",
        ),
        Flag("DISABLE_X64", False, _as_bool, "refuse 64-bit device types"),
        Flag("TEST_PLATFORM", "cpu", str, "test backend (cpu|axon)"),
        Flag("NATIVE_LIB", "", str, "explicit native library path"),
        Flag(
            "HBM_BUDGET_GB", 0.0, float,
            "per-chip HBM budget in GiB for the footprint planner "
            "(utils/hbm.py); 0 = backend default (v5e: 16)",
        ),
        Flag(
            "GROUPBY_FORMULATION", "single", _parse_formulation,
            "large-input eager groupby routing: single (one variadic "
            "sort - the round-5 on-chip winner) | packed | chunked "
            "(the two-level designs, kept for A/B)",
        ),
        Flag(
            "KERNELS", "auto", _parse_kernels,
            "Pallas kernel tier (kernels/registry.py): on = try every "
            "applicable hand-written kernel runner (interpret-mode off "
            "TPU, so tests/CI exercise the kernel code path on CPU) | "
            "off = never | auto (default) = only on a real TPU, where "
            "Mosaic compiles the kernels natively. Any kernel error or "
            "decline replays the op on the bucketed/exact path "
            "(metered kernel.fallbacks / kernel.declines) — the tier "
            "can change performance, never bytes",
        ),
        Flag(
            "FLIGHT", "", str,
            "flight recorder (utils/flight.py): off (default) | on = "
            "ring of 8192 events | an integer ring capacity. Records "
            "span begin/end, dispatch/wire/cache/retry events with "
            "monotonic timestamps + thread ids; ~100ns/event",
        ),
        Flag(
            "FLIGHT_DUMP", "", str,
            "path to write the flight-recorder tail JSON at process "
            "exit (atexit) and from the bench SIGTERM handler; a "
            "non-empty path implies FLIGHT",
        ),
        Flag(
            "BUCKETS", "", str,
            "shape-bucket spec for the dispatch plane (utils/buckets.py):"
            " '' = default geometric ladder (1024 x2 up to 8.4M rows), "
            "'floor:growth[:cap]', an explicit 'a,b,c' size list, or "
            "off|none|0 to disable pad-to-bucket batching",
        ),
        Flag(
            "PIPELINE", "", str,
            "pipelined dispatch plane (pipeline.py): off (default) = "
            "fully synchronous dispatch; an integer = pipeline depth "
            "(max batches in flight: wire serde on background workers "
            "overlapping device compute, resident ops enqueue and "
            "return ids immediately); on = default depth 2",
        ),
        Flag(
            "PROFILE", "", str,
            "query profiler (utils/profiler.py): on = auto-open a "
            "profile session around every table_plan_wire / "
            "table_plan_resident / table_stream_wire call, collecting "
            "per-segment compile/execute/serde/stall splits rendered "
            "by tools/explain.py; off (default) costs one cached "
            "generation compare per entry",
        ),
        Flag(
            "PROFILE_DUMP", "", str,
            "path to write finished profile sessions as JSON at "
            "process exit (atexit) and from the bench SIGTERM handler; "
            "a non-empty path implies PROFILE",
        ),
        Flag(
            "PLANSTATS", False, _as_bool,
            "plan-statistics store (utils/planstats.py): on = every "
            "profile session (and therefore every run_plan execution — "
            "PLANSTATS implies PROFILE-style auto-sessions and the "
            "metrics plane) appends one CRC-framed record keyed by "
            "plan fingerprint x schema x bucket, with per-segment "
            "observed times/rows/bytes, counter deltas, and drift "
            "findings vs plancheck's static predictions; off (default) "
            "costs one cached generation compare per dispatch",
        ),
        Flag(
            "PLANSTATS_DIR", "", str,
            "directory for plan-statistics store files "
            "(planstats-<host>-<pid>.wal); '' (default) = "
            "<tempdir>/srt-planstats. A non-empty path implies "
            "PLANSTATS. Files are NEVER swept at exit — history across "
            "processes is what the drift layer compares against",
        ),
        Flag(
            "PLANSTATS_ROTATE_MB", 64.0,
            _parse_positive_float("PLANSTATS_ROTATE_MB"),
            "per-process stats-store rotation threshold in MiB: past "
            "it the live WAL rotates to <name>.wal.1 (one old "
            "generation kept, older dropped) — bounded disk, "
            "crash-safe at every point",
        ),
        Flag(
            "DRIFT_ROWS_FACTOR", 4.0,
            _parse_positive_float("DRIFT_ROWS_FACTOR"),
            "cardinality drift threshold: a segment whose observed "
            "rows_out deviates from its history median by more than "
            "this factor (either direction) gets a typed drift "
            "finding and a drift.cardinality tick",
        ),
        Flag(
            "DRIFT_HBM_FACTOR", 2.0,
            _parse_positive_float("DRIFT_HBM_FACTOR"),
            "HBM drift threshold: a segment whose observed working-set "
            "proxy exceeds plancheck's static est_hbm_bytes by more "
            "than this factor gets a typed drift finding and a "
            "drift.hbm tick",
        ),
        Flag(
            "SKEW_SPLIT_FACTOR", 2.0,
            _parse_positive_float("SKEW_SPLIT_FACTOR"),
            "adaptive shuffle-skew threshold: after the two-phase "
            "counts pass, any destination whose planned recv rows "
            "exceed this factor x the mean gets its hot keys salted "
            "across sub-partitions (partial-agg before exchange, "
            "merge-agg after) so exchange capacity is sized from the "
            "post-split counts; disable the machinery wholesale with "
            "SKEW_SPLIT=0",
        ),
        Flag(
            "SKEW_SPLIT", True, _as_bool,
            "master switch for adaptive skew repartitioning on the "
            "mesh shuffle path; off = always size capacity from the "
            "raw per-destination counts (BENCH_r04 behaviour)",
        ),
        Flag(
            "SERVE_PORT", 0, _parse_port,
            "serving daemon (serving/server.py) localhost TCP port; "
            "0 (default) = OS-assigned ephemeral port, read back from "
            "Server.port",
        ),
        Flag(
            "SERVE_MAX_SESSIONS", 8,
            _parse_positive_int("SERVE_MAX_SESSIONS"),
            "serving daemon session-admission cap: a HELLO past this "
            "many live sessions gets a typed session_limit rejection",
        ),
        Flag(
            "SERVE_SESSION_HBM_FRACTION", 0.25,
            _parse_fraction("SERVE_SESSION_HBM_FRACTION"),
            "per-session HBM budget as a fraction of hbm.budget_bytes()"
            "; admission rejects (or queues behind in-flight work) any "
            "plan whose estimate exceeds the session's remainder",
        ),
        Flag(
            "SERVE_QUEUE_DEPTH", 16,
            _parse_positive_int("SERVE_QUEUE_DEPTH"),
            "serving daemon per-session scheduler queue depth; a "
            "request past it is shed with a typed BUSY response",
        ),
        Flag(
            "FAULTS", "", _parse_fault_spec,
            "deterministic fault-injection plan (utils/faults.py): "
            "'[seed=N,]site:kind:prob[:count],...' — site in "
            "dispatch|compile|serde|hbm_admit|serve_accept|spill|"
            "checkpoint|shuffle|collective|mesh, kind in "
            "transient|oom|permanent, prob in [0,1], count = max "
            "injections (0/absent = unlimited); '' (default) = off",
        ),
        Flag(
            "SPILL", False, _as_bool,
            "tiered memory hierarchy (utils/spill.py): on = resident "
            "tables gain a device|host|disk residency state with "
            "LRU-by-last-touch eviction under HBM pressure and "
            "transparent repage-on-access, so admission and OOM degrade "
            "to slower instead of shedding; off (default) costs one "
            "cached generation compare per registry access",
        ),
        Flag(
            "SPILL_DIR", "", str,
            "directory for disk-tier spill files (utils/spill.py); '' "
            "(default) = a per-process directory under the system temp "
            "dir; files this process wrote are swept at exit either way",
        ),
        Flag(
            "DURABLE", False, _as_bool,
            "durable serving plane (serving/durable.py): on = per-"
            "session write-ahead journal of namespace mutations with "
            "CRC-framed fsync'd records, table payloads checkpointed "
            "via the spill .npz serde, crash-safe restore + warm-start "
            "manifest replay before the listener accepts traffic; off "
            "(default) costs one cached generation compare per mutation",
        ),
        Flag(
            "CHECKPOINT_DIR", "", _parse_checkpoint_dir,
            "directory for durable serving checkpoints (journals, "
            "table payloads, warm-start manifest); '' (default) = "
            "<tempdir>/srt-checkpoint. Unlike SPILL_DIR this directory "
            "is NEVER swept at exit — checkpoints must survive the "
            "process to be worth writing",
        ),
        Flag(
            "HOST_SPILL_BUDGET_GB", 4.0,
            _parse_nonneg_float("HOST_SPILL_BUDGET_GB"),
            "host-RAM spill tier budget in GiB (utils/spill.py); past "
            "it the coldest host entries demote to the disk tier; 0 = "
            "skip the host tier and spill straight to disk",
        ),
        Flag(
            "RETRY_MAX", 3, _parse_nonneg_int("RETRY_MAX"),
            "max retries for a transient-classified failure at one "
            "dispatch/segment boundary (utils/faults.py); 0 disables "
            "retry, surfacing the typed error on the first failure",
        ),
        Flag(
            "RETRY_BASE_MS", 25.0,
            _parse_positive_float("RETRY_BASE_MS"),
            "base backoff for transient retries in milliseconds; "
            "attempt N sleeps ~base*2^(N-1) with deterministic jitter",
        ),
        Flag(
            "DEADLINE_DEFAULT_S", 0.0,
            _parse_nonneg_float("DEADLINE_DEFAULT_S"),
            "default per-request deadline in seconds for served "
            "requests whose hello/command frames carry none; 0 "
            "(default) = no deadline",
        ),
        Flag(
            "BREAKER_THRESHOLD", 5,
            _parse_positive_int("BREAKER_THRESHOLD"),
            "serving circuit breaker: consecutive transient-classified "
            "failures before the daemon flips to the typed Degraded "
            "shed state",
        ),
        Flag(
            "BREAKER_PROBE_S", 1.0,
            _parse_positive_float("BREAKER_PROBE_S"),
            "serving circuit breaker: seconds an OPEN breaker waits "
            "before letting one half-open probe through",
        ),
        Flag(
            "MESH_PROBE_S", 5.0,
            _parse_positive_float("MESH_PROBE_S"),
            "deadline in seconds for one MeshHealth heartbeat "
            "(parallel/mesh.py): an all-reduce that has not answered "
            "by then marks the probed mesh unhealthy and the "
            "degradation ladder drops to fewer devices",
        ),
        Flag(
            "TRACE_SLO_MS", 250.0,
            _parse_nonneg_float("TRACE_SLO_MS"),
            "slow-request SLO threshold in milliseconds for the trace "
            "plane's tail sampling (utils/tracing.py): a finished "
            "serving request at or over this duration — or one ending "
            "in a typed error — keeps its full span detail in the "
            "slow-request log; faster requests keep only the summary "
            "row. 0 keeps detail for every request",
        ),
        Flag(
            "TRACE_TOPK", 32,
            _parse_positive_int("TRACE_TOPK"),
            "slow-request log depth: the serving `trace` command "
            "returns the top-K finished requests by duration",
        ),
        Flag(
            "LOCKCHECK", False, _as_bool,
            "dynamic lock-order detector (utils/lockcheck.py): on = "
            "every tracked package lock records per-thread held sets "
            "and a global acquisition-order graph, reporting cycles "
            "(potential deadlocks), inversions of the sanctioned "
            "registry->session->scheduler->spill order, and locks held "
            "across device dispatch / blocking IO; off (default) costs "
            "one cached generation compare per acquisition",
        ),
    ]
}

# Test/runtime overrides set via set_flag (take precedence over env).
_overrides: dict = {}

# Monotonic counter bumped on every set_flag/clear_flag: the cache-
# invalidation key for hot-path gates (utils/metrics.py caches its
# enabled() verdict against it so a disabled instrumentation site costs
# an int compare, not an environ read per call). Environment-variable
# changes made mid-process after the first read are NOT observed by
# cached gates — set flags through this API (tests already must, since
# exported shell values are pinned per-process anyway).
_generation = 0


def generation() -> int:
    return _generation


def get_flag(name: str):
    """Current value of a declared flag (override > env > default)."""
    flag = _FLAGS[name]
    if name in _overrides:
        return _overrides[name]
    raw = os.environ.get(flag.env_var)
    if raw is None:
        return flag.default
    return flag.parse(raw)


def flag_is_set(name: str) -> bool:
    """True when the flag has an explicit value (override or env) as
    opposed to riding its declared default — for knobs where "set to
    the default value" and "unset" mean different things (e.g.
    ALLOC_LOG_LEVEL=OFF silences its channels; unset defers)."""
    flag = _FLAGS[name]
    return name in _overrides or flag.env_var in os.environ


def flag_default(name: str):
    """Declared default of a flag — the fallback target when an
    explicitly set value fails to parse (log.py's invalid-level path)."""
    return _FLAGS[name].default


def set_flag(name: str, value) -> None:
    global _generation
    if name not in _FLAGS:
        raise KeyError(f"unknown flag {name!r}")
    _overrides[name] = value
    _generation += 1


def clear_flag(name: str) -> None:
    global _generation
    _overrides.pop(name, None)
    _generation += 1


def describe_flags() -> str:
    """Human-readable flag table (the CONTRIBUTING.md:57-70 analog)."""
    lines = []
    for f in _FLAGS.values():
        lines.append(
            f"{f.env_var:<40} default={f.default!r:<10} {f.doc}"
        )
    return "\n".join(lines)
